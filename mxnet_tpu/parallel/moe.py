"""Expert parallelism: mixture-of-experts FFN with all-to-all dispatch.

New-framework extension (SURVEY.md §2.3 TP/PP/SP/EP row): the reference
predates MoE; this supplies the 'ep' leg of the parallelism menu the
TPU build treats as first-class. Design is the standard top-1
switch-style layer expressed for GSPMD:

- tokens arrive batch-sharded; each device holds ONE expert's weights
  (expert count == 'ep' axis size);
- a router picks an expert per token; tokens are packed into
  fixed-capacity per-expert buffers (static shapes — XLA-friendly;
  overflow tokens are dropped, the canonical switch behaviour);
- one ``all_to_all`` moves token buffers to their experts over ICI, the
  expert MLP runs locally, a second ``all_to_all`` brings results back,
  and the router probability scales the combined output.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .compat import shard_map

__all__ = ["moe_ffn", "PARTITION_RULES"]

# The layer's layout as a partition-rule set the engine can apply
# (``PartitionRules(PARTITION_RULES)``): the router is tiny and
# replicated; expert weight stacks carry a leading expert axis sharded
# over ``ep`` — one expert's MLP per device, exactly the placement
# ``moe_ffn`` commits by hand below. Exporting it graduates the kernel
# from a standalone demo to a layout any Module/InferenceEngine bind
# can consume (name your expert stacks ``*_expert_w1``/``*_expert_w2``
# and the rules light up).
PARTITION_RULES = [
    (r"router", P()),
    (r"expert_w[12]$", P("ep")),
    (r"expert", P("ep")),
]


def _local_moe(x, wr, w1, w2, axis_name, capacity):
    """Per-device body. x (T, E) local tokens; wr (n_exp, E) router;
    w1 (1, F, E), w2 (1, E, F): THIS device's expert (leading expert
    axis sharded to size 1 under shard_map)."""
    n = lax.psum(1, axis_name)
    T, E = x.shape
    f32 = jnp.float32

    logits = x.astype(f32) @ wr.T.astype(f32)            # (T, n)
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)                  # (T,)
    gate = jnp.take_along_axis(probs, expert[:, None], axis=-1)[:, 0]

    # position of each token within its expert's capacity buffer
    onehot = jax.nn.one_hot(expert, n, dtype=f32)        # (T, n)
    pos = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot    # (T, n)
    pos_in_exp = jnp.sum(pos, axis=-1).astype(jnp.int32)  # (T,)
    keep = pos_in_exp < capacity

    # scatter tokens into (n, capacity, E) dispatch buffers
    buf = jnp.zeros((n, capacity, E), x.dtype)
    idx_e = jnp.where(keep, expert, 0)
    idx_c = jnp.where(keep, pos_in_exp, 0)
    contrib = jnp.where(keep[:, None], x, 0.0)
    buf = buf.at[idx_e, idx_c].add(contrib)

    # exchange: device d receives every device's buffer for expert d
    recv = lax.all_to_all(buf, axis_name, split_axis=0, concat_axis=0,
                          tiled=True)                    # (n*cap, E)
    h = jnp.maximum(recv.astype(f32) @ w1[0].T, 0.0)
    y = (h @ w2[0].T).astype(x.dtype)                    # (n*cap, E)
    back = lax.all_to_all(y.reshape(n, capacity, E), axis_name,
                          split_axis=0, concat_axis=0, tiled=True) \
        .reshape(n, capacity, E)

    out = back[idx_e, idx_c]                             # (T, E)
    out = jnp.where(keep[:, None], out, 0.0)
    return out * gate[:, None].astype(x.dtype)


def moe_ffn(x, router_w, expert_w1, expert_w2, mesh, axis_name="ep",
            capacity_factor=1.25):
    """Top-1 MoE feed-forward over an expert-parallel mesh axis.

    x: (B, T, E) tokens, batch-sharded over ``axis_name`` (the standard
    setup where the data and expert meshes coincide for this layer);
    router_w (n_exp, E) replicated; expert_w1 (n_exp, F, E) /
    expert_w2 (n_exp, E, F) sharded over experts. n_exp must equal the
    'ep' axis size. Returns (B, T, E) with x's sharding. Dropped
    (over-capacity) tokens contribute zeros, the switch convention.
    """
    from ..ndarray.ndarray import NDArray, _wrap
    wrap = isinstance(x, NDArray)
    raw = [a._data if isinstance(a, NDArray) else a
           for a in (x, router_w, expert_w1, expert_w2)]
    xr, wr, w1, w2 = raw
    n = dict(zip(mesh.axis_names, mesh.devices.shape))[axis_name]
    if w1.shape[0] != n:
        raise ValueError("expert count %d != %r axis size %d"
                         % (w1.shape[0], axis_name, n))
    B, T, E = xr.shape
    if B % n:
        raise ValueError("batch %d must divide by %r axis size %d"
                         % (B, axis_name, n))
    flat = xr.reshape(B * T, E)
    local_tokens = (B * T) // n
    capacity = max(1, int(capacity_factor * local_tokens / n))

    xs = P(axis_name)
    flat = jax.device_put(flat, NamedSharding(mesh, xs))
    wr = jax.device_put(wr, NamedSharding(mesh, P()))
    w1 = jax.device_put(w1, NamedSharding(mesh, P(axis_name)))
    w2 = jax.device_put(w2, NamedSharding(mesh, P(axis_name)))

    fn = shard_map(
        functools.partial(_local_moe, axis_name=axis_name,
                          capacity=capacity),
        mesh=mesh, in_specs=(xs, P(), P(axis_name), P(axis_name)),
        out_specs=xs)
    out = fn(flat, wr, w1, w2).reshape(B, T, E)
    return _wrap(out) if wrap else out
