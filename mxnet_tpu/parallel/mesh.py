"""Device-mesh helpers.

The mesh is the TPU-native analogue of the reference's device group /
kvstore topology: within a slice the axes ride ICI, across slices DCN
(jax handles the distinction; lay out the fastest-varying axis on ICI).
"""
from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

from ..base import MXNetError

__all__ = ["make_mesh", "default_mesh", "mesh_from_contexts", "barrier"]


def make_mesh(axes, devices=None):
    """Create a Mesh from {axis_name: size}. Sizes may include one -1 to
    absorb remaining devices (like reshape)."""
    devices = list(devices if devices is not None else jax.devices())
    names = list(axes.keys())
    sizes = list(axes.values())
    if sizes.count(-1) > 1:
        raise MXNetError("at most one axis may be -1")
    known = int(np.prod([s for s in sizes if s != -1]))
    if -1 in sizes:
        if len(devices) % known != 0:
            raise MXNetError("device count %d not divisible by %d"
                             % (len(devices), known))
        sizes[sizes.index(-1)] = len(devices) // known
    total = int(np.prod(sizes))
    if total > len(devices):
        raise MXNetError("mesh needs %d devices, have %d"
                         % (total, len(devices)))
    arr = np.array(devices[:total]).reshape(sizes)
    return Mesh(arr, tuple(names))


def default_mesh(data_axis="dp"):
    """All visible devices on one data-parallel axis."""
    return make_mesh({data_axis: -1})


def mesh_from_contexts(contexts, axis="dp", axes=None):
    """Mesh over a Module-style context list — the TPU-native reading
    of the reference's per-GPU context list (the devices that
    DataParallelExecutorGroup would have bound one executor each on
    become the axes of ONE program's mesh).

    Default: a one-axis ``(axis,)`` data-parallel mesh. ``axes`` (an
    ordered ``{name: size}``, one size may be -1 to absorb the rest)
    folds the SAME context list into a multi-axis form — e.g.
    ``{"dp": 2, "mp": 4}`` lays 8 contexts out as a 2x4 dp x mp mesh
    for the partition-rule engine. The product must cover the context
    list exactly: the caller named these devices, so silently dropping
    some would train on fewer chips than asked."""
    devs = [c.jax_device() for c in contexts]
    if len(set(devs)) != len(devs):
        raise MXNetError("duplicate devices in context list %s"
                         % (list(contexts),))
    if axes is None:
        return Mesh(np.array(devs), (axis,))
    names = list(axes.keys())
    sizes = [int(s) for s in axes.values()]
    if sizes.count(-1) > 1:
        raise MXNetError("at most one mesh axis may be -1")
    known = int(np.prod([s for s in sizes if s != -1]))
    if -1 in sizes:
        if known == 0 or len(devs) % known != 0:
            raise MXNetError("context count %d not divisible by the "
                             "fixed axes product %d" % (len(devs), known))
        sizes[sizes.index(-1)] = len(devs) // known
    if int(np.prod(sizes)) != len(devs):
        raise MXNetError(
            "mesh axes %s need %d devices, context list has %d"
            % (dict(zip(names, sizes)), int(np.prod(sizes)), len(devs)))
    return Mesh(np.array(devs).reshape(sizes), tuple(names))


def barrier():
    """Cross-device sync: a tiny psum everyone must join (the portable
    replacement for ps::Postoffice::Barrier)."""
    n = len(jax.devices())
    if n <= 1:
        return
    import jax.numpy as jnp
    x = jnp.ones((n,))
    jax.block_until_ready(jnp.sum(x))
