"""Partition-rule sharding engine: ONE declarative spec for dp x mp
meshes, shared by training and serving.

``DataParallelSpec`` hardcoded "batch over dp, params replicated" —
models that exceed one chip's HBM had no path. This module generalises
it into a rule tree: an ordered list of ``(regex, PartitionSpec)``
pairs matched against parameter PATH NAMES, first match wins, with an
explicit UNMATCHED policy (replicate or error). The compiler consumes
the result — per-parameter ``NamedSharding``s committed on bound
storage and threaded into ``jax.jit in_shardings`` — instead of every
call site plumbing its own layout (the whole-program XLA-partitioning
stance of Julia-to-TPU arXiv 1810.09868 / TPU-MLIR arXiv 2210.15016;
the rule-matching shape follows the ``match_partition_rules`` exemplar,
SNIPPETS.md [3]).

::

    rules = PartitionRules([
        (r"fc\\d+_weight$", P("mp", None)),   # row-shard linear weights
        (r"fc\\d+_bias$",   P("mp")),
        # everything else: the UNMATCHED policy (default: replicate)
    ])
    mod = mx.mod.Module(sym, context=[mx.cpu(i) for i in range(8)],
                        partition_rules=rules,
                        mesh_axes={"dp": 2, "mp": 4})

Semantics:

* **first match wins** — rules are tried in order with ``re.search``;
  order encodes specificity exactly like a routing table.
* **scalars never shard** — a 0-d or one-element leaf always gets
  ``P()`` (the exemplar convention), before any rule is consulted.
* **UNMATCHED policy** — ``unmatched="replicate"`` (default) maps
  unmatched names to ``P()``; ``unmatched="error"`` raises, so a
  layout meant to be exhaustive fails loudly at bind time instead of
  silently replicating a tensor that does not fit.
* **divisibility downgrade** — a MATCHED spec whose sharded dim does
  not divide by the mesh axis (or names an axis the mesh lacks)
  downgrades to replicate with a once-per-parameter warning and a
  ``partition.replicated_fallback`` counter: broad rules over a zoo of
  shapes must not crash the bind, but the downgrade is never silent.
"""
from __future__ import annotations

import re
import threading

import numpy as np

from jax.sharding import NamedSharding, PartitionSpec as P

from ..base import MXNetError
from .. import telemetry

__all__ = ["PartitionRules", "UNMATCHED_REPLICATE", "UNMATCHED_ERROR",
           "spec_nbytes", "committed_nbytes", "partition_summary"]

UNMATCHED_REPLICATE = "replicate"
UNMATCHED_ERROR = "error"

# once-per-(param, cause) divisibility-downgrade warnings already sent
# through log.py
_DOWNGRADE_WARNED = set()          # guarded by: _downgrade_lock
_downgrade_lock = threading.Lock()


def _as_pspec(spec):
    """Normalise one rule's right-hand side to a PartitionSpec."""
    if spec is None:
        return P()
    if isinstance(spec, P):
        return spec
    if isinstance(spec, (tuple, list)):
        return P(*spec)
    if isinstance(spec, str):
        return P(spec)
    raise MXNetError("partition rule spec must be a PartitionSpec, "
                     "axis name, or tuple of axis names, got %r" % (spec,))


class PartitionRules:
    """Ordered ``(pattern, PartitionSpec)`` rule tree.

    ``spec_for(name, shape)`` resolves one parameter; ``apply(params)``
    maps a whole ``{name: array_or_shape}`` tree. Hashable (rides in
    the executor's jit-cache key: two Modules sharing a rule set share
    one compiled SPMD step) and JSON-summarisable (``describe()`` —
    what checkpoint meta and program cards record).
    """

    __slots__ = ("rules", "unmatched", "_compiled", "_cache", "_lock")

    def __init__(self, rules, unmatched=UNMATCHED_REPLICATE):
        if unmatched not in (UNMATCHED_REPLICATE, UNMATCHED_ERROR):
            raise MXNetError("unmatched policy must be %r or %r, got %r"
                             % (UNMATCHED_REPLICATE, UNMATCHED_ERROR,
                                unmatched))
        norm = []
        for entry in rules:
            try:
                pattern, spec = entry
            except (TypeError, ValueError):
                raise MXNetError("each rule must be a (pattern, spec) "
                                 "pair, got %r" % (entry,))
            norm.append((str(pattern), _as_pspec(spec)))
        self.rules = tuple(norm)
        self.unmatched = unmatched
        self._compiled = tuple(re.compile(p) for p, _ in self.rules)
        # resolved (name, shape) -> PartitionSpec memo: regex scans are
        # cheap but the fused plan re-resolves every parameter on each
        # rebuild, and bind paths run from multiple threads (serving
        # warmup vs coalescer dispatch share an engine's rule set)
        self._cache = {}                 # guarded by: self._lock
        self._lock = threading.Lock()

    # -- identity ----------------------------------------------------------
    def _key(self):
        return (self.rules, self.unmatched)

    def __hash__(self):
        return hash(self._key())

    def __eq__(self, other):
        return isinstance(other, PartitionRules) \
            and self._key() == other._key()

    def __repr__(self):
        return "PartitionRules(%s, unmatched=%r)" % (
            [(p, tuple(s)) for p, s in self.rules], self.unmatched)

    def describe(self):
        """JSON-safe summary (checkpoint meta / program cards)."""
        return {"rules": [[p, [None if a is None else a for a in s]]
                          for p, s in self.rules],
                "unmatched": self.unmatched}

    # -- resolution --------------------------------------------------------
    def spec_for(self, name, shape=None):
        """The PartitionSpec for one parameter path name. Scalars and
        one-element leaves never shard; otherwise the first rule whose
        pattern ``re.search``-matches ``name`` wins; unmatched names
        follow the policy."""
        shape = None if shape is None else tuple(int(d) for d in shape)
        key = (name, shape)
        with self._lock:
            hit = self._cache.get(key)
        if hit is not None:
            return hit
        if shape is not None and (len(shape) == 0
                                  or int(np.prod(shape)) <= 1):
            spec = P()
        else:
            spec = None
            for rx, (_, ps) in zip(self._compiled, self.rules):
                if rx.search(name) is not None:
                    spec = ps
                    break
            if spec is None:
                if self.unmatched == UNMATCHED_ERROR:
                    raise MXNetError(
                        "partition: no rule matches parameter %r "
                        "(unmatched policy is 'error'; add a rule or "
                        "a catch-all)" % name)
                spec = P()
        with self._lock:
            self._cache[key] = spec
        return spec

    def prepended(self, rules):
        """A new ``PartitionRules`` with ``rules`` tried BEFORE this
        set's, same unmatched policy — how an engine layers state-
        specific rules (the decode engine's KV-cache leaves) over a
        model's layout without mutating either rule set. First-match-
        wins makes prepending the specificity override."""
        norm = [(p, s) for p, s in rules]
        return PartitionRules(tuple(norm) + self.rules,
                              unmatched=self.unmatched)

    def apply(self, params):
        """{name: PartitionSpec} for a ``{name: array_or_shape}`` tree
        (arrays need only a ``.shape``; plain shape tuples work too)."""
        out = {}
        for name, leaf in params.items():
            shape = getattr(leaf, "shape", leaf)
            out[name] = self.spec_for(name, shape)
        return out


def _downgrade(name, shape, spec, mesh, why):
    """Replicate-with-warning for a matched-but-unplaceable spec: the
    bind survives, the downgrade is counted and logged once."""
    telemetry.counter_inc("partition.replicated_fallback")
    with _downgrade_lock:
        fresh = (name, why) not in _DOWNGRADE_WARNED
        if fresh:
            _DOWNGRADE_WARNED.add((name, why))
    if fresh:
        from .. import log as _log
        _log.get_logger("mxnet_tpu.partition").warning(
            "partition: parameter %r %s cannot take spec %s on mesh %s "
            "(%s) — replicating it instead",
            name, shape, tuple(spec), dict(mesh.shape), why)
    return NamedSharding(mesh, P())


def sharding_for(mesh, name, shape, spec):
    """``NamedSharding`` placing one parameter by its resolved rule
    spec, validated against the mesh: an axis the mesh lacks, a spec
    longer than the rank, or a sharded dim that does not divide by its
    axis product downgrades to replicate (warned + counted)."""
    shape = tuple(int(d) for d in shape)
    entries = tuple(spec)
    if not entries:
        return NamedSharding(mesh, P())
    if len(entries) > len(shape):
        return _downgrade(name, shape, spec, mesh,
                          "spec rank %d exceeds tensor rank %d"
                          % (len(entries), len(shape)))
    axes = dict(mesh.shape)
    for dim, entry in enumerate(entries):
        if entry is None:
            continue
        names = entry if isinstance(entry, tuple) else (entry,)
        factor = 1
        for ax in names:
            if ax not in axes:
                return _downgrade(name, shape, spec, mesh,
                                  "mesh has no %r axis" % (ax,))
            factor *= axes[ax]
        if factor and shape[dim] % factor != 0:
            return _downgrade(
                name, shape, spec, mesh,
                "dim %d (size %d) not divisible by the %r axis "
                "product %d" % (dim, shape[dim], entry, factor))
    return NamedSharding(mesh, P(*entries))


def spec_nbytes(global_nbytes, shape, sharding):
    """Total DEVICE-RESIDENT bytes of one committed array across its
    mesh: per-shard bytes summed over every device. A replicated array
    costs one full copy per device; a sharded dim divides the copy —
    this is the figure the buffer ledger charges (the old global-size
    charge read an mp-sharded parameter as if it were replicated)."""
    try:
        n = len(sharding.device_set)
        if n <= 1:
            return int(global_nbytes)
        shard_shape = sharding.shard_shape(tuple(shape))
        total = int(global_nbytes) or 1
        full = int(np.prod(shape)) if shape else 1
        per = (total * int(np.prod(shard_shape))) // max(full, 1) \
            if shape else total
        return per * n
    except Exception:
        return int(global_nbytes)


def committed_nbytes(arr):
    """``spec_nbytes`` of a live (possibly sharded) jax array."""
    nbytes = int(arr.size) * arr.dtype.itemsize
    sh = getattr(arr, "sharding", None)
    if sh is None:
        return nbytes
    return spec_nbytes(nbytes, tuple(arr.shape), sh)


def partition_summary(spec, param_shapes=None):
    """JSON-safe layout description of one mesh spec (``spmd.
    DataParallelSpec``): what checkpoint meta, tuner plans and program
    cards record so a reader can see HOW the run was laid out. With
    ``param_shapes`` ({name: shape}) the per-parameter resolved specs
    ride along (sharded entries only — replicated is the default and
    listing every bias would bloat the meta)."""
    if spec is None:
        return None
    out = {
        "mesh_axes": {str(k): int(v) for k, v in spec.mesh.shape.items()},
        "data_axis": getattr(spec, "data_axis", None),
        "partition": None,
    }
    rules = getattr(spec, "rules", None)
    if rules is not None:
        out["partition"] = rules.describe()
        if param_shapes:
            sharded = {}
            for name, shape in param_shapes.items():
                ps = rules.spec_for(name, shape)
                if tuple(ps):
                    sharded[name] = [None if a is None else a
                                     for a in tuple(ps)]
            out["partition"]["sharded_params"] = sharded
    return out
