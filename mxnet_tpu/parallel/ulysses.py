"""Ulysses (all-to-all) sequence parallelism.

The second long-context strategy alongside ring attention (SURVEY.md
§5.7 extension; design follows DeepSpeed-Ulysses, Jacobs et al. 2023):
inputs arrive sharded along the SEQUENCE axis; an ``all_to_all`` over
the 'sp' mesh axis re-shards them along the HEAD axis so every device
computes full-sequence attention for its subset of heads; a second
all_to_all restores sequence sharding. Two collectives per attention
call, each moving S·H·D/n elements — on TPU they ride ICI.

Trade-off vs ring attention: Ulysses needs num_heads % n_devices == 0
and moves activations twice, but each device sees the FULL sequence so
the local kernel is a plain (flash) attention with no online-softmax
accumulation across steps; ring keeps memory strictly local but
serializes K/V rotation. Both are exact.
"""
from __future__ import annotations

import functools
import math

import jax
from jax import lax
from jax.sharding import PartitionSpec as P

from .compat import shard_map

from .ring_attention import attention as _plain_attention

__all__ = ["ulysses_attention", "PARTITION_RULES"]

# The Ulysses layout as a partition-rule set: attention runs with the
# HEAD axis sharded over ``sp`` (the all_to_all re-shards activations
# seq->heads), so head-major projection weights — (H*D, E) q/k/v
# producers laid out head-major on dim 0 — shard over ``sp`` while the
# output projection consumes head-major dim 1. Everything else
# replicates.
PARTITION_RULES = [
    (r"(q|k|v)_proj.*weight$", P("sp", None)),
    (r"out_proj.*weight$", P(None, "sp")),
    (r".*", P()),
]


def _ulysses_local(q, k, v, axis_name, causal, scale, use_pallas):
    """Local body under shard_map: q/k/v are (B, H, S_local, D)."""
    n = lax.psum(1, axis_name)

    def seq_to_heads(x):
        # (B, H, S/n, D) -> (B, H/n, S, D): split heads, concat sequence
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qh, kh, vh = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    if use_pallas:
        from ..pallas.flash_attention import flash_attention
        out = flash_attention(qh, kh, vh, causal=causal, scale=scale)
    else:
        out = _plain_attention(qh, kh, vh, causal=causal, scale=scale)
    return heads_to_seq(out)


def ulysses_attention(q, k, v, mesh, axis_name="sp", batch_axis_name=None,
                      causal=False, scale=None, use_pallas=None):
    """All-to-all sequence-parallel attention.

    q/k/v: (B, H, S, D) sharded along S over ``axis_name`` (optionally
    along B over ``batch_axis_name``); H must divide evenly by the 'sp'
    axis size. Returns output with the same sharding. Accepts NDArrays
    or jax arrays.
    """
    from ..ndarray.ndarray import NDArray, _wrap
    from ..base import MXNetError
    wrap_out = isinstance(q, NDArray)
    raw = [x._data if isinstance(x, NDArray) else x for x in (q, k, v)]

    n = mesh.shape[axis_name]
    H = raw[0].shape[1]
    if H % n != 0:
        raise MXNetError(
            "ulysses_attention: num_heads (%d) must be divisible by the "
            "'%s' axis size (%d) — use ring_attention for uneven heads"
            % (H, axis_name, n))
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"

    spec = P(batch_axis_name, None, axis_name, None)
    from jax.sharding import NamedSharding
    # inputs committed to one device (NDArrays) must be laid out over the
    # mesh before shard_map will accept them
    raw = [jax.device_put(x, NamedSharding(mesh, spec)) for x in raw]
    fn = shard_map(
        functools.partial(_ulysses_local, axis_name=axis_name,
                          causal=causal, scale=scale,
                          use_pallas=use_pallas),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=not use_pallas)
    out = fn(*raw)
    return _wrap(out) if wrap_out else out
