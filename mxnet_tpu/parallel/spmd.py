"""SPMD train-step compiler: dp/tp-sharded training as ONE XLA program.

This is where the reference's data-parallel machinery
(DataParallelExecutorGroup splitting batches + KVStore reducing grads,
SURVEY.md §2.3) becomes TPU-native: parameters and batch get sharding
annotations over a Mesh; ``jax.jit`` compiles forward+backward+optimizer
into one program and XLA GSPMD inserts the gradient all-reduce over ICI.
Scaling efficiency is then XLA's collective scheduling, which is the
≥90% target regime (BASELINE.md north star).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..base import MXNetError

__all__ = ["SPMDTrainer", "shard_params_rule"]


def shard_params_rule(params, mesh, tp_axis=None):
    """Default parameter shardings: replicate 1-D params; shard the
    largest divisible dim of matrices over ``tp_axis`` when given.

    Any sharding is semantically valid under GSPMD — this rule is the
    perf default (Megatron-style column split for weight matrices).
    """
    specs = {}
    tp = mesh.shape[tp_axis] if tp_axis else 1
    for name, arr in params.items():
        shape = arr.shape
        if tp_axis and len(shape) >= 2 and shape[0] % tp == 0 and shape[0] >= tp:
            spec = [tp_axis] + [None] * (len(shape) - 1)
            specs[name] = P(*spec)
        elif tp_axis and len(shape) == 1 and shape[0] % tp == 0 and shape[0] >= 128:
            specs[name] = P(tp_axis)
        else:
            specs[name] = P()
    return specs


class SPMDTrainer:
    """Compile and run a sharded train step.

    Parameters
    ----------
    apply_fn : pure fn(params_dict, *batch_arrays) -> loss (scalar jax)
    params : dict name -> jax array (initial values, host or device)
    mesh : jax.sharding.Mesh
    data_axis : mesh axis name the batch is sharded over
    tp_axis : optional mesh axis for tensor-parallel param sharding
    optimizer : 'sgd' (momentum/wd supported) — the fused-update set can
        be extended per ops/optimizer_ops.py
    """

    def __init__(self, apply_fn, params, mesh, data_axis="dp", tp_axis=None,
                 optimizer="sgd", learning_rate=0.01, momentum=0.0, wd=0.0,
                 param_specs=None, batch_specs=None, n_batch_args=2):
        self.mesh = mesh
        self.data_axis = data_axis
        self._apply = apply_fn
        if optimizer != "sgd":
            raise MXNetError("SPMDTrainer supports sgd in this build")
        self.lr = learning_rate
        self.momentum = momentum
        self.wd = wd

        if param_specs is None:
            param_specs = shard_params_rule(params, mesh, tp_axis)
        self.param_shardings = {k: NamedSharding(mesh, param_specs[k])
                                for k in params}
        if batch_specs is None:
            batch_specs = [P(data_axis)] * n_batch_args
        self.batch_shardings = [NamedSharding(mesh, s) for s in batch_specs]

        # place params + momentum sharded
        self.params = {k: jax.device_put(v, self.param_shardings[k])
                       for k, v in params.items()}
        self.mom = {k: jax.device_put(jnp.zeros_like(v),
                                      self.param_shardings[k])
                    for k, v in self.params.items()} if momentum else None

        lr, mom_c, wd_c = self.lr, self.momentum, self.wd

        def step(params, mom, *batch):
            loss, grads = jax.value_and_grad(apply_fn)(params, *batch)
            new_params = {}
            new_mom = {}
            for k, g in grads.items():
                g = g + wd_c * params[k]
                if mom is not None:
                    m = mom_c * mom[k] - lr * g
                    new_mom[k] = m
                    new_params[k] = params[k] + m
                else:
                    new_params[k] = params[k] - lr * g
            return new_params, (new_mom if mom is not None else None), loss

        param_sh = self.param_shardings
        self._step = jax.jit(
            step,
            in_shardings=(param_sh, param_sh if momentum else None,
                          *self.batch_shardings),
            out_shardings=(param_sh, param_sh if momentum else None, None),
            donate_argnums=(0, 1))

    def step(self, *batch):
        """Run one sharded train step; returns the scalar loss."""
        batch = [jax.device_put(np.asarray(b) if not isinstance(b, jax.Array)
                                else b, s)
                 for b, s in zip(batch, self.batch_shardings)]
        self.params, self.mom, loss = self._step(self.params, self.mom,
                                                 *batch)
        return loss

    def get_params(self):
        return {k: np.asarray(jax.device_get(v))
                for k, v in self.params.items()}
