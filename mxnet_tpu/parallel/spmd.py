"""SPMD train-step compiler: dp/tp-sharded training as ONE XLA program.

This is where the reference's data-parallel machinery
(DataParallelExecutorGroup splitting batches + KVStore reducing grads,
SURVEY.md §2.3) becomes TPU-native: parameters and batch get sharding
annotations over a Mesh; ``jax.jit`` compiles forward+backward+optimizer
into one program and XLA GSPMD inserts the gradient all-reduce over ICI.
Scaling efficiency is then XLA's collective scheduling, which is the
≥90% target regime (BASELINE.md north star).
"""
from __future__ import annotations

import collections

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..base import MXNetError
from .. import telemetry

__all__ = ["SPMDTrainer", "shard_params_rule", "DataParallelSpec",
           "dp_spec", "rule_spec", "dist_dp_spec", "is_process_spanning",
           "check_batch_divisible", "shard_put", "dist_shard_put",
           "put_replicated_local", "broadcast_from_zero", "local_value",
           "commit_dp_placements", "commit_state", "DP_AXIS", "MP_AXIS"]

# the canonical data-parallel axis name shared by the Module mesh path,
# the executor's SPMD train-step program and the bench/probe lanes
DP_AXIS = "dp"
# the canonical model-parallel axis name the partition-rule engine
# shards parameters over on a 2-D (dp, mp) mesh
MP_AXIS = "mp"


class DataParallelSpec(
        collections.namedtuple("DataParallelSpec",
                               ["mesh", "data_sharding", "repl_sharding",
                                "rules", "data_axis"],
                               defaults=(None, DP_AXIS))):
    """Hashable bundle describing one mesh layout: the Mesh, the batch
    sharding (dim 0 over the dp axis), the replicated sharding for
    step scalars/metric accumulators — and, for a rule-sharded 2-D
    (dp, mp) mesh, the ``parallel.partition.PartitionRules`` tree that
    resolves per-PARAMETER placements (``rules is None`` keeps the
    original everything-replicated dp layout). Hashability matters:
    the spec rides in ``_GraphProgram.train_step_fn``'s jit-cache key,
    so two Modules on the same mesh + rule set share one compiled SPMD
    step."""
    __slots__ = ()

    @property
    def num_devices(self):
        return self.mesh.devices.size

    @property
    def dp_size(self):
        """Size of the data axis — what the batch dim must divide by
        (NOT the device count: on a 2-D dp x mp mesh only dp splits
        the batch)."""
        return int(dict(self.mesh.shape).get(self.data_axis, 1))

    @property
    def mp_size(self):
        """Product of the non-data axes (1 on a pure dp mesh)."""
        return self.num_devices // max(self.dp_size, 1)

    def param_sharding(self, name, shape):
        """The rule-resolved ``NamedSharding`` for one parameter (the
        replicated sharding when no rule tree is bound)."""
        if self.rules is None:
            return self.repl_sharding
        from .partition import sharding_for
        return sharding_for(self.mesh, name, shape,
                            self.rules.spec_for(name, shape))


def dp_spec(mesh, data_axis=DP_AXIS):
    """DataParallelSpec for a one-axis data-parallel mesh."""
    return DataParallelSpec(mesh,
                            NamedSharding(mesh, P(data_axis)),
                            NamedSharding(mesh, P()),
                            None, data_axis)


def rule_spec(mesh, rules, data_axis=DP_AXIS):
    """Spec for a rule-sharded (possibly 2-D dp x mp) mesh: batch over
    ``data_axis``, parameters by the ``PartitionRules`` tree (None =
    replicate everything — the plain dp layout on a reshaped mesh)."""
    if data_axis not in mesh.axis_names:
        raise MXNetError("rule_spec: mesh %s has no %r data axis"
                         % (tuple(mesh.axis_names), data_axis))
    return DataParallelSpec(mesh,
                            NamedSharding(mesh, P(data_axis)),
                            NamedSharding(mesh, P()),
                            rules, data_axis)


def is_process_spanning(mesh):
    """Whether the mesh crosses worker processes — the dist tier: batch
    assembly must go through the process-local constructors and the
    fit loop must gate collectives on worker liveness."""
    return len({d.process_index for d in mesh.devices.flat}) > 1


def _mesh_local_devices(mesh):
    """This process's devices within the mesh, in mesh order."""
    me = jax.process_index()
    return [d for d in mesh.devices.flat if d.process_index == me]


def dist_dp_spec(contexts, data_axis=DP_AXIS, live_ranks=None):
    """Process-spanning DataParallelSpec: ONE dp mesh over every live
    worker process — the TPU-native reading of the reference's
    worker set (each ps-lite worker's device group becomes a
    contiguous slab of the ``dp`` axis of ONE program's mesh, so the
    cross-host gradient all-reduce compiles INTO the train step).

    Every process contributes the same number of devices (SPMD jobs
    are symmetric): this process uses its bound ``contexts``, remote
    processes their first ``len(contexts)`` devices by id.
    ``live_ranks`` restricts membership — the elastic re-mesh after a
    member loss builds the smaller mesh from exactly the surviving
    process set."""
    local_devs = [c.jax_device() for c in contexts] if contexts \
        else jax.local_devices()[:1]
    n_local = len(local_devs)
    by_proc = {}
    for d in jax.devices():
        by_proc.setdefault(d.process_index, []).append(d)
    live = sorted(by_proc) if live_ranks is None \
        else sorted(int(r) for r in live_ranks)
    me = jax.process_index()
    devs = []
    for p in live:
        plist = sorted(by_proc.get(p, []), key=lambda d: d.id)[:n_local]
        if len(plist) < n_local:
            raise MXNetError(
                "dist mesh needs %d devices on process %d, found %d "
                "(SPMD jobs must be symmetric)"
                % (n_local, p, len(plist)))
        if p == me and plist != local_devs:
            # every process must derive the IDENTICAL global mesh from
            # its own view, and a peer's actual binding is unknowable —
            # so the first-N-by-id convention is mandatory. A worker
            # bound to other (or reordered) local devices would build a
            # mesh its peers disagree with: cross-process collectives
            # over mismatched device orders hang or mis-place shards.
            raise MXNetError(
                "dist mesh requires each worker to bind its first %d "
                "local device(s) in id order (got %s, expected %s): "
                "every process derives the global mesh by that "
                "convention" % (n_local, local_devs, plist))
        devs.extend(plist)
    return dp_spec(Mesh(np.array(devs), (data_axis,)), data_axis)


def dist_shard_put(raw, spec):
    """Assemble the GLOBAL batch from this process's LOCAL portion on a
    process-spanning mesh: each worker feeds only its own rows (its
    data iterator's batch); the constructor places them as this
    process's shard of the global array — no cross-process transfer,
    no host-side gather. The global batch dim is
    ``local_rows x live_processes``."""
    with telemetry.span("shard_put"):
        raw = np.asarray(raw)   # mxlint: disable=host-sync -- feed-path marshalling of the LOCAL host batch (the iterator's rows); device arrays are a view, not a fetch
        telemetry.record_transfer(raw.nbytes)
        locals_ = _mesh_local_devices(spec.mesh)
        check_batch_divisible(raw.shape[0], len(locals_),
                              "local batch size")
        factor = spec.mesh.devices.size // len(locals_)
        global_shape = (raw.shape[0] * factor,) + tuple(raw.shape[1:])
        out = jax.make_array_from_process_local_data(
            spec.data_sharding, raw, global_shape)
        if telemetry.enabled():
            telemetry.ledger_track(
                out, "mesh(%ddev)" % spec.mesh.devices.size,
                int(out.size) * out.dtype.itemsize,
                shape=out.shape, dtype=out.dtype, kind="shard_put")
        return out


def put_replicated_local(raw, spec):
    """Global REPLICATED array from a value every process already
    holds, with NO collective: each process installs its local copy on
    its mesh devices and the constructor declares them one replicated
    array. Correct only under the SPMD discipline (every worker
    computes the same replicated values in the same order — true for
    params/optimizer state/step scalars after the one-time
    :func:`broadcast_from_zero` at commit); the zero per-step cost is
    why the fused dist step can feed lrs/ts/rng without a cross-host
    round trip."""
    if isinstance(raw, (int, float)):
        raw = np.asarray(raw)   # mxlint: disable=host-sync -- host scalar literal, no device buffer involved
    shards = [jax.device_put(raw, d) for d in _mesh_local_devices(spec.mesh)]
    return jax.make_array_from_single_device_arrays(
        tuple(np.shape(raw)), spec.repl_sharding, shards)


def broadcast_from_zero(tree):   # mxsync: collective channel=kv
    """One host-level broadcast of a pytree from process 0 to all
    (parity: the reference's kv.init server seeding + worker pull —
    every worker starts from rank 0's values). A no-op outside
    multi-process runs. Indexed as a cross-process collective for
    mxsync's collective-discipline rule (default channel ``kv``; the
    fused-step commit path overrides per call site): every caller must
    be dominated by a matching CollectiveGate crossing, or a peer that
    died earlier hangs the broadcast."""
    if jax.process_count() <= 1:
        return tree
    from jax.experimental import multihost_utils
    return multihost_utils.broadcast_one_to_all(tree)


def local_value(garr):
    """This process's host-side view of a (possibly process-spanning)
    array: the full value when replicated, the locally-addressable rows
    (concatenated in shard order) when batch-sharded. Never talks to a
    peer — safe in elastic recovery when some mesh members are dead."""
    if not hasattr(garr, "sharding"):
        return np.asarray(garr)   # mxlint: disable=host-sync -- detach/commit path by design: placement transitions NEED the host value (runs per commit/fallback/re-mesh, not per step)
    if garr.sharding.is_fully_replicated:
        return np.asarray(garr.addressable_data(0))   # mxlint: disable=host-sync -- same: the local replica read IS the detach
    shards = sorted(garr.addressable_shards,
                    key=lambda s: s.index[0].start or 0)
    return np.concatenate([np.asarray(s.data) for s in shards], axis=0)   # mxlint: disable=host-sync -- same: local shard reads on the detach path


def check_batch_divisible(batch_dim, n_devices, what="batch size",
                          axis=None):
    """The ONE owner of the dp divisibility rule: bind-time shape checks
    (Module bind / executor-group construction) and per-step feeds (a
    variable-shape batch swapped in mid-training) raise the same clear
    error instead of padding silently or dying inside XLA.

    ``axis`` names the mesh axis the batch divides over: on a 2-D
    dp x mp mesh "batch 6 not divisible by 8 devices" would be WRONG —
    the batch divides by ``dp``, not by the device count — so mesh
    callers pass the axis and the error names it."""
    if batch_dim % n_devices != 0:
        if axis is not None:
            raise MXNetError(
                "%s %d not divisible by the %r mesh axis (size %d; the "
                "batch shards over %r only, not over every device)"
                % (what, batch_dim, axis, n_devices, axis))
        raise MXNetError("%s %d not divisible by %d devices"
                         % (what, batch_dim, n_devices))


def shard_put(raw, sharding):
    """Sharded device_put of a GLOBAL batch array: each device receives
    only its shard (no host-side splitting, no full-batch replication —
    the TPU-native replacement for the reference's decide_slices copy
    loop, executor_group.py:266). Host-resident inputs count toward the
    telemetry h2d-bytes register; device-side reshards do not. Every
    sharded batch also enters the live device-buffer LEDGER under its
    mesh's context key (released when the buffer dies), so an OOM
    mid-feed names the in-flight batches alongside the executor's
    resident arrays. The ledger charge is the summed PER-SHARD bytes
    across the mesh (``partition.committed_nbytes``): an mp-sharded
    parameter charges 1/mp of a replicated copy per device, not the
    replicated global size."""
    with telemetry.span("shard_put"):
        if isinstance(raw, np.ndarray):
            telemetry.record_transfer(raw.nbytes)
        out = jax.device_put(raw, sharding)
        if telemetry.enabled():
            from .partition import committed_nbytes
            try:
                n_dev = len(sharding.device_set)
            except AttributeError:
                n_dev = 0
            telemetry.ledger_track(
                out, "mesh(%ddev)" % n_dev, committed_nbytes(out),
                shape=out.shape, dtype=out.dtype, kind="shard_put")
        return out


def commit_state(raw, sharding, anchor, kind="kv_cache"):
    """Commit LONG-LIVED, donation-cycled device state (the decode
    engine's KV-cache pool): ``device_put`` per the rule-resolved
    sharding plus a DURABLE per-shard ledger charge under ``kind``.

    The charge is keyed on ``anchor`` — an owner-held token object —
    not on the array wrapper: donated dispatches rebind the wrapper
    every step while the storage stays aliased, so a wrapper-keyed
    charge (``shard_put``'s contract) would silently vanish after the
    first decode step. ``replace=True`` makes a rebuild (cache re-init
    after a poisoned dispatch) update the charge instead of
    double-counting. The charge retires when the anchor dies with its
    engine."""
    with telemetry.span("shard_put"):
        if isinstance(raw, np.ndarray):
            telemetry.record_transfer(raw.nbytes)
        out = jax.device_put(raw, sharding)
        if telemetry.enabled():
            from .partition import committed_nbytes
            try:
                n_dev = len(sharding.device_set)
            except AttributeError:
                n_dev = 1
            telemetry.ledger_track(
                anchor, "mesh(%ddev)" % n_dev, committed_nbytes(out),
                shape=out.shape, dtype=out.dtype, kind=kind,
                replace=True)
        return out


def commit_dp_placements(executor, input_names, spec, sync=True,
                         gate=None):
    """Commit the mesh placements on ONE bound executor's storage:
    batch-like inputs (data/labels/states, all batch-major) shard over
    the data axis; params/grads/aux take their RULE-resolved placement
    (``spec.param_sharding`` — replicated on a plain dp spec, per-
    parameter mp shards under a ``PartitionRules`` tree; a gradient
    rides its parameter's placement, so the psum GSPMD inserts reduces
    over ``dp`` only). The ONE owner of the placement rule —
    Module._shard_exec_arrays and the multi-context
    DataParallelExecutorGroup facade both call this, so the two can
    never drift. GSPMD propagates from these committed placements for
    every program the executor runs. Committed parameters are charged
    on the buffer ledger under the mesh context key at their summed
    per-shard size (kind ``param``, replacing any prior commit charge)
    — the figure the mp-smoke lane gates 1/mp savings on.

    ``gate``: the caller's pre-collective :class:`CollectiveGate`,
    crossed before the rank-0 sync broadcast on the process-spanning
    path — a peer that died before the first commit must surface as
    ``DeadWorkerError`` here, not hang the broadcast (mxsync's
    collective-discipline check drove this). In-process callers (the
    local dp facade) have no cross-process exchange and pass None."""
    if not is_process_spanning(spec.mesh):
        from .partition import committed_nbytes
        ctx_key = "mesh(%ddev)" % spec.num_devices
        arg_names = list(executor.arg_dict)

        def _track_param(arr):
            if telemetry.enabled():
                telemetry.ledger_track(
                    arr, ctx_key, committed_nbytes(arr._data),
                    shape=arr._data.shape, dtype=arr._data.dtype,
                    kind="param", replace=True)

        for name, arr in executor.arg_dict.items():
            if name in input_names:
                arr._set_data(jax.device_put(arr._data,
                                             spec.data_sharding))
            else:
                arr._set_data(jax.device_put(
                    arr._data, spec.param_sharding(name, arr.shape)))
                _track_param(arr)
        # a gradient lives where its parameter does (the optimizer step
        # reads both; mismatched placements would reshard every step);
        # input gradients (inputs_need_grad) are batch-major like their
        # input
        for name, arr in zip(arg_names, executor.grad_arrays):
            if arr is not None:
                sh = spec.data_sharding if name in input_names \
                    else spec.param_sharding(name, arr.shape)
                arr._set_data(jax.device_put(arr._data, sh))
        for name, arr in executor.aux_dict.items():
            if arr is not None:
                arr._set_data(jax.device_put(
                    arr._data, spec.param_sharding(name, arr.shape)))
                _track_param(arr)
        return
    if spec.rules is not None:
        # the dist tier commits replicated state via one rank-0
        # broadcast; re-sharding rule trees across worker processes is
        # not wired yet (ROADMAP: multi-host mp)
        raise MXNetError("partition rules are not supported on a "
                         "process-spanning mesh yet; use a dp-only "
                         "dist spec")
    # process-spanning commit (the dist tier): replicated state is
    # synchronised from rank 0 in ONE host broadcast — parity with the
    # reference's kv.init-then-pull worker seeding, and the guarantee
    # behind put_replicated_local's no-collective puts — then installed
    # via the process-local constructors; batch-like inputs install this
    # worker's local rows as its shard of the global batch
    repl, batch = {}, {}
    for name, arr in executor.arg_dict.items():
        (batch if name in input_names else repl)[name] = \
            local_value(arr._data)
    grads = {i: local_value(a._data)
             for i, a in enumerate(executor.grad_arrays) if a is not None}
    auxes = {i: local_value(a._data)
             for i, a in enumerate(executor.aux_arrays) if a is not None}
    synced = {"params": repl, "grads": grads, "aux": auxes}
    if sync:
        # sync=False is the elastic re-mesh path: the broadcast spans
        # EVERY launched process (dead members would hang it), and the
        # survivors' replicated values are already identical — the
        # checkpoint restore that follows overwrites them anyway
        if gate is not None:
            gate.arrive_and_wait()
        synced = broadcast_from_zero(synced)   # mxsync: collective channel=step
    for name, arr in executor.arg_dict.items():
        if name in input_names:
            arr._set_data(dist_shard_put(batch[name], spec))
        else:
            arr._set_data(put_replicated_local(synced["params"][name], spec))
    for i, arr in enumerate(executor.grad_arrays):
        if arr is not None:
            arr._set_data(put_replicated_local(synced["grads"][i], spec))
    for i, arr in enumerate(executor.aux_arrays):
        if arr is not None:
            arr._set_data(put_replicated_local(synced["aux"][i], spec))


def shard_params_rule(params, mesh, tp_axis=None):
    """Default parameter shardings: replicate 1-D params; shard the
    largest divisible dim of matrices over ``tp_axis`` when given.

    Any sharding is semantically valid under GSPMD — this rule is the
    perf default (Megatron-style column split for weight matrices).
    """
    specs = {}
    tp = mesh.shape[tp_axis] if tp_axis else 1
    for name, arr in params.items():
        shape = arr.shape
        if tp_axis and len(shape) >= 2 and shape[0] % tp == 0 and shape[0] >= tp:
            spec = [tp_axis] + [None] * (len(shape) - 1)
            specs[name] = P(*spec)
        elif tp_axis and len(shape) == 1 and shape[0] % tp == 0 and shape[0] >= 128:
            specs[name] = P(tp_axis)
        else:
            specs[name] = P()
    return specs


class SPMDTrainer:
    """Compile and run a sharded train step.

    Parameters
    ----------
    apply_fn : pure fn(params_dict, *batch_arrays) -> loss (scalar jax)
    params : dict name -> jax array (initial values, host or device)
    mesh : jax.sharding.Mesh
    data_axis : mesh axis name the batch is sharded over
    tp_axis : optional mesh axis for tensor-parallel param sharding
    optimizer : 'sgd' (momentum/wd supported) — the fused-update set can
        be extended per ops/optimizer_ops.py
    """

    def __init__(self, apply_fn, params, mesh, data_axis="dp", tp_axis=None,
                 optimizer="sgd", learning_rate=0.01, momentum=0.0, wd=0.0,
                 param_specs=None, batch_specs=None, n_batch_args=2,
                 **optimizer_kwargs):
        from . import opt_kernels
        self.mesh = mesh
        self.data_axis = data_axis
        self._apply = apply_fn

        # any registered optimizer: an Optimizer instance (or name, built
        # via the optimizer registry so per-optimizer defaults apply) maps
        # onto its pure kernel
        from .. import optimizer as opt_mod
        if not isinstance(optimizer, opt_mod.Optimizer):
            okw = dict(optimizer_kwargs)
            okw.setdefault("learning_rate", learning_rate)
            okw.setdefault("wd", wd)
            if momentum:
                okw.setdefault("momentum", momentum)
            optimizer = opt_mod.create(optimizer, **okw)
        kname, hyper = opt_kernels.hyper_from_optimizer(optimizer)
        init_fn, update_fn = opt_kernels.get_kernel(kname)
        self.lr = hyper["lr"]
        self.momentum = hyper.get("momentum", 0.0)
        self.wd = hyper["wd"]
        self._hyper = hyper
        self._num_update = 0

        if param_specs is None:
            param_specs = shard_params_rule(params, mesh, tp_axis)
        self.param_shardings = {k: NamedSharding(mesh, param_specs[k])
                                for k in params}
        if batch_specs is None:
            batch_specs = [P(data_axis)] * n_batch_args
        self.batch_shardings = [NamedSharding(mesh, s) for s in batch_specs]

        # place params + per-param optimizer state sharded like the param
        self.params = {k: jax.device_put(v, self.param_shardings[k])
                       for k, v in params.items()}
        self.opt_state = {
            k: tuple(jax.device_put(s, self.param_shardings[k])
                     for s in init_fn(v))
            for k, v in self.params.items()}

        # static hyperparams fold into the program; lr and t stay traced
        # so schedules/bias-correction never trigger a recompile
        static_h = dict(hyper)

        def step(params, opt_state, lr, t, *batch):
            loss, grads = jax.value_and_grad(apply_fn)(params, *batch)
            h = dict(static_h)
            h["lr"] = lr
            new_params = {}
            new_state = {}
            for k, g in grads.items():
                new_params[k], new_state[k] = update_fn(
                    params[k], g, opt_state[k], t, h)
            return new_params, new_state, loss

        param_sh = self.param_shardings
        state_sh = {k: tuple(param_sh[k] for _ in self.opt_state[k])
                    for k in self.opt_state}
        self._step = jax.jit(
            step,
            in_shardings=(param_sh, state_sh, None, None,
                          *self.batch_shardings),
            out_shardings=(param_sh, state_sh, None),
            donate_argnums=(0, 1))

    # back-compat: round-1 callers read .mom for sgd momentum state
    @property
    def mom(self):
        if not self.momentum:
            return None
        return {k: s[0] for k, s in self.opt_state.items()}

    def step(self, *batch):
        """Run one sharded train step; returns the scalar loss."""
        batch = [jax.device_put(np.asarray(b) if not isinstance(b, jax.Array)
                                else b, s)
                 for b, s in zip(batch, self.batch_shardings)]
        self._num_update += 1
        self.params, self.opt_state, loss = self._step(
            self.params, self.opt_state,
            jnp.float32(self.lr), jnp.float32(self._num_update), *batch)
        return loss

    def get_params(self):
        return {k: np.asarray(jax.device_get(v))
                for k, v in self.params.items()}
