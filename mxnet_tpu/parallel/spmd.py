"""SPMD train-step compiler: dp/tp-sharded training as ONE XLA program.

This is where the reference's data-parallel machinery
(DataParallelExecutorGroup splitting batches + KVStore reducing grads,
SURVEY.md §2.3) becomes TPU-native: parameters and batch get sharding
annotations over a Mesh; ``jax.jit`` compiles forward+backward+optimizer
into one program and XLA GSPMD inserts the gradient all-reduce over ICI.
Scaling efficiency is then XLA's collective scheduling, which is the
≥90% target regime (BASELINE.md north star).
"""
from __future__ import annotations

import collections

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..base import MXNetError
from .. import telemetry

__all__ = ["SPMDTrainer", "shard_params_rule", "DataParallelSpec",
           "dp_spec", "check_batch_divisible", "shard_put",
           "commit_dp_placements", "DP_AXIS"]

# the canonical data-parallel axis name shared by the Module mesh path,
# the executor's SPMD train-step program and the bench/probe lanes
DP_AXIS = "dp"


class DataParallelSpec(
        collections.namedtuple("DataParallelSpec",
                               ["mesh", "data_sharding", "repl_sharding"])):
    """Hashable bundle describing one data-parallel mesh: the Mesh, the
    batch sharding (dim 0 over the dp axis) and the replicated sharding
    for params/optimizer state/metric accumulators. Hashability matters:
    the spec rides in ``_GraphProgram.train_step_fn``'s jit-cache key, so
    two Modules on the same mesh share one compiled SPMD step."""
    __slots__ = ()

    @property
    def num_devices(self):
        return self.mesh.devices.size


def dp_spec(mesh, data_axis=DP_AXIS):
    """DataParallelSpec for a one-axis data-parallel mesh."""
    return DataParallelSpec(mesh,
                            NamedSharding(mesh, P(data_axis)),
                            NamedSharding(mesh, P()))


def check_batch_divisible(batch_dim, n_devices, what="batch size"):
    """The ONE owner of the dp divisibility rule: bind-time shape checks
    (Module bind / executor-group construction) and per-step feeds (a
    variable-shape batch swapped in mid-training) raise the same clear
    error instead of padding silently or dying inside XLA."""
    if batch_dim % n_devices != 0:
        raise MXNetError("%s %d not divisible by %d devices"
                         % (what, batch_dim, n_devices))


def shard_put(raw, sharding):
    """Sharded device_put of a GLOBAL batch array: each device receives
    only its shard (no host-side splitting, no full-batch replication —
    the TPU-native replacement for the reference's decide_slices copy
    loop, executor_group.py:266). Host-resident inputs count toward the
    telemetry h2d-bytes register; device-side reshards do not. Every
    sharded batch also enters the live device-buffer LEDGER under its
    mesh's context key (global bytes; released when the buffer dies),
    so an OOM mid-feed names the in-flight batches alongside the
    executor's resident arrays."""
    with telemetry.span("shard_put"):
        if isinstance(raw, np.ndarray):
            telemetry.record_transfer(raw.nbytes)
        out = jax.device_put(raw, sharding)
        if telemetry.enabled():
            try:
                n_dev = len(sharding.device_set)
            except AttributeError:
                n_dev = 0
            telemetry.ledger_track(
                out, "mesh(%ddev)" % n_dev,
                int(out.size) * out.dtype.itemsize,
                shape=out.shape, dtype=out.dtype, kind="shard_put")
        return out


def commit_dp_placements(executor, input_names, spec):
    """Commit the dp-mesh placements on ONE bound executor's storage:
    batch-like inputs (data/labels/states, all batch-major) shard over
    the data axis, params/grads/aux replicate. The ONE owner of the
    placement rule — Module._shard_exec_arrays and the multi-context
    DataParallelExecutorGroup facade both call this, so the two can
    never drift. GSPMD propagates from these committed placements for
    every program the executor runs."""
    for name, arr in executor.arg_dict.items():
        sh = spec.data_sharding if name in input_names \
            else spec.repl_sharding
        arr._set_data(jax.device_put(arr._data, sh))
    for arr in list(executor.grad_arrays) + list(executor.aux_arrays):
        if arr is not None:
            arr._set_data(jax.device_put(arr._data, spec.repl_sharding))


def shard_params_rule(params, mesh, tp_axis=None):
    """Default parameter shardings: replicate 1-D params; shard the
    largest divisible dim of matrices over ``tp_axis`` when given.

    Any sharding is semantically valid under GSPMD — this rule is the
    perf default (Megatron-style column split for weight matrices).
    """
    specs = {}
    tp = mesh.shape[tp_axis] if tp_axis else 1
    for name, arr in params.items():
        shape = arr.shape
        if tp_axis and len(shape) >= 2 and shape[0] % tp == 0 and shape[0] >= tp:
            spec = [tp_axis] + [None] * (len(shape) - 1)
            specs[name] = P(*spec)
        elif tp_axis and len(shape) == 1 and shape[0] % tp == 0 and shape[0] >= 128:
            specs[name] = P(tp_axis)
        else:
            specs[name] = P()
    return specs


class SPMDTrainer:
    """Compile and run a sharded train step.

    Parameters
    ----------
    apply_fn : pure fn(params_dict, *batch_arrays) -> loss (scalar jax)
    params : dict name -> jax array (initial values, host or device)
    mesh : jax.sharding.Mesh
    data_axis : mesh axis name the batch is sharded over
    tp_axis : optional mesh axis for tensor-parallel param sharding
    optimizer : 'sgd' (momentum/wd supported) — the fused-update set can
        be extended per ops/optimizer_ops.py
    """

    def __init__(self, apply_fn, params, mesh, data_axis="dp", tp_axis=None,
                 optimizer="sgd", learning_rate=0.01, momentum=0.0, wd=0.0,
                 param_specs=None, batch_specs=None, n_batch_args=2,
                 **optimizer_kwargs):
        from . import opt_kernels
        self.mesh = mesh
        self.data_axis = data_axis
        self._apply = apply_fn

        # any registered optimizer: an Optimizer instance (or name, built
        # via the optimizer registry so per-optimizer defaults apply) maps
        # onto its pure kernel
        from .. import optimizer as opt_mod
        if not isinstance(optimizer, opt_mod.Optimizer):
            okw = dict(optimizer_kwargs)
            okw.setdefault("learning_rate", learning_rate)
            okw.setdefault("wd", wd)
            if momentum:
                okw.setdefault("momentum", momentum)
            optimizer = opt_mod.create(optimizer, **okw)
        kname, hyper = opt_kernels.hyper_from_optimizer(optimizer)
        init_fn, update_fn = opt_kernels.get_kernel(kname)
        self.lr = hyper["lr"]
        self.momentum = hyper.get("momentum", 0.0)
        self.wd = hyper["wd"]
        self._hyper = hyper
        self._num_update = 0

        if param_specs is None:
            param_specs = shard_params_rule(params, mesh, tp_axis)
        self.param_shardings = {k: NamedSharding(mesh, param_specs[k])
                                for k in params}
        if batch_specs is None:
            batch_specs = [P(data_axis)] * n_batch_args
        self.batch_shardings = [NamedSharding(mesh, s) for s in batch_specs]

        # place params + per-param optimizer state sharded like the param
        self.params = {k: jax.device_put(v, self.param_shardings[k])
                       for k, v in params.items()}
        self.opt_state = {
            k: tuple(jax.device_put(s, self.param_shardings[k])
                     for s in init_fn(v))
            for k, v in self.params.items()}

        # static hyperparams fold into the program; lr and t stay traced
        # so schedules/bias-correction never trigger a recompile
        static_h = dict(hyper)

        def step(params, opt_state, lr, t, *batch):
            loss, grads = jax.value_and_grad(apply_fn)(params, *batch)
            h = dict(static_h)
            h["lr"] = lr
            new_params = {}
            new_state = {}
            for k, g in grads.items():
                new_params[k], new_state[k] = update_fn(
                    params[k], g, opt_state[k], t, h)
            return new_params, new_state, loss

        param_sh = self.param_shardings
        state_sh = {k: tuple(param_sh[k] for _ in self.opt_state[k])
                    for k in self.opt_state}
        self._step = jax.jit(
            step,
            in_shardings=(param_sh, state_sh, None, None,
                          *self.batch_shardings),
            out_shardings=(param_sh, state_sh, None),
            donate_argnums=(0, 1))

    # back-compat: round-1 callers read .mom for sgd momentum state
    @property
    def mom(self):
        if not self.momentum:
            return None
        return {k: s[0] for k, s in self.opt_state.items()}

    def step(self, *batch):
        """Run one sharded train step; returns the scalar loss."""
        batch = [jax.device_put(np.asarray(b) if not isinstance(b, jax.Array)
                                else b, s)
                 for b, s in zip(batch, self.batch_shardings)]
        self._num_update += 1
        self.params, self.opt_state, loss = self._step(
            self.params, self.opt_state,
            jnp.float32(self.lr), jnp.float32(self._num_update), *batch)
        return loss

    def get_params(self):
        return {k: np.asarray(jax.device_get(v))
                for k, v in self.params.items()}
