"""Named XLA collectives — the communication backend.

Parity mapping (SURVEY.md §5.8): NCCL reduce+bcast / CommDevice P2P
reduce → ``all_reduce`` (psum over ICI); row_sparse pull → sharded
gather/``all_to_all``; ps-lite push/pull → nothing (sharding + psum in
the compiled step). These functions are for use INSIDE shard_map-ped
functions; at the jit level, shardings make XLA insert collectives
automatically.
"""
from __future__ import annotations

import jax
from jax import lax

__all__ = ["all_reduce", "all_gather", "reduce_scatter", "ppermute",
           "all_to_all"]


def all_reduce(x, axis_name, op="sum"):
    if op == "sum":
        return lax.psum(x, axis_name)
    if op == "mean":
        return lax.pmean(x, axis_name)
    if op == "max":
        return lax.pmax(x, axis_name)
    if op == "min":
        return lax.pmin(x, axis_name)
    raise ValueError("unknown op %r" % op)


def all_gather(x, axis_name, axis=0, tiled=True):
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name, scatter_dimension=0):
    return lax.psum_scatter(x, axis_name,
                            scatter_dimension=scatter_dimension, tiled=True)


def ppermute(x, axis_name, perm):
    return lax.ppermute(x, axis_name, perm)


def all_to_all(x, axis_name, split_axis, concat_axis, tiled=True):
    return lax.all_to_all(x, axis_name, split_axis, concat_axis, tiled=tiled)
