"""Pipeline parallelism: GPipe-style microbatch pipelining over a mesh axis.

New-framework extension (SURVEY.md §2.3 — the reference's closest
analogue is manual per-layer ``group2ctx`` model parallelism). Design:
each device along the 'pp' axis holds ONE stage's parameters; a
microbatch stream flows through the ring with one ``ppermute`` per
tick. The schedule runs n_micro + n_stages - 1 ticks inside a
``lax.scan``, so the whole pipeline — bubbles, transfers, compute — is
a single compiled program and XLA overlaps the neighbour transfer with
the next tick's compute. Differentiable end to end (the backward
pipeline falls out of jax.vjp through the scan/ppermute).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .compat import shard_map

__all__ = ["pipeline_apply", "PARTITION_RULES"]

# The GPipe layout as a partition-rule set: every stage-stacked
# parameter (leading stage axis of size n, the shape
# ``pipeline_apply`` requires) shards over ``pp`` — device i holds
# stage i, the placement the kernel commits by hand below. Name
# stage-stacked leaves ``*_stages`` (or match everything with a
# catch-all when the whole tree is stage-stacked) and the rule engine
# reproduces it.
PARTITION_RULES = [
    (r"stage", P("pp")),
    (r".*", P("pp")),
]


def _pipe_local(params, x, stage_fn, axis_name, n_micro):
    """Per-device body. params: this stage's params (leading stage axis
    of size 1). x: (n_micro_local..., ) — every device receives the
    full microbatch stream but only stage 0 injects it."""
    n = lax.psum(1, axis_name)
    stage = lax.axis_index(axis_name)
    mb_shape = x.shape[1:]

    total = n_micro + n - 1
    perm = [(i, (i + 1) % n) for i in range(n)]

    def tick(carry, t):
        acc, cur = carry
        # stage 0 ingests microbatch t (when one remains); others use the
        # activation ppermuted from the previous stage
        inject = jnp.where(t < n_micro, t, n_micro - 1)
        x_in = jnp.where(stage == 0, x[inject], cur)
        y = stage_fn(jax.tree.map(lambda p: p[0], params), x_in)
        # last stage records finished microbatch t - (n - 1); a where-
        # based update keeps both sides' varying-mesh-axes types equal
        # under shard_map (lax.cond would reject the mismatch)
        done_idx = t - (n - 1)
        is_done = jnp.logical_and(stage == n - 1, done_idx >= 0)
        upd = lax.dynamic_update_index_in_dim(
            acc, y, jnp.maximum(done_idx, 0), 0)
        acc = jnp.where(is_done, upd, acc)
        nxt = lax.ppermute(y, axis_name, perm)
        return (acc, nxt), None

    # carries become device-varying after one tick; mark them so from
    # the start or the scan's carry types disagree (shard_map vma rules)
    def _varying(v):
        if hasattr(lax, "pcast"):
            return lax.pcast(v, (axis_name,), to="varying")
        if hasattr(lax, "pvary"):
            return lax.pvary(v, (axis_name,))
        return v  # pre-vma JAX: shard_map has no varying/replicated types

    acc0 = _varying(jnp.zeros((n_micro,) + mb_shape, x.dtype))
    cur0 = _varying(jnp.zeros(mb_shape, x.dtype))
    (acc, _), _ = lax.scan(tick, (acc0, cur0), jnp.arange(total))
    # every device returns the accumulator; only the last stage's is
    # non-zero — a psum broadcasts it to all (cheap at dryrun scale;
    # production would keep outputs stage-local)
    return lax.psum(acc, axis_name)


def pipeline_apply(stage_fn, stage_params, x, mesh, axis_name="pp",
                   n_micro=None):
    """Apply ``n`` pipeline stages to ``x``.

    stage_fn(params_i, mb) -> mb : one stage's computation; every stage
    must map activations to the same shape (classic GPipe layout).
    stage_params: pytree whose leaves have a leading stage axis of size
    n (sharded over ``axis_name``). x: (n_micro, mb...) microbatched
    input, replicated. Returns (n_micro, mb...) outputs of the last
    stage.
    """
    from ..ndarray.ndarray import NDArray, _wrap
    wrap = isinstance(x, NDArray)
    xr = x._data if isinstance(x, NDArray) else x
    n = dict(zip(mesh.axis_names, mesh.devices.shape))[axis_name]
    n_micro = n_micro or xr.shape[0]

    params = jax.tree.map(
        lambda p: jax.device_put(p._data if isinstance(p, NDArray) else p,
                                 NamedSharding(mesh, P(axis_name))),
        stage_params)
    xr = jax.device_put(xr, NamedSharding(mesh, P()))

    fn = shard_map(
        functools.partial(_pipe_local, stage_fn=stage_fn,
                          axis_name=axis_name, n_micro=n_micro),
        mesh=mesh,
        in_specs=(jax.tree.map(lambda _: P(axis_name), stage_params), P()),
        out_specs=P())
    out = fn(params, xr)
    return _wrap(out) if wrap else out
