"""Runtime configuration knobs.

Parity: the reference's ~30 ``MXNET_*`` environment variables read via
``dmlc::GetEnv`` (SURVEY.md §5.6). Every reference knob is REGISTERED
here with its disposition on TPU:

- ``honored`` — read and acted on by this build;
- ``mapped``  — the need it served is met by a TPU-native mechanism
  (named in the description); the variable is accepted and ignored;
- the registry makes the surface introspectable (:func:`list_knobs`),
  which the reference never had.

Knobs with real behavior here:
- ``MXNET_BACKWARD_DO_MIRROR`` -> ``jax.checkpoint`` rematerialisation of
  the forward inside the fused fwd+bwd program (the reference's memory
  mirroring trades FLOPs for memory exactly the same way,
  graph_executor.cc:282-305).
- ``MXNET_CPU_WORKER_NTHREADS`` -> engine worker pool size AND the
  default ImageIter decode-thread count.
- ``MXNET_ENGINE_TYPE=NaiveEngine`` -> synchronous engine debugging mode.
- ``MXNET_EXEC_NUM_TEMP`` -> pooled temp-space slots (resource.py).
- ``MXNET_STORAGE_FALLBACK_LOG_VERBOSE`` -> warn when a sparse op falls
  back to its dense view.
- ``MXNET_PROFILER_AUTOSTART`` -> profiler starts at import.
"""
from __future__ import annotations

import logging
import os

from .base import get_env

__all__ = ["list_knobs", "storage_fallback_log", "do_mirror", "fused_fit"]

# name -> (disposition, description)
_KNOBS = {
    # engine
    "MXNET_ENGINE_TYPE": ("honored", "NaiveEngine = synchronous debug mode "
                          "(engine.py; ≙ reference threaded_engine.h:355)"),
    "MXNET_CPU_WORKER_NTHREADS": ("honored", "engine pool size and default "
                                  "image decode threads"),
    "MXNET_CPU_PRIORITY_NTHREADS": ("mapped", "PJRT owns dispatch; no "
                                    "priority CPU queue exists"),
    "MXNET_GPU_WORKER_NTHREADS": ("mapped", "PJRT streams replace per-GPU "
                                  "worker threads"),
    "MXNET_OMP_MAX_THREADS": ("mapped", "XLA:CPU threadpool is configured "
                              "by XLA flags"),
    "MXNET_ENGINE_INFO": ("honored", "verbose engine dispatch logging "
                          "(engine.py)"),
    # memory
    "MXNET_GPU_MEM_POOL_RESERVE": ("mapped", "PJRT owns HBM; use "
                                   "XLA_PYTHON_CLIENT_MEM_FRACTION"),
    "MXNET_EXEC_NUM_TEMP": ("honored", "pooled temp-space slots "
                            "(resource.py)"),
    "MXNET_BACKWARD_DO_MIRROR": ("honored", "rematerialise the forward in "
                                 "the fused fwd+bwd program "
                                 "(jax.checkpoint)"),
    "MXNET_MODULE_FUSED_STEP": ("honored", "Module.fit/fused_step compile "
                                "forward+backward+optimizer+metric into "
                                "ONE donated-buffer XLA program — on a "
                                "multi-context dp mesh ONE SPMD program "
                                "with the gradient all-reduce inside "
                                "(in-process kvstores subsumed). Default "
                                "on; =0 pins the phase-split path — the "
                                "PERF.md \"Module.fit gap\" A/B)"),
    "MXNET_FUSED_BN_ADD_RELU": ("honored", "model-zoo ResNet V1 block "
                                "tails run the fused "
                                "_contrib_BatchNormAddReLU op "
                                "(gluon/model_zoo/vision/resnet.py; "
                                "A/B in PERF.md)"),
    "MXNET_CONV_S2D_STEM": ("honored", "space-to-depth rewrite of the "
                            "channels-last 7x7/s2 stem conv (ops/nn.py; "
                            "default on, =0 for the PERF.md A/B)"),
    # executor
    "MXNET_EXEC_BULK_EXEC_TRAIN": ("mapped", "whole-graph jit IS maximal "
                                   "op bulking"),
    "MXNET_EXEC_BULK_EXEC_INFERENCE": ("mapped", "whole-graph jit"),
    "MXNET_EXEC_BULK_EXEC_MAX_NODE_TRAIN": ("mapped", "whole-graph jit"),
    "MXNET_EXEC_INPLACE_GRAD_SUM_CAP": ("mapped", "XLA memory planning"),
    "MXNET_EXEC_VERBOSE_LOGGING": ("mapped", "use jax logging / "
                                   "dump_jaxpr"),
    # kvstore
    "MXNET_KVSTORE_REDUCTION_NTHREADS": ("mapped", "XLA collectives own "
                                         "the reduction"),
    "MXNET_KVSTORE_BIGARRAY_BOUND": ("mapped", "no key->server striping; "
                                     "all-reduce shards by mesh"),
    "MXNET_KVSTORE_SERIAL_PUSH": ("mapped", "batched pushes run as one "
                                  "jitted collective"),
    "MXNET_ENABLE_GPU_P2P": ("mapped", "ICI links replace CUDA P2P"),
    # cudnn / tuning
    "MXNET_CUDNN_AUTOTUNE_DEFAULT": ("mapped", "XLA autotunes"),
    "MXNET_CUDA_ALLOW_TENSOR_CORE": ("mapped", "MXU is always on; "
                                     "precision via jax matmul precision"),
    "MXNET_USE_OPERATOR_TUNING": ("honored", "mxnet_tpu.tuner measures "
                                  "dispatch-level candidates (Pallas "
                                  "meta-params); XLA autotunes inside "
                                  "programs"),
    "MXNET_OUTPUT_TUNING_DATA": ("honored", "log tuner measurements"),
    "MXNET_TUNING_CACHE": ("honored", "persist tuner decisions (JSON)"),
    "MXNET_TUNING_REPEAT": ("honored", "timed runs per tuner candidate"),
    # storage / sparse
    "MXNET_STORAGE_FALLBACK_LOG_VERBOSE": ("honored", "warn on sparse -> "
                                           "dense fallbacks"),
    "MXNET_INFER_STORAGE_TYPE_VERBOSE_LOGGING": ("mapped", "storage types "
                                                 "are explicit here"),
    # profiler / telemetry
    "MXNET_PROFILER_AUTOSTART": ("honored", "start the profiler at import"),
    "MXNET_PROFILER_MODE": ("honored", "profiler.py set_config"),
    "MXNET_TELEMETRY": ("honored", "runtime telemetry registry (dispatch/"
                        "jit/fallback/transfer counters + host-span "
                        "tracing, telemetry.py); default on, =0 starts "
                        "disabled — the <2% overhead A/B pin"),
    # io
    "MXNET_CPU_TEMP_COPY": ("mapped", "PJRT staging buffers"),
    # distributed wiring (reference ps-lite envs, kvstore.h:254)
    "DMLC_ROLE": ("honored", "exported by tools/launch.py"),
    "DMLC_NUM_WORKER": ("honored", "worker count fallback (kvstore.py)"),
    "DMLC_RANK": ("honored", "rank fallback (kvstore.py)"),
    "DMLC_PS_ROOT_URI": ("mapped", "jax.distributed coordinator address "
                         "(MXNET_TPU_COORDINATOR)"),
    "DMLC_PS_ROOT_PORT": ("mapped", "jax.distributed coordinator address"),
    "MXNET_ENFORCE_DETERMINISM": ("mapped", "TPU execution is "
                                  "deterministic by default"),
}


def list_knobs():
    """All registered knobs: {name: (disposition, description, value)}."""
    return {k: (d, desc, os.environ.get(k))
            for k, (d, desc) in sorted(_KNOBS.items())}


def do_mirror():
    """MXNET_BACKWARD_DO_MIRROR: rematerialise the forward during the
    backward pass (reference graph_executor.cc:282-305)."""
    return bool(get_env("MXNET_BACKWARD_DO_MIRROR", 0, int))


def fused_fit():
    """MXNET_MODULE_FUSED_STEP: whole-train-step fusion in Module.fit /
    Module.fused_step (forward+backward+optimizer+metric as one donated
    XLA program). Default on; =0 pins the phase-split path (the
    correctness oracle and the PERF.md A/B baseline)."""
    return bool(get_env("MXNET_MODULE_FUSED_STEP", 1, int))


_fallback_logged = set()


def storage_fallback_log(what):
    """Warn (once per site) when a sparse op computes via its dense view
    (parity: MXNET_STORAGE_FALLBACK_LOG_VERBOSE, src/common/utils.h)."""
    if not get_env("MXNET_STORAGE_FALLBACK_LOG_VERBOSE", 0, int):
        return
    if what in _fallback_logged:
        return
    _fallback_logged.add(what)
    logging.getLogger("mxnet_tpu").warning(
        "storage fallback: %s computes via its dense view", what)


def _autostart_profiler():
    if get_env("MXNET_PROFILER_AUTOSTART", 0, int):
        from . import profiler
        profiler.set_state("run")


_autostart_profiler()
