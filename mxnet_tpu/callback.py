"""Training callbacks (parity: python/mxnet/callback.py)."""
from __future__ import annotations

import logging
import math
import time


def module_checkpoint(mod, prefix, period=1, save_optimizer_states=False):
    """(parity: callback.module_checkpoint)"""
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            mod.save_checkpoint(prefix, iter_no + 1, save_optimizer_states)
    return _callback


def do_checkpoint(prefix, period=1):
    """Epoch-end checkpoint callback (parity: callback.do_checkpoint)."""
    from .model import save_checkpoint
    period = int(max(1, period))

    def _callback(iter_no, sym, arg, aux):
        if (iter_no + 1) % period == 0:
            save_checkpoint(prefix, iter_no + 1, sym, arg, aux)
    return _callback


def log_train_metric(period, auto_reset=False):
    def _callback(param):
        if param.nbatch % period == 0 and param.eval_metric is not None:
            name_value = param.eval_metric.get_name_value()
            for name, value in name_value:
                logging.info("Iter[%d] Batch[%d] Train-%s=%f",
                             param.epoch, param.nbatch, name, value)
            if auto_reset:
                param.eval_metric.reset()
    return _callback


class Speedometer:
    """Log samples/sec every `frequent` batches (parity: callback.Speedometer)."""

    def __init__(self, batch_size, frequent=50, auto_reset=True):
        self.batch_size = batch_size
        self.frequent = frequent
        self.init = False
        self.tic = 0
        self.last_count = 0
        self.auto_reset = auto_reset

    def __call__(self, param):
        count = param.nbatch
        if self.last_count > count:
            self.init = False
        self.last_count = count
        if self.init:
            if count % self.frequent == 0:
                speed = self.frequent * self.batch_size / (time.time() - self.tic)
                if param.eval_metric is not None:
                    name_value = param.eval_metric.get_name_value()
                    if self.auto_reset:
                        param.eval_metric.reset()
                    msg = "Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec" \
                        % (param.epoch, count, speed)
                    msg += "".join("\t%s=%f" % kv for kv in name_value)
                    logging.info(msg)
                else:
                    logging.info("Iter[%d] Batch [%d]\tSpeed: %.2f samples/sec",
                                 param.epoch, count, speed)
                self.tic = time.time()
        else:
            self.init = True
            self.tic = time.time()


class TelemetryLogger:
    """Speedometer-style batch-end callback over the telemetry registry:
    every ``frequent`` batches, log the window's jitted-program
    dispatches per batch, jit compiles vs. cache hits, fused-fallback
    events, host->device bytes, blocking host syncs, and the step-span
    p50/p95/p99 — the counters PERF.md wants attached to every training
    run (no reference counterpart; the reference had no host-side
    registry to read)::

        mod.fit(train, batch_end_callback=mx.callback.TelemetryLogger(50))

    ``programs=True`` additionally logs every NEW program card the
    moment it appears in ``telemetry.programs()`` — entry, kind,
    trace/compile wall-time, cost-model GFLOPs and peak HBM — so a
    recompile mid-training is visible in the training log, next to the
    recompile-cause warning the executor emits::

        mod.fit(train, batch_end_callback=mx.callback.TelemetryLogger(
            50, programs=True))

    The same object also understands the SERVING registry: hand it to a
    ``serving.InferenceEngine`` and every ``frequent`` coalesced batches
    it logs queue depth, batch-fill ratio, pad bytes and the request
    p50/p95/p99 latency window (``log_serving``)::

        engine = mx.serving.InferenceEngine(
            sym, params, {"data": (1, 3, 224, 224)},
            telemetry_logger=mx.callback.TelemetryLogger(100))
    """

    def __init__(self, frequent=50, logger=None, programs=False):
        from . import telemetry
        self.frequent = int(max(1, frequent))
        self.logger = logger or logging.getLogger("mxnet_tpu.telemetry")
        self._telemetry = telemetry
        self._last_counters = {}
        self._last_nbatch = None
        self._last_step_total = 0
        self._programs = bool(programs)
        self._seen_programs = set()
        self._last_serving = None
        self._last_serve_total = 0
        self._last_decode = None
        self._last_decode_ts = None
        self._last_decode_total = 0
        self._last_series_ts = None
        self._tag = None

    def _rank_tag(self):
        """``"[r<N>] "`` prefix on every log line of a multi-process
        run — N ranks tail into ONE launcher stream, and an unprefixed
        "step p99 spiked" line is unattributable exactly when it
        matters. Cached: identity is fixed for the process lifetime;
        single-process runs stay untagged."""
        if self._tag is None:
            ident = self._telemetry.process_identity()
            self._tag = ("[r%d] " % ident["rank"]
                         if ident["num_processes"] > 1 else "")
        return self._tag

    def _rebase(self, count):
        self._last_counters = self._telemetry.counters()
        self._last_step_total = self._telemetry.span_count("step")
        self._last_nbatch = count
        self._window_start = count

    def _window(self):
        cur = self._telemetry.counters()
        if any(v < self._last_counters.get(k, 0) for k, v in cur.items()) \
                or any(k not in cur for k in self._last_counters):
            # someone reset() the registry mid-window: the deltas are
            # meaningless — skip this log line and rebase
            self._last_counters = cur
            return None
        delta = {k: v - self._last_counters.get(k, 0)
                 for k, v in cur.items()
                 if v != self._last_counters.get(k, 0)}
        self._last_counters = cur
        return delta

    def _log_new_programs(self):
        """Report cards not seen before (cheap: one registry read per
        callback, and new cards only appear on compiles)."""
        for key, card in self._telemetry.programs().items():
            if key in self._seen_programs:
                continue
            self._seen_programs.add(key)
            flops = card.get("flops")
            peak = card.get("peak_bytes")
            self.logger.info(
                self._rank_tag() +
                "program card %s: kind=%s trace=%.1fms compile=%.1fms "
                "flops=%s peak_hbm=%s donated=%d",
                key, card.get("kind"),
                card.get("trace_ms") or 0.0, card.get("compile_ms") or 0.0,
                "%.4g" % flops if flops else None,
                "%.2fMiB" % (peak / 2.0 ** 20) if peak else None,
                len(card.get("donated") or ()))

    def log_serving(self, force=False):
        """One serving-window log line (queue depth, batch fill, request
        p50/p95/p99): a running ``serving.InferenceEngine`` built with
        ``telemetry_logger=`` calls this after every coalesced batch;
        every ``frequent`` batches one line lands. ``force=True`` (the
        engine's close()) flushes a final partial window. Reads the same
        process-global telemetry registry as the training path — the
        ``serving.*`` counters and ``serve_request`` spans."""
        t = self._telemetry
        cur = t.counters()
        batches = cur.get("serving.batches", 0)
        if self._last_serving is None:
            # first look: establish the window baseline
            self._last_serving = cur
            self._last_serve_total = t.span_count("serve_request")
            if not force:
                return
        last = self._last_serving
        nb = batches - last.get("serving.batches", 0)
        if nb < 0:          # someone reset() the registry mid-window
            self._last_serving = cur
            self._last_serve_total = t.span_count("serve_request")
            return
        if not force and nb < self.frequent:
            return
        if nb == 0 and not force:
            return
        self._last_serving = cur
        delta = {k: v - last.get(k, 0) for k, v in cur.items()
                 if k.startswith("serving.")}
        if self._programs:
            self._log_new_programs()
        rows = delta.get("serving.batch_rows", 0)
        pad = delta.get("serving.pad_rows", 0)
        # admitted-but-unterminated: the ONE shared formula (same
        # depth InferenceEngine.stats() and the flight sampler report)
        depth = t.serving_queue_depth(cur)
        # request-latency percentiles over THIS window's samples only
        durs = t.span_durations("serve_request")
        total = t.span_count("serve_request")
        k = min(max(total - self._last_serve_total, 0), len(durs))
        self._last_serve_total = total
        window = sorted(durs[-k:]) if k else []
        msg = ("serving: batches=%d requests=%d queue_depth=%d"
               % (nb, delta.get("serving.requests", 0), depth))
        if rows + pad:
            msg += "\tbatch_fill=%.2f" % (rows / float(rows + pad))
        if window:
            pct = t._percentile            # the ONE percentile rule
            msg += "\treq p50/p95/p99=%.2f/%.2f/%.2fms" % (
                pct(window, 50) * 1e3, pct(window, 95) * 1e3,
                pct(window, 99) * 1e3)
        pad_b = delta.get("serving.pad_bytes", 0)
        if pad_b:
            msg += "\tpad=%.1fKiB" % (pad_b / 1024.0)
        # overload-control window: shed/retry/breaker events are the
        # degradation signal an operator tails the log for
        shed = delta.get("serving.shed_requests", 0)
        if shed:
            msg += "\tshed=%d" % shed
        retries = delta.get("serving.retries", 0)
        if retries:
            msg += "\tretries=%d" % retries
        trips = delta.get("serving.breaker_trips", 0)
        if trips:
            msg += "\tbreaker_trips=%d" % trips
        self.logger.info(self._rank_tag() + msg)

    def log_decode(self, engine=None, force=False):
        """One decode-window log line (tokens/s, active slots, slot-pool
        fill, per-token p50/p95/p99): a running ``decode.DecodeEngine``
        built with ``telemetry_logger=`` calls this after every decode
        step; every ``frequent`` steps one line lands. ``force=True``
        (the engine's close()) flushes a final partial window. Reads
        the ``decode.*`` counters and ``serve_decode_step`` spans from
        the same process-global registry as everything else; ``engine``
        (when given) contributes the instantaneous slot occupancy."""
        import time as _time
        t = self._telemetry
        cur = t.counters()
        steps = cur.get("decode.steps", 0)
        now = _time.monotonic()
        if self._last_decode is None:
            self._last_decode = cur
            self._last_decode_ts = now
            self._last_decode_total = t.span_count("serve_decode_step")
            if not force:
                return
        last = self._last_decode
        ns = steps - last.get("decode.steps", 0)
        if ns < 0:          # someone reset() the registry mid-window
            self._last_decode = cur
            self._last_decode_ts = now
            self._last_decode_total = t.span_count("serve_decode_step")
            return
        if not force and ns < self.frequent:
            return
        if ns == 0 and not force:
            return
        elapsed = max(now - (self._last_decode_ts or now), 1e-9)
        self._last_decode = cur
        self._last_decode_ts = now
        delta = {k: v - last.get(k, 0) for k, v in cur.items()
                 if k.startswith("decode.")}
        if self._programs:
            self._log_new_programs()
        tokens = delta.get("decode.tokens", 0)
        msg = ("decode: steps=%d tokens=%d tok/s=%.1f"
               % (ns, tokens, tokens / elapsed))
        # mean decode batch over the window = tokens per step; with the
        # engine at hand the INSTANTANEOUS occupancy rides along too
        if ns:
            msg += "\tmean_batch=%.2f" % (tokens / float(ns))
        if engine is not None:
            ov = engine.overload_state()
            slots = ov.get("slots") or 1
            msg += "\tactive_slots=%d/%d fill=%.2f" % (
                ov.get("active_slots", 0), slots,
                ov.get("active_slots", 0) / float(slots))
        # per-token percentiles over THIS window's step spans only
        durs = t.span_durations("serve_decode_step")
        total = t.span_count("serve_decode_step")
        k = min(max(total - self._last_decode_total, 0), len(durs))
        self._last_decode_total = total
        window = sorted(durs[-k:]) if k else []
        if window:
            pct = t._percentile            # the ONE percentile rule
            msg += "\ttok p50/p95/p99=%.2f/%.2f/%.2fms" % (
                pct(window, 50) * 1e3, pct(window, 95) * 1e3,
                pct(window, 99) * 1e3)
        shed = delta.get("decode.shed", 0)
        if shed:
            msg += "\tshed=%d" % shed
        retries = delta.get("decode.retries", 0)
        if retries:
            msg += "\tretries=%d" % retries
        trips = delta.get("decode.breaker_trips", 0)
        if trips:
            msg += "\tbreaker_trips=%d" % trips
        self.logger.info(self._rank_tag() + msg)

    def log_series(self, force=False):
        """One RATE log line from the flight recorder's sampler ring
        (``mxnet_tpu/flight.py``) — req/s, sheds/s, dispatches/s and
        the online MFU over the samples that landed since the last
        call — instead of re-snapshotting the cumulative counters and
        diffing them here: the sampler already banked the deltas on its
        own clock, so this reads (not recomputes) the trajectory.
        Nothing is logged until a new sample lands (``force=True``
        logs whatever the newest sample says). Needs
        ``flight.sampler_start()`` (or ``MXNET_METRICS_INTERVAL_MS``)
        — without a running sampler this is a silent no-op."""
        from . import flight
        samples = flight.series()
        if self._last_series_ts is not None:
            samples = [s for s in samples
                       if s["ts"] > self._last_series_ts]
        if not samples:
            if force and flight.series(1):
                samples = flight.series(1)
            else:
                return
        self._last_series_ts = samples[-1]["ts"]
        dt = sum(s.get("dt_ms", 0.0) for s in samples) / 1e3
        if dt <= 0:
            return

        def rate(key):
            total = sum(s.get("counters", {}).get(key, 0)
                        for s in samples)
            return total / dt

        last = samples[-1]
        msg = ("series: window=%.1fs req/s=%.1f shed/s=%.1f "
               "dispatch/s=%.1f queue_depth=%d"
               % (dt, rate("serving.requests"),
                  rate("serving.shed_requests"),
                  sum(sum(v for k, v in s.get("counters", {}).items()
                          if k.startswith("dispatch."))
                      for s in samples) / dt,
                  last.get("queue_depth", 0)))
        mfu = last.get("mfu")
        if mfu is not None:
            msg += "\tmfu=%.4g" % mfu
        if last.get("serving", {}).get("breaker_open"):
            msg += "\tbreaker=OPEN"
        self.logger.info(self._rank_tag() + msg)

    def __call__(self, param):
        if self._programs:
            self._log_new_programs()
        count = param.nbatch
        if self._last_nbatch is None or count < self._last_nbatch:
            # first call of an epoch (fit fires batch-end at nbatch=0,
            # Speedometer-style): establish the window baseline — a
            # partial first window would misreport every per-batch rate
            self._rebase(count)
            return
        self._last_nbatch = count
        # the window spans everything since the last LOG (or rebase),
        # not since the last callback — skipped callbacks must not
        # shrink the per-batch denominator
        nbatches = count - self._window_start
        if count % self.frequent != 0 or nbatches <= 0:
            return
        self._window_start = count
        delta = self._window()
        if delta is None:
            self._last_step_total = self._telemetry.span_count("step")
            return
        n = float(nbatches)
        dispatches = sum(v for k, v in delta.items()
                         if k.startswith("dispatch."))
        fallbacks = {k[len("fused_fallback."):]: v
                     for k, v in delta.items()
                     if k.startswith("fused_fallback.")}
        # step percentiles over THIS WINDOW's samples only (the
        # cumulative histogram would keep the first batch's compile
        # outlier in p99 forever)
        durs = self._telemetry.span_durations("step")
        total = self._telemetry.span_count("step")
        k = min(max(total - self._last_step_total, 0), len(durs))
        self._last_step_total = total
        window = sorted(durs[-k:]) if k else []
        msg = ("Epoch[%d] Batch [%d]\tdispatches/batch=%.2f"
               % (param.epoch, count, dispatches / n))
        msg += "\tjit compile/hit=%d/%d" % (
            delta.get("jit.compile", 0), delta.get("jit.hit", 0))
        if window:
            pct = self._telemetry._percentile    # the ONE percentile rule
            msg += "\tstep p50/p95/p99=%.2f/%.2f/%.2fms" % (
                pct(window, 50) * 1e3, pct(window, 95) * 1e3,
                pct(window, 99) * 1e3)
        h2d = delta.get("transfer.h2d_bytes", 0)
        if h2d:
            msg += "\th2d=%.1fKiB/batch" % (h2d / 1024.0 / n)
        syncs = delta.get("host_sync.blocking", 0)
        if syncs:
            msg += "\tblocking_syncs=%d" % syncs
        # collective gate wait this window (ISSUE 18): the per-rank
        # view of fleet skew — a rank whose gate_wait/batch is high is
        # WAITING on a straggler; the straggler's own is ~0
        gate_ms = sum(v for k, v in delta.items()
                      if k.startswith("heartbeat.gate_wait_ms."))
        if gate_ms:
            msg += "\tgate_wait=%.1fms/batch" % (gate_ms / n)
        if fallbacks:
            msg += "\tfused_fallbacks=%s" % (
                ",".join("%s:%d" % kv for kv in sorted(fallbacks.items())))
        self.logger.info(self._rank_tag() + msg)


class ProgressBar:
    """(parity: callback.ProgressBar)"""

    def __init__(self, total, length=80):
        self.bar_len = length
        self.total = total

    def __call__(self, param):
        count = param.nbatch
        filled_len = int(round(self.bar_len * count / float(self.total)))
        percents = math.ceil(100.0 * count / float(self.total))
        prog_bar = "=" * filled_len + "-" * (self.bar_len - filled_len)
        logging.info("[%s] %s%s", prog_bar, percents, "%")


class LogValidationMetricsCallback:
    """Log eval metrics at the end of each epoch (parity:
    callback.LogValidationMetricsCallback)."""

    def __call__(self, param):
        if not param.eval_metric:
            return
        for name, value in param.eval_metric.get_name_value():
            logging.info("Epoch[%d] Validation-%s=%f", param.epoch, name,
                         value)
