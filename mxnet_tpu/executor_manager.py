"""Legacy executor manager (parity: python/mxnet/executor_manager.py).

The reference's oldest data-parallel layer: FeedForward used
``DataParallelExecutorManager`` to keep one executor per GPU and split
each batch by ``_split_input_slice``. TPU-native: data parallelism is a
sharding of ONE program over the mesh (mxnet_tpu.parallel), so this
manager delegates to a single bound executor; the slicing helpers keep
their exact reference semantics for callers that use them directly.
"""
from __future__ import annotations

import logging

import numpy as np

from .base import MXNetError
from . import ndarray as nd

__all__ = ["DataParallelExecutorGroup", "DataParallelExecutorManager",
           "_split_input_slice", "_check_arguments", "_load_data",
           "_load_label", "_load_general"]


def _split_input_slice(batch_size, work_load_list):
    """Get input slice from the input shape (parity:
    executor_manager.py:31).

    Raises ValueError when there are two many slices such that some
    slice can be empty.
    """
    total_work_load = sum(work_load_list)
    batch_num_list = [round(batch_size * item / total_work_load)
                      for item in work_load_list]
    batch_num_sum = sum(batch_num_list)
    if batch_num_sum < batch_size:
        batch_num_list[-1] += batch_size - batch_num_sum
    slices = []
    end = 0
    for batch_num in batch_num_list:
        begin = int(min(end, batch_size))
        end = int(min(begin + batch_num, batch_size))
        if begin >= end:
            raise ValueError("Too many slices. Some splits are empty.")
        slices.append(slice(begin, end))
    return slices


def _check_arguments(symbol):
    """Check the argument names of symbol: arguments and auxiliary states
    must each be distinct (parity: executor_manager.py:68)."""
    arg_set = set()
    arg_names = symbol.list_arguments()
    for name in arg_names:
        if name in arg_set:
            raise ValueError(
                "Find duplicated argument name \"%s\", please make the "
                "weight name non-duplicated(using name arguments), "
                "arguments are %s" % (name, str(arg_names)))
        arg_set.add(name)
    aux_set = set()
    aux_names = symbol.list_auxiliary_states()
    for name in aux_names:
        if name in aux_set:
            raise ValueError(
                "Find duplicated auxiliary param name \"%s\", please make "
                "the weight name non-duplicated(using name arguments), "
                "aux states are %s" % (name, str(aux_names)))
        aux_set.add(name)


def _load_general(data, targets):
    """Load a list of arrays into a list of arrays specified by slices."""
    for d_src, d_targets in zip(data, targets):
        if isinstance(d_targets, nd.NDArray):
            d_src.copyto(d_targets)
        else:
            for slice_idx, d_dst in d_targets:
                d_src[slice_idx].copyto(d_dst)


def _load_data(batch, targets):
    _load_general(batch.data, targets)


def _load_label(batch, targets):
    _load_general(batch.label, targets)


class DataParallelExecutorGroup:
    """A group of executors living on one logical device set (parity:
    executor_manager.py:204). On TPU this is one sharded executor."""

    def __init__(self, sym, arg_names, param_names, ctx, slices, train_data,
                 shared_group=None):
        _check_arguments(sym)
        self.ctx = ctx
        self.param_names = param_names
        self.arg_names = arg_names
        shapes = {name: shape for name, shape in
                  list(train_data.provide_data) +
                  list(train_data.provide_label or [])}
        grad_req = {name: ("write" if name in param_names else "null")
                    for name in arg_names}
        self.train_exec = sym.simple_bind(ctx=ctx[0], grad_req=grad_req,
                                          **shapes)
        self.data_names = [d[0] for d in train_data.provide_data]
        self.label_names = [l[0] for l in (train_data.provide_label or [])]
        self.param_arrays = [self.train_exec.arg_dict[name]
                             for name in param_names]
        self.grad_arrays = [self.train_exec.grad_dict[name]
                            for name in param_names]
        self.aux_arrays = list(self.train_exec.aux_arrays)
        self.slices = slices

    def load_data_batch(self, data_batch):
        for name, arr in zip(self.data_names, data_batch.data):
            arr.copyto(self.train_exec.arg_dict[name])
        for name, arr in zip(self.label_names, data_batch.label or []):
            arr.copyto(self.train_exec.arg_dict[name])

    def forward(self, is_train=False):
        self.train_exec.forward(is_train=is_train)

    def backward(self):
        self.train_exec.backward()

    def update_metric(self, metric, labels):
        metric.update(labels, self.train_exec.outputs)


class DataParallelExecutorManager:
    """Helper to manage data-parallel training (parity:
    executor_manager.py:295). One sharded executor on TPU."""

    def __init__(self, symbol, ctx, train_data, arg_names, param_names,
                 aux_names, work_load_list=None, logger=None,
                 sym_gen=None):
        if logger is None:
            logger = logging
        num_device = len(ctx)
        logger.info("Start training with %s", str(ctx))
        if work_load_list is None:
            work_load_list = [1] * num_device
        assert isinstance(work_load_list, list) and \
            len(work_load_list) == num_device, \
            "Invalid settings for work load."
        batch_size = train_data.batch_size
        self.slices = _split_input_slice(batch_size, work_load_list)
        self.arg_names = arg_names
        self.param_names = param_names
        self.aux_names = aux_names
        self.ctx = ctx
        self.execgrp = DataParallelExecutorGroup(
            symbol, self.arg_names, self.param_names, self.ctx,
            self.slices, train_data)
        self.symbol = symbol
        self.sym_gen = sym_gen
        self.curr_execgrp = self.execgrp
        self.execgrp_bucket = {}

    def install_monitor(self, monitor):
        monitor.install(self.curr_execgrp.train_exec)

    def set_params(self, arg_params, aux_params):
        exec_ = self.curr_execgrp.train_exec
        for name, arr in arg_params.items():
            if name in exec_.arg_dict:
                arr.copyto(exec_.arg_dict[name])
        for name, arr in aux_params.items():
            if name in exec_.aux_dict:
                arr.copyto(exec_.aux_dict[name])

    def copy_to(self, arg_params, aux_params):
        """Copy fitted parameters out (parity: executor_manager.py:374)."""
        for name in self.param_names:
            arg_params[name] = \
                self.curr_execgrp.train_exec.arg_dict[name].copy()
        for name in self.aux_names:
            aux_params[name] = \
                self.curr_execgrp.train_exec.aux_dict[name].copy()

    @property
    def param_arrays(self):
        # wrap in a list-of-lists: reference keeps one array per device
        return [[a] for a in self.curr_execgrp.param_arrays]

    @property
    def grad_arrays(self):
        return [[g] for g in self.curr_execgrp.grad_arrays]

    @property
    def aux_arrays(self):
        return [[a] for a in self.curr_execgrp.aux_arrays]

    def load_data_batch(self, data_batch):
        if self.sym_gen is not None:
            key = getattr(data_batch, "bucket_key", None)
            if key is not None and key not in self.execgrp_bucket:
                symbol = self.sym_gen(key)
                self.execgrp_bucket[key] = DataParallelExecutorGroup(
                    symbol, self.arg_names, self.param_names, self.ctx,
                    self.slices, data_batch)
            if key is not None:
                self.curr_execgrp = self.execgrp_bucket[key]
        else:
            self.curr_execgrp = self.execgrp
        self.curr_execgrp.load_data_batch(data_batch)

    def forward(self, is_train=False):
        self.curr_execgrp.forward(is_train=is_train)

    def backward(self):
        self.curr_execgrp.backward()

    def update_metric(self, metric, labels):
        self.curr_execgrp.update_metric(metric, labels)
