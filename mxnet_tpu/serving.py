"""High-throughput inference serving: bucketed AOT forward programs +
a dynamic micro-batching engine.

No reference counterpart — the reference's deployment surface
(c_predict_api.cc, one synchronous executor per client) predates
serving-scale inference. The TPU-native design follows the compiled-
program serving playbook (TVM arXiv:1802.04799, Julia-to-TPU
arXiv:1810.09868): pin the abstract signature, compile once, dispatch
many. Concretely:

* **bucketed AOT forward programs** — batch-dimension buckets (powers
  of two up to ``max_batch``), each compiled ONCE through the
  executor's instrumented wrapper (``executor._InstrumentedProgram``),
  so every bucket gets a program card in ``telemetry.programs()``,
  recompile diagnosis and ledger accounting for free. Parameters are
  committed device-resident once and shared by all buckets; the
  ``_GraphProgram`` is shared with any ``Predictor`` over the same
  symbol (``Predictor.reshape`` rides the same cache — no re-trace).

* **a dynamic micro-batcher** — ``submit()`` enqueues a request and
  returns a ``concurrent.futures.Future``; a coalescer thread packs
  pending requests into the smallest covering bucket (padding the
  remainder with zeros), flushes when the pending rows fill
  ``max_batch`` OR a ``max_wait_ms`` deadline expires, dispatches the
  program asynchronously with up to ``max_inflight`` batches in
  flight, and a resolver pool slices the padded output back into
  per-request results after the (blocking) device-to-host fetch.

* **telemetry** — counters ``serving.requests`` / ``serving.rows`` /
  ``serving.batches`` / ``serving.batch_rows`` / ``serving.pad_rows``
  / ``serving.pad_bytes`` / ``serving.resolved`` and the
  ``serve_wait`` / ``serve_batch`` / ``serve_d2h`` /
  ``serve_request`` spans (``telemetry.SERVE_SPANS``), so one
  ``telemetry.snapshot()`` reports request p50/p95/p99 latency next
  to throughput and the per-bucket program cards.

Every graph output must be batch-major (dim 0 = batch) — true of the
whole symbol zoo; the padded rows are sliced off before a future
resolves, so callers never see them.
"""
from __future__ import annotations

import collections
import queue
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor

import numpy as np

import jax

from .base import MXNetError
from . import telemetry
from .executor import record_dispatch
from .predictor import Predictor

__all__ = ["InferenceEngine", "bucket_sizes"]


def bucket_sizes(max_batch):
    """The power-of-two batch buckets up to ``max_batch`` (inclusive;
    ``max_batch`` itself is always a bucket so a full batch never pads)."""
    max_batch = int(max_batch)
    if max_batch < 1:
        raise MXNetError("max_batch must be >= 1, got %d" % max_batch)
    sizes, b = [], 1
    while b < max_batch:
        sizes.append(b)
        b *= 2
    sizes.append(max_batch)
    return sizes


class _Request:
    __slots__ = ("arrays", "rows", "future", "wait_span", "req_span")

    def __init__(self, arrays, rows):
        self.arrays = arrays          # {input name: np.ndarray (rows,...)}
        self.rows = rows
        self.future = Future()
        # spans are entered on the submitting thread and closed on the
        # coalescer / resolver threads — _Span carries its own t0
        self.wait_span = telemetry.span("serve_wait").__enter__()
        self.req_span = telemetry.span("serve_request").__enter__()


_FLUSH = object()
_SHUTDOWN = object()


class InferenceEngine:
    """Dynamic micro-batching over bucketed AOT forward programs.

    Parameters
    ----------
    symbol : Symbol | str — graph (or its JSON), as for ``Predictor``
    params : dict | bytes | str — ``arg:``/``aux:`` blob, as for
        ``Predictor``
    input_shapes : dict name -> shape — per-input shape; dim 0 is the
        batch dimension (its value only seeds shape inference, requests
        may carry any row count up to ``max_batch``)
    ctx : Context — device (default: current context)
    max_batch : int — largest batch one program serves; buckets are the
        powers of two up to it
    max_wait_ms : float — coalescing deadline: a pending request waits
        at most this long for co-batchable traffic before a partial
        bucket is flushed
    max_inflight : int — dispatched-but-unresolved batch bound (the
        device-queue depth the coalescer may run ahead)
    dtype : optional input dtype override (e.g. bfloat16), as for
        ``Predictor``
    warmup : bool — compile every bucket at construction (AOT); with
        ``False`` buckets compile on first use
    telemetry_logger : optional ``callback.TelemetryLogger`` — the
        engine calls its ``log_serving()`` after every batch so a
        running engine logs queue depth / fill / p95 periodically
    predictor : optional existing ``Predictor`` to share programs and
        device-resident parameters with (``symbol``/``params``/
        ``input_shapes`` are then taken from it)
    """

    def __init__(self, symbol=None, params=None, input_shapes=None,
                 ctx=None, max_batch=32, max_wait_ms=2.0, max_inflight=2,
                 dtype=None, warmup=True, telemetry_logger=None,
                 predictor=None):
        if predictor is None:
            if symbol is None or input_shapes is None:
                raise MXNetError("InferenceEngine needs (symbol, params, "
                                 "input_shapes) or predictor=")
            predictor = Predictor(symbol, params or {}, input_shapes,
                                  ctx=ctx, dtype=dtype)
        self._predictor = predictor
        ex = predictor._executor
        self._prog = ex._prog
        if self._prog.node_devices:
            raise MXNetError("serving: grouped (group2ctx) programs run "
                             "eagerly per segment and cannot be bucketed")
        self._symbol = predictor._symbol
        self._ctx = ex._ctx
        self._device = self._ctx.jax_device()
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.buckets = bucket_sizes(self.max_batch)
        self._input_names = list(predictor._input_names)
        self._row_shapes = {n: tuple(predictor._input_shapes[n][1:])
                            for n in self._input_names}
        self._in_dtypes = {n: np.dtype(ex.arg_dict[n].dtype)
                           for n in self._input_names}
        # params/aux stay device-resident across ALL buckets: the raw
        # arrays of the predictor's bound storage, shared (not copied)
        auto = set(predictor._auto_args)
        self._param_raw = {n: a._data for n, a in ex.arg_dict.items()
                           if n not in self._input_names and n not in auto}
        self._aux_raw = {n: a._data for n, a in ex.aux_dict.items()}
        # inference-time dummies (loss-layer labels) are batch-shaped:
        # one zero set per bucket, built lazily in _bucket_extras
        self._auto_names = sorted(auto)
        self._extras = {}
        self._rng = ex._step_key()
        self._forward = self._prog.forward_fn(False)

        self._logger = telemetry_logger
        self._lock = threading.Lock()
        self._stats = collections.Counter()
        self._bucket_batches = collections.Counter()
        self._q = queue.Queue()
        self._inflight = threading.Semaphore(max(1, int(max_inflight)))
        self._closed = False
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, int(max_inflight)),
            thread_name_prefix="mxtpu-serve-resolve")
        self._thread = threading.Thread(target=self._coalesce_loop,
                                        name="mxtpu-serve-coalesce",
                                        daemon=True)
        self._thread.start()
        if warmup:
            self.warmup()

    # -- program cache ------------------------------------------------------
    def warmup(self):
        """Compile (and execute once, on zeros) every bucket's forward
        program — after this, serving dispatches are all AOT cache hits
        and ``program_cards()`` holds one card per bucket signature.
        The recompile-cause warning is suppressed ONLY for the duration
        (bucket compiles are planned signatures, not a storm); a
        steady-state signature drift afterwards still warns, for this
        engine and for any Predictor sharing the program."""
        prev = getattr(self._forward, "warn_recompile", True)
        if hasattr(self._forward, "warn_recompile"):
            self._forward.warn_recompile = False
        try:
            for b in self.buckets:
                args = dict(self._param_raw)
                for n in self._input_names:
                    args[n] = jax.device_put(
                        np.zeros((b,) + self._row_shapes[n],
                                 self._in_dtypes[n]), self._device)
                args.update(self._bucket_extras(b))
                outs, _ = self._forward(args, self._aux_raw, self._rng)
                for o in outs:
                    o.block_until_ready()
        finally:
            if hasattr(self._forward, "warn_recompile"):
                self._forward.warn_recompile = prev

    def _bucket_extras(self, bucket):
        """Device-resident zero dummies (softmax labels etc.) at this
        bucket's batch size, cached per bucket."""
        cached = self._extras.get(bucket)
        if cached is not None:
            return cached
        extras = {}
        if self._auto_names:
            known = {n: (bucket,) + self._row_shapes[n]
                     for n in self._input_names}
            known.update({n: tuple(v.shape)
                          for n, v in self._param_raw.items()})
            shapes, _, _ = self._symbol.infer_shape_partial(**known)
            inferred = dict(zip(self._symbol.list_arguments(), shapes))
            ex = self._predictor._executor
            for n in self._auto_names:
                shp = inferred.get(n)
                if shp is None:
                    raise MXNetError("serving: cannot infer dummy shape "
                                     "for %r at bucket %d" % (n, bucket))
                extras[n] = jax.device_put(
                    np.zeros(shp, np.dtype(ex.arg_dict[n].dtype)),
                    self._device)
        self._extras[bucket] = extras
        return extras

    def bucket_for(self, rows):
        """Smallest bucket covering ``rows``."""
        for b in self.buckets:
            if b >= rows:
                return b
        raise MXNetError("serving: %d rows exceed max_batch=%d"
                         % (rows, self.max_batch))

    def program_cards(self):
        """{card_id: card} for THIS engine's forward programs — one card
        per compiled (bucket, dtype) signature."""
        entry = getattr(self._forward, "entry", None)
        if entry is None:
            return {}
        return {k: c for k, c in telemetry.programs().items()
                if k == entry or k.startswith(entry + "/")}

    # -- request surface ----------------------------------------------------
    def submit(self, *args, **kwargs):
        """Enqueue one request; returns a Future resolving to the list
        of per-output numpy arrays (each ``(rows, ...)``). Inputs go by
        name (``submit(data=x)``); a single-input graph also accepts one
        positional array. Each input must be ``(rows,) + row_shape``
        with 1 <= rows <= max_batch."""
        if self._closed:                 # fast path; re-checked under
            raise MXNetError("serving: engine is closed")   # the lock
        if args:
            if len(args) != 1 or kwargs or len(self._input_names) != 1:
                raise MXNetError("serving: pass inputs by name "
                                 "(submit(name=array))")
            kwargs = {self._input_names[0]: args[0]}
        if set(kwargs) != set(self._input_names):
            raise MXNetError("serving: inputs %s do not match engine "
                             "inputs %s" % (sorted(kwargs),
                                            sorted(self._input_names)))
        arrays, rows = {}, None
        for n, v in kwargs.items():
            a = np.asarray(getattr(v, "asnumpy", lambda: v)())
            want = self._row_shapes[n]
            if a.shape == want:           # a single row without batch dim
                a = a[None]
            if a.ndim != len(want) + 1 or tuple(a.shape[1:]) != want:
                raise MXNetError(
                    "serving: input %r shape %s != (rows,)+%s"
                    % (n, a.shape, want))
            if rows is None:
                rows = a.shape[0]
            elif a.shape[0] != rows:
                raise MXNetError("serving: inputs disagree on rows")
            arrays[n] = np.ascontiguousarray(
                a.astype(self._in_dtypes[n], copy=False))
        if not rows:
            raise MXNetError("serving: empty request")
        if rows > self.max_batch:
            raise MXNetError("serving: request rows %d exceed max_batch %d"
                             % (rows, self.max_batch))
        req = _Request(arrays, rows)
        # the closed-check and the enqueue share the lock with close()'s
        # flag-set + sentinel-put: a request that passes the check is
        # guaranteed to land BEFORE the shutdown sentinel, so its future
        # always resolves
        with self._lock:
            if self._closed:
                raise MXNetError("serving: engine is closed")
            self._stats["requests"] += 1
            self._stats["rows"] += rows
            self._q.put(req)
        telemetry.counter_inc("serving.requests")
        telemetry.counter_inc("serving.rows", rows)
        return req.future

    def predict(self, *args, **kwargs):
        """Blocking convenience: ``submit(...).result()``."""
        return self.submit(*args, **kwargs).result()

    def flush(self):
        """Ask the coalescer to dispatch whatever is pending now instead
        of waiting out the deadline."""
        self._q.put(_FLUSH)

    def stats(self):
        """Engine-side counters + the request-latency percentiles: what
        a load balancer's health endpoint would export."""
        with self._lock:
            st = dict(self._stats)
        rows = st.get("batch_rows", 0)
        pad = st.get("pad_rows", 0)
        lat = telemetry.span_stats("serve_request").get("serve_request", {})
        return {
            "requests": st.get("requests", 0),
            "resolved": st.get("resolved", 0),
            "queue_depth": st.get("requests", 0) - st.get("resolved", 0),
            "batches": st.get("batches", 0),
            "rows": st.get("rows", 0),
            "pad_rows": pad,
            "pad_bytes": st.get("pad_bytes", 0),
            "batch_fill": round(rows / (rows + pad), 4) if rows + pad
            else None,
            "buckets": {str(k): v for k, v in
                        sorted(self._bucket_batches.items())},
            "latency_ms": {k: lat.get(k) for k in
                           ("p50_ms", "p95_ms", "p99_ms")}
            if lat else None,
        }

    # -- lifecycle ----------------------------------------------------------
    def close(self):
        """Drain and stop: already-submitted requests (queued, pending,
        or in flight) all resolve before close() returns; later
        ``submit`` calls raise."""
        with self._lock:
            if self._closed:
                already = True
            else:
                already = False
                self._closed = True
                self._q.put(_SHUTDOWN)
        if already:
            return
        self._thread.join()
        self._pool.shutdown(wait=True)
        if self._logger is not None:
            try:
                self._logger.log_serving(force=True)
            except Exception:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- coalescer ----------------------------------------------------------
    def _coalesce_loop(self):
        pending, pending_rows = [], 0
        deadline = None

        def dispatch():
            nonlocal pending, pending_rows, deadline
            if pending:
                batch, pending = pending, []
                pending_rows = 0
                deadline = None
                self._dispatch(batch)

        while True:
            if pending:
                try:
                    item = self._q.get(
                        timeout=max(0.0, deadline - time.monotonic()))
                except queue.Empty:
                    dispatch()        # deadline flush under trickle load
                    continue
            else:
                item = self._q.get()
            if item is _SHUTDOWN:
                dispatch()
                self._drain_after_shutdown()
                break
            if item is _FLUSH:
                dispatch()
                continue
            if pending_rows + item.rows > self.max_batch:
                dispatch()            # the new request doesn't fit
            pending.append(item)
            pending_rows += item.rows
            if deadline is None:
                deadline = time.monotonic() + self.max_wait_s
            if pending_rows >= self.max_batch:
                dispatch()

    def _drain_after_shutdown(self):
        """Backstop: submit() enqueues under the same lock close() uses
        to set the flag and post the sentinel, so nothing should land
        behind it — but nothing already enqueued may ever be left
        unresolved, so drain defensively anyway."""
        left = []
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is not _SHUTDOWN and item is not _FLUSH:
                left.append(item)
        while left:
            batch, rows = [], 0
            while left and rows + left[0].rows <= self.max_batch:
                r = left.pop(0)
                batch.append(r)
                rows += r.rows
            self._dispatch(batch)

    def _dispatch(self, reqs):
        """Pack ``reqs`` into the smallest covering bucket, launch the
        bucket's program (async), and hand resolution to the pool."""
        self._inflight.acquire()
        try:
            rows = sum(r.rows for r in reqs)
            bucket = self.bucket_for(rows)
            for r in reqs:
                r.wait_span.__exit__(None, None, None)
            args = dict(self._param_raw)
            pad_bytes = 0
            for n in self._input_names:
                buf = np.zeros((bucket,) + self._row_shapes[n],
                               self._in_dtypes[n])
                off = 0
                for r in reqs:
                    buf[off:off + r.rows] = r.arrays[n]
                    off += r.rows
                pad_bytes += (bucket - rows) * buf[0].nbytes
                telemetry.record_transfer(buf.nbytes)
                args[n] = jax.device_put(buf, self._device)
            args.update(self._bucket_extras(bucket))
            record_dispatch("serve")
            with telemetry.span("serve_batch"):
                outs, _ = self._forward(args, self._aux_raw, self._rng)
            with self._lock:
                self._stats["batches"] += 1
                self._stats["batch_rows"] += rows
                self._stats["pad_rows"] += bucket - rows
                self._stats["pad_bytes"] += pad_bytes
                self._bucket_batches[bucket] += 1
            telemetry.counter_inc("serving.batches")
            telemetry.counter_inc("serving.batch_rows", rows)
            telemetry.counter_inc("serving.pad_rows", bucket - rows)
            telemetry.counter_inc("serving.pad_bytes", pad_bytes)
            self._pool.submit(self._resolve, outs, reqs)
        except BaseException as e:
            self._inflight.release()
            for r in reqs:
                if not r.future.done():
                    r.future.set_exception(e)
        else:
            if self._logger is not None:
                try:
                    self._logger.log_serving()
                except Exception:
                    pass

    def _resolve(self, outs, reqs):
        """Resolver-pool worker: blocking d2h of the whole padded batch,
        then slice each request's rows off and resolve its future."""
        try:
            with telemetry.span("serve_d2h"):
                host = [np.asarray(o) for o in outs]
            off = 0
            for r in reqs:
                sl = [h[off:off + r.rows] for h in host]
                off += r.rows
                r.req_span.__exit__(None, None, None)
                with self._lock:
                    self._stats["resolved"] += 1
                telemetry.counter_inc("serving.resolved")
                r.future.set_result(sl)
        except BaseException as e:
            for r in reqs:
                if not r.future.done():
                    r.future.set_exception(e)
        finally:
            self._inflight.release()
