"""High-throughput inference serving: bucketed AOT forward programs +
a dynamic micro-batching engine.

No reference counterpart — the reference's deployment surface
(c_predict_api.cc, one synchronous executor per client) predates
serving-scale inference. The TPU-native design follows the compiled-
program serving playbook (TVM arXiv:1802.04799, Julia-to-TPU
arXiv:1810.09868): pin the abstract signature, compile once, dispatch
many. Concretely:

* **bucketed AOT forward programs** — batch-dimension buckets (powers
  of two up to ``max_batch``), each compiled ONCE through the
  executor's instrumented wrapper (``executor._InstrumentedProgram``),
  so every bucket gets a program card in ``telemetry.programs()``,
  recompile diagnosis and ledger accounting for free. Parameters are
  committed device-resident once and shared by all buckets; the
  ``_GraphProgram`` is shared with any ``Predictor`` over the same
  symbol (``Predictor.reshape`` rides the same cache — no re-trace).

* **a dynamic micro-batcher** — ``submit()`` enqueues a request and
  returns a ``concurrent.futures.Future``; a coalescer thread packs
  pending requests into the smallest covering bucket (padding the
  remainder with zeros), flushes when the pending rows fill
  ``max_batch`` OR a ``max_wait_ms`` deadline expires, dispatches the
  program asynchronously with up to ``max_inflight`` batches in
  flight, and a resolver pool slices the padded output back into
  per-request results after the (blocking) device-to-host fetch.

* **telemetry** — counters ``serving.requests`` / ``serving.rows`` /
  ``serving.batches`` / ``serving.batch_rows`` / ``serving.pad_rows``
  / ``serving.pad_bytes`` / ``serving.resolved`` and the
  ``serve_wait`` / ``serve_batch`` / ``serve_d2h`` /
  ``serve_request`` spans (``telemetry.SERVE_SPANS``), so one
  ``telemetry.snapshot()`` reports request p50/p95/p99 latency next
  to throughput and the per-bucket program cards.

Every graph output must be batch-major (dim 0 = batch) — true of the
whole symbol zoo; the padded rows are sliced off before a future
resolves, so callers never see them.

**Overload control** (ISSUE 7): production serving melts at the EDGES,
not in the steady state, so the engine degrades deliberately instead of
queuing without bound:

* **bounded admission** — ``max_queue_rows`` caps the rows waiting for
  a bucket; past it, ``overload="shed"`` fails the submit fast with
  :class:`QueueOverflow` (the load balancer's retry-elsewhere signal)
  while ``overload="block"`` applies backpressure to the submitting
  thread (bounded by the request's deadline, if any);
* **deadlines** — ``submit(..., deadline_ms=)`` (or the engine-wide
  ``deadline_ms`` default) is enforced three times: at admission
  (blocked submits give up), at coalesce time (stale requests are shed
  with :class:`DeadlineExceeded` BEFORE they pad a bucket and burn
  device time), and at resolution (a result arriving past its deadline
  resolves the future with ``DeadlineExceeded`` — the client stopped
  caring, delivering late data as success would hide the overload);
* **retry with backoff** — a TRANSIENT dispatch failure (an injected
  ``faults.InjectedFault``, a flaky backend RPC) is retried up to
  ``retry_budget`` times with exponential backoff; program errors
  (shape/dtype/OOM) never retry;
* **a breaker** — ``breaker_threshold`` CONSECUTIVE dispatch failures
  trip the engine into fast-fail (:class:`CircuitOpen` at submit, no
  device work) until ``breaker_reset_s`` elapses and a half-open trial
  batch succeeds — a down backend costs microseconds per request, not
  a timeout each.

Counters: ``serving.shed_requests`` / ``serving.shed_rows`` (with
``serving.shed.admission`` / ``.coalesce`` / ``.resolve`` causes),
``serving.deadline_exceeded``, ``serving.retries``,
``serving.dispatch_failures``, ``serving.breaker_trips``,
``serving.breaker_fastfail`` — all in ``stats()`` and the telemetry
registry, so the chaos lane asserts exact shed/retry trajectories.

**Causal ids** (ISSUE 10): every ``submit()`` stamps a process-unique
``req_id`` (surfaced on the returned future) that rides the request
through coalesce → batch dispatch → d2h → resolve — the request spans
carry it, the batch-level spans carry the member ``req_ids``, sheds
and batch failures land in the telemetry event ring under it, and a
TERMINAL batch failure (retries exhausted / failed fetch) or breaker
trip dumps a flight-recorder postmortem naming the dying batch's
members (``mxnet_tpu/flight.py``; inert without ``MXNET_FLIGHT_DIR``).
"""
from __future__ import annotations

import collections
import contextlib
import itertools
import queue
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor

import numpy as np

import jax

from .base import MXNetError
from . import telemetry
from . import faults
from . import flight
from .executor import record_dispatch, DeviceMemoryError
from .predictor import Predictor

__all__ = ["InferenceEngine", "bucket_sizes", "validate_buckets",
           "DeadlineExceeded", "QueueOverflow", "CircuitOpen",
           "EngineClosed"]


class DeadlineExceeded(MXNetError):
    """The request's deadline passed before a result could be
    delivered (shed in queue, or resolved too late)."""


class QueueOverflow(MXNetError):
    """Admission denied: the bounded queue (``max_queue_rows``) is full
    and the overload policy is ``shed``."""


class CircuitOpen(MXNetError):
    """The dispatch breaker is open (too many consecutive failures) —
    the engine fast-fails instead of queuing onto a dead backend."""


class EngineClosed(MXNetError):
    """``submit``/``flush`` after ``close()``."""


def bucket_sizes(max_batch):
    """The power-of-two batch buckets up to ``max_batch`` (inclusive;
    ``max_batch`` itself is always a bucket so a full batch never pads)."""
    max_batch = int(max_batch)
    if max_batch < 1:
        raise MXNetError("max_batch must be >= 1, got %d" % max_batch)
    sizes, b = [], 1
    while b < max_batch:
        sizes.append(b)
        b *= 2
    sizes.append(max_batch)
    return sizes


def validate_buckets(buckets, max_batch):
    """Normalise a custom bucket set (e.g. an autotuner plan): unique,
    sorted, clamped to [1, max_batch], and always topped by
    ``max_batch`` itself so a full batch never pads and every request
    has a covering bucket."""
    try:
        bs = sorted({int(b) for b in buckets})
    except (TypeError, ValueError):
        raise MXNetError("serving: buckets must be a list of ints, got %r"
                         % (buckets,))
    bs = [b for b in bs if 1 <= b <= max_batch]
    if not bs or bs[-1] != max_batch:
        bs.append(int(max_batch))
    return bs


@contextlib.contextmanager
def _quiet_recompile(fn):
    """Suppress the instrumented wrapper's recompile-cause warning for
    the duration of a PLANNED multi-signature compile run (warming one
    program per bucket is a deliberate signature set, not a storm).
    The flag is restored in a ``finally`` even when a bucket build
    raises mid-warmup, and a forward callable WITHOUT the attribute
    (a grouped/eager fn, or a test double) passes through untouched."""
    prev = getattr(fn, "warn_recompile", None)
    if prev is not None:
        fn.warn_recompile = False
    try:
        yield
    finally:
        if prev is not None:
            fn.warn_recompile = prev


# process-global request-id source: the CAUSAL id that rides one
# request through submit -> coalesce -> batch dispatch -> d2h ->
# resolve. Process-global (not per-engine) so a postmortem covering two
# engines never shows two requests under one id.
_REQ_SEQ = itertools.count(1)


class _Request:
    __slots__ = ("arrays", "rows", "future", "wait_span", "req_span",
                 "deadline", "req_id")

    def __init__(self, arrays, rows, deadline=None):
        self.arrays = arrays          # {input name: np.ndarray (rows,...)}
        self.rows = rows
        self.deadline = deadline      # monotonic instant, or None
        self.req_id = next(_REQ_SEQ)
        self.future = Future()
        # the causal id surfaces on the future too, so a client (and
        # the postmortem lane) can join its outcome against the dump's
        # member req_ids
        self.future.req_id = self.req_id
        # spans are entered on the submitting thread and closed on the
        # coalescer / resolver threads — _Span carries its own t0 and
        # causal ctx (explicit: thread-local ids would not follow the
        # request across threads)
        ctx = {"req_id": self.req_id}
        self.wait_span = telemetry.span("serve_wait", ctx=ctx).__enter__()
        self.req_span = telemetry.span("serve_request",
                                       ctx=ctx).__enter__()

    def expired(self, now=None):
        return self.deadline is not None \
            and (now if now is not None else time.monotonic()) \
            > self.deadline


_FLUSH = object()
_SHUTDOWN = object()

# substrings that mark a backend error as transient (worth a retry):
# RPC-layer flakes on a remoted PJRT backend, never compiler/program
# errors
_TRANSIENT_MARKERS = ("UNAVAILABLE", "DEADLINE_EXCEEDED",
                      "Connection reset", "connection", "socket closed")


def _is_transient(exc):
    """Whether a dispatch failure is worth retrying: injected faults
    flagged ``transient`` (faults.InjectedFault) and RPC-ish backend
    errors are; program errors (TypeError/ValueError — wrong
    shape/dtype, they fail identically every time) and OOM
    (DeviceMemoryError — retrying allocates the same bytes) never
    are."""
    if getattr(exc, "transient", False):
        return True
    if isinstance(exc, (TypeError, ValueError, DeviceMemoryError)):
        return False
    s = str(exc)
    return any(m in s for m in _TRANSIENT_MARKERS)


class InferenceEngine:
    """Dynamic micro-batching over bucketed AOT forward programs.

    Parameters
    ----------
    symbol : Symbol | str — graph (or its JSON), as for ``Predictor``
    params : dict | bytes | str — ``arg:``/``aux:`` blob, as for
        ``Predictor``
    input_shapes : dict name -> shape — per-input shape; dim 0 is the
        batch dimension (its value only seeds shape inference, requests
        may carry any row count up to ``max_batch``)
    ctx : Context — device (default: current context)
    max_batch : int — largest batch one program serves; buckets are the
        powers of two up to it
    max_wait_ms : float — coalescing deadline: a pending request waits
        at most this long for co-batchable traffic before a partial
        bucket is flushed
    max_inflight : int | None — dispatched-but-unresolved batch bound
        (the device-queue depth the coalescer may run ahead). ``None``
        (the default) means 2, or the autotuner plan's choice when
        ``autotune=True`` found one
    dtype : optional input dtype override (e.g. bfloat16), as for
        ``Predictor``
    warmup : bool — compile every bucket at construction (AOT); with
        ``False`` buckets compile on first use
    telemetry_logger : optional ``callback.TelemetryLogger`` — the
        engine calls its ``log_serving()`` after every batch so a
        running engine logs queue depth / fill / p95 periodically
    predictor : optional existing ``Predictor`` to share programs and
        device-resident parameters with (``symbol``/``params``/
        ``input_shapes`` are then taken from it)
    buckets : optional explicit batch-bucket list (e.g. an autotuner
        plan's) replacing the pow-2 default; normalised through
        ``validate_buckets`` (``max_batch`` always tops the set)
    autotune : bool — derive ``buckets``/``max_inflight`` from the
        persisted program-card corpus (``compile_cache.corpus_records``
        → ``tuner.plan_serving``): measured rows-histogram and
        per-bucket step-ms data replace the pow-2 default. Falls back
        silently to the defaults when the corpus is absent or empty;
        the chosen plan is stamped onto every bucket's program card
        (``autotune_plan``) and reported by ``stats()``
    max_queue_rows : int | None — admission bound: rows allowed to wait
        for a bucket (queued + pending, excludes in-flight batches).
        ``None`` (default) keeps the legacy unbounded queue
    deadline_ms : float | None — engine-wide default request deadline
        (per-request ``submit(deadline_ms=)`` overrides); enforced at
        admission, coalesce and resolution (``DeadlineExceeded``)
    overload : "shed" | "block" — full-queue policy: fail the submit
        fast (``QueueOverflow``) or backpressure the submitting thread
        (bounded by the request deadline, if any)
    retry_budget : int — max retries of one coalesced batch's dispatch
        on TRANSIENT failures (injected faults, flaky backend RPCs);
        program errors (shape/dtype/OOM) never retry
    retry_backoff_ms : float — base backoff before retry k is
        ``retry_backoff_ms * 2**k``
    breaker_threshold : int — consecutive dispatch failures that trip
        the breaker into fast-fail (``CircuitOpen``); 0 disables
    breaker_reset_s : float — open-state cooldown before ONE half-open
        trial batch is allowed through (success closes the breaker)
    partition_rules : optional ``parallel.partition.PartitionRules`` —
        the SAME rule tree training uses: parameters commit
        device-resident mp-SHARDED across every bucket (a model that
        exceeds one chip's HBM serves from N chips without
        replication), GSPMD inserting the collectives each bucket's
        forward needs. Requires ``contexts``.
    mesh_axes : optional ordered ``{axis: size}`` laying ``contexts``
        out as the serving mesh (default ``{"dp": 1, "mp": -1}`` — all
        serving devices model-parallel; a ``dp`` axis > 1 additionally
        splits each bucket's batch, so every bucket size must divide
        by it)
    contexts : optional Context list backing the serving mesh (with
        ``partition_rules``); defaults to the single ``ctx``
    """

    def __init__(self, symbol=None, params=None, input_shapes=None,
                 ctx=None, max_batch=32, max_wait_ms=2.0, max_inflight=None,
                 dtype=None, warmup=True, telemetry_logger=None,
                 predictor=None, buckets=None, autotune=False,
                 max_queue_rows=None, deadline_ms=None, overload="shed",
                 retry_budget=2, retry_backoff_ms=5.0,
                 breaker_threshold=5, breaker_reset_s=30.0,
                 partition_rules=None, mesh_axes=None, contexts=None):
        if predictor is None:
            if symbol is None or input_shapes is None:
                raise MXNetError("InferenceEngine needs (symbol, params, "
                                 "input_shapes) or predictor=")
            predictor = Predictor(symbol, params or {}, input_shapes,
                                  ctx=ctx, dtype=dtype)
        self._predictor = predictor
        ex = predictor._executor
        self._prog = ex._prog
        if self._prog.node_devices:
            raise MXNetError("serving: grouped (group2ctx) programs run "
                             "eagerly per segment and cannot be bucketed")
        self._symbol = predictor._symbol
        self._ctx = ex._ctx
        self._device = self._ctx.jax_device()
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_ms) / 1e3
        # partition-rule serving: the SAME rule tree training uses
        # commits the params mp-sharded over a serving mesh, shared by
        # every bucket program (GSPMD inserts the per-bucket
        # collectives); batches land via the spec's dp sharding. Built
        # BEFORE the autotune plan load — the plan records the layout.
        self._mesh_spec = None
        if partition_rules is not None or mesh_axes:
            from .parallel import mesh as _pmesh, spmd as _spmd
            ctxs = list(contexts) if contexts else [self._ctx]
            mesh = _pmesh.mesh_from_contexts(
                ctxs, axes=dict(mesh_axes) if mesh_axes
                else {_spmd.DP_AXIS: 1, _spmd.MP_AXIS: -1})
            self._mesh_spec = _spmd.rule_spec(mesh, partition_rules)
        self._autotune_plan = None
        if autotune and buckets is None:
            plan = self._load_plan()
            if plan and plan.get("buckets"):
                self._autotune_plan = plan
                buckets = plan["buckets"]
                if max_inflight is None and plan.get("max_inflight"):
                    max_inflight = plan["max_inflight"]
        if max_inflight is None:
            max_inflight = 2
        self._max_inflight = max(1, int(max_inflight))
        self.buckets = bucket_sizes(self.max_batch) if buckets is None \
            else validate_buckets(buckets, self.max_batch)
        self._input_names = list(predictor._input_names)
        self._row_shapes = {n: tuple(predictor._input_shapes[n][1:])
                            for n in self._input_names}
        self._in_dtypes = {n: np.dtype(ex.arg_dict[n].dtype)
                           for n in self._input_names}
        # params/aux stay device-resident across ALL buckets: the raw
        # arrays of the predictor's bound storage, shared (not copied)
        auto = set(predictor._auto_args)
        self._param_raw = {n: a._data for n, a in ex.arg_dict.items()
                           if n not in self._input_names and n not in auto}
        self._aux_raw = {n: a._data for n, a in ex.aux_dict.items()}
        # commit the shared device-resident params/aux onto the
        # partition mesh (every bucket program reads these buffers)
        if self._mesh_spec is not None:
            from .parallel import spmd as _spmd
            spec = self._mesh_spec
            if spec.dp_size > 1:
                bad = [b for b in self.buckets if b % spec.dp_size]
                if bad:
                    raise MXNetError(
                        "serving: bucket size(s) %s not divisible by "
                        "the %r mesh axis (size %d)"
                        % (bad, spec.data_axis, spec.dp_size))
            self._param_raw = {
                n: _spmd.shard_put(
                    r, spec.param_sharding(n, tuple(r.shape)))
                for n, r in self._param_raw.items()}
            self._aux_raw = {
                n: _spmd.shard_put(
                    r, spec.param_sharding(n, tuple(r.shape)))
                for n, r in self._aux_raw.items()}
        # inference-time dummies (loss-layer labels) are batch-shaped:
        # one zero set per bucket, built lazily in _bucket_extras —
        # from the MAIN thread (warmup) and the coalescer/drain threads
        # (dispatch), so the cache has its own tiny lock (not the
        # admission lock: a first-touch device_put must not stall
        # submit())
        self._auto_names = sorted(auto)
        self._extras_lock = threading.Lock()
        self._extras = {}                # guarded by: self._extras_lock
        self._rng = ex._step_key()
        self._forward = self._prog.forward_fn(False)

        if overload not in ("shed", "block"):
            raise MXNetError("serving: overload must be 'shed' or "
                             "'block', got %r" % (overload,))
        self.max_queue_rows = None if max_queue_rows is None \
            else max(1, int(max_queue_rows))
        self.deadline_s = None if deadline_ms is None \
            else float(deadline_ms) / 1e3
        self.overload = overload
        self._retry_budget = max(0, int(retry_budget))
        self._retry_backoff_s = max(0.0, float(retry_backoff_ms)) / 1e3
        self._breaker_threshold = max(0, int(breaker_threshold))
        self._breaker_reset_s = float(breaker_reset_s)
        self._breaker_open_at = None     # guarded by: self._lock
        self._breaker_probing = False    # guarded by: self._lock
        self._consecutive_failures = 0   # guarded by: self._lock
        self._queued_rows = 0            # guarded by: self._lock

        self._logger = telemetry_logger
        self._lock = threading.Lock()
        # admission backpressure: notified whenever queued rows leave
        # the admission queue (dispatch or shed). Condition over the
        # SAME lock — ``with self._space:`` satisfies every
        # ``guarded by: self._lock`` annotation above/below
        self._space = threading.Condition(self._lock)
        self._stats = collections.Counter()            # guarded by: self._lock
        self._bucket_batches = collections.Counter()   # guarded by: self._lock
        # measured serving data the card corpus persists for the
        # autotuner: coalesced-batch row counts (pre-padding) and
        # dispatch->resolution wall-time per bucket
        self._rows_hist = collections.Counter()        # guarded by: self._lock
        self._bucket_lat = {}            # guarded by: self._lock
        self._q = queue.Queue()
        self._inflight = threading.Semaphore(self._max_inflight)
        self._closed = False             # guarded by: self._lock
        # close() ran to completion (joined + pool down + corpus
        # flushed). Distinct from _closed: a coalescer death closes
        # the ENGINE (submits fast-fail) but the first close() call
        # must still shut the pool down and flush — only a completed
        # close() makes later calls no-ops.
        self._close_done = False         # guarded by: self._lock
        self._pool = ThreadPoolExecutor(
            max_workers=self._max_inflight,
            thread_name_prefix="mxtpu-serve-resolve")
        self._thread = threading.Thread(target=self._coalesce_loop,
                                        name="mxtpu-serve-coalesce",
                                        daemon=True)
        self._thread.start()
        # the flight recorder's sampler/postmortems read this engine's
        # queue/breaker state (weakly held — close() is not required)
        flight.register_engine(self)
        if warmup:
            self.warmup()

    def _put_batch(self, buf):
        """Commit one bucket-shaped host batch: sharded over the mesh
        spec's dp axis (replicated over mp) on a partitioned engine,
        plain single-device put otherwise."""
        if self._mesh_spec is not None:
            return jax.device_put(buf, self._mesh_spec.data_sharding)
        return jax.device_put(buf, self._device)

    def _put_extra(self, buf, batch_major):
        """Commit one inference dummy: batch-major dummies ride the
        batch placement, fixed-shape ones replicate on the mesh."""
        if self._mesh_spec is None:
            return jax.device_put(buf, self._device)
        return jax.device_put(buf, self._mesh_spec.data_sharding
                              if batch_major
                              else self._mesh_spec.repl_sharding)

    def partition_summary(self):
        """JSON-safe layout description (None without rules) — what
        the autotuner plan and the bucket program cards record."""
        if self._mesh_spec is None:
            return None
        from .parallel.partition import partition_summary as _summary
        params = getattr(self, "_param_raw", None)
        return _summary(self._mesh_spec,
                        {n: tuple(r.shape) for n, r in params.items()}
                        if params else None)

    # -- program cache ------------------------------------------------------
    def _load_plan(self):
        """The autotuner plan for this engine's ``max_batch`` from the
        persisted card corpus, or None (no corpus / no serving records
        / tuner failure — autotune must never break construction).
        Records are filtered to THIS engine's graph fingerprint: the
        corpus is shared per cache dir, and another model's rows
        histogram / step-ms would plan pessimal buckets here."""
        try:
            from . import compile_cache
            from .tuner import plan_serving
            records = compile_cache.corpus_records(kind="serving")
            return plan_serving(records, max_batch=self.max_batch,
                                graph=self._prog.graph_fingerprint(),
                                layout=self.partition_summary())
        except Exception as e:
            from . import log as _log
            _log.get_logger("mxnet_tpu.serving").warning(
                "serving: autotune plan unavailable (%s); using pow-2 "
                "bucket defaults", e)
            return None

    def warmup(self):
        """Build every bucket's forward program — after this, serving
        dispatches are all AOT cache hits and ``program_cards()`` holds
        one card per bucket signature. Building does NOT execute when
        the wrapper exposes ``build`` (an execution per bucket bought
        nothing but startup wall); with the persisted compile cache on,
        each bucket's program DESERIALIZES from disk instead of
        invoking XLA (the zero-cold-start path). The recompile-cause
        warning is suppressed ONLY for the duration (bucket compiles
        are planned signatures, not a storm; restored in a finally even
        when a bucket build raises); a steady-state signature drift
        afterwards still warns, for this engine and for any Predictor
        sharing the program."""
        build = getattr(self._forward, "build", None)
        with _quiet_recompile(self._forward):
            for b in self.buckets:
                args = dict(self._param_raw)
                for n in self._input_names:
                    args[n] = self._put_batch(
                        np.zeros((b,) + self._row_shapes[n],
                                 self._in_dtypes[n]))
                args.update(self._bucket_extras(b))
                if build is not None:
                    build(args, self._aux_raw, self._rng)
                else:
                    outs, _ = self._forward(args, self._aux_raw,
                                            self._rng)
                    for o in outs:
                        o.block_until_ready()
        if self._autotune_plan is not None:
            # stamp the plan onto every bucket card: a card reader sees
            # WHY this bucket set exists next to what each bucket costs
            for cid in self.program_cards():
                telemetry.card_annotate(cid,
                                        autotune_plan=self._autotune_plan)
        layout = self.partition_summary()
        if layout is not None:
            # per-bucket cards carry the layout the bucket ran under —
            # a card corpus mixing replicated and mp-sharded rows stays
            # attributable
            for cid in self.program_cards():
                telemetry.card_annotate(cid, partition=layout)

    def _infer_dummy_shapes(self, bucket):
        """{arg name: inferred shape} at one batch size."""
        known = {n: (bucket,) + self._row_shapes[n]
                 for n in self._input_names}
        known.update({n: tuple(v.shape)
                      for n, v in self._param_raw.items()})
        shapes, _, _ = self._symbol.infer_shape_partial(**known)
        return dict(zip(self._symbol.list_arguments(), shapes))

    def _extra_row_shapes(self):
        """Per-dummy (row_shape_or_None, dtype, full_shape,
        calibration_bucket) — the shape inference runs at most TWICE
        (warming N buckets used to run the whole per-node walk N
        times, a measurable slice of the cold/warm startup wall the
        compile-cache tier exists to shrink). Batch-major detection
        compares the smallest and largest bucket: a dummy whose
        leading dim tracks BOTH probe sizes really scales with the
        batch; a fixed shape that happens to equal one probe size
        (e.g. a constant (1, K) state input at bucket 1) cannot fool
        both, and falls back to per-bucket inference."""
        cached = getattr(self, "_extra_rows", None)
        if cached is not None:
            return cached
        b0, b1 = self.buckets[0], self.buckets[-1]
        inf0 = self._infer_dummy_shapes(b0)
        inf1 = self._infer_dummy_shapes(b1) if b1 != b0 else inf0
        ex = self._predictor._executor
        rows = {}
        for n in self._auto_names:
            s0, s1 = inf0.get(n), inf1.get(n)
            if s0 is None or s1 is None:
                raise MXNetError("serving: cannot infer dummy shape "
                                 "for %r" % n)
            batch_major = (b1 != b0 and len(s0) >= 1
                           and s0[0] == b0 and s1[0] == b1
                           and tuple(s0[1:]) == tuple(s1[1:]))
            rows[n] = (tuple(s0[1:]) if batch_major else None,
                       np.dtype(ex.arg_dict[n].dtype), tuple(s0), b0)
        self._extra_rows = rows
        return rows

    def _bucket_extras(self, bucket):
        """Device-resident zero dummies (softmax labels etc.) at this
        bucket's batch size, cached per bucket. Serialised on the
        extras lock: warmup (main thread) and dispatch (coalescer /
        shutdown-drain threads) race on first touch of a bucket, and
        an unlocked check-then-set could publish a half-built dict or
        build the same dummies twice (the thread-race mxsync
        flagged)."""
        with self._extras_lock:
            return self._bucket_extras_locked(bucket)

    def _bucket_extras_locked(self, bucket):
        cached = self._extras.get(bucket)
        if cached is not None:
            return cached
        extras = {}
        if self._auto_names:
            reinferred = None
            for n, (row, dt, full, cal_b) in \
                    self._extra_row_shapes().items():
                if row is not None:
                    shp = (bucket,) + row
                elif bucket == cal_b:
                    shp = full       # the calibrated inference IS this bucket
                else:
                    # fixed-shape (non-batch-major) dummy: re-infer at
                    # THIS bucket — the engine must not guess
                    if reinferred is None:
                        reinferred = self._infer_dummy_shapes(bucket)
                    shp = reinferred.get(n)
                    if shp is None:
                        raise MXNetError("serving: cannot infer dummy "
                                         "shape for %r at bucket %d"
                                         % (n, bucket))
                extras[n] = self._put_extra(np.zeros(shp, dt),
                                            batch_major=row is not None)
        self._extras[bucket] = extras
        return extras

    def bucket_for(self, rows):
        """Smallest bucket covering ``rows``."""
        for b in self.buckets:
            if b >= rows:
                return b
        raise MXNetError("serving: %d rows exceed max_batch=%d"
                         % (rows, self.max_batch))

    def program_cards(self):
        """{card_id: card} for THIS engine's forward programs — one card
        per compiled (bucket, dtype) signature."""
        entry = getattr(self._forward, "entry", None)
        if entry is None:
            return {}
        return {k: c for k, c in telemetry.programs().items()
                if k == entry or k.startswith(entry + "/")}

    # -- request surface ----------------------------------------------------
    def _shed(self, req, cause, exc):
        """Resolve one request's future with a structured shed error and
        account it (engine stats + telemetry, by cause). The wait/req
        spans still close — shed time is real queue time."""
        if req.future.done():
            return
        req.wait_span.__exit__(None, None, None)
        req.req_span.__exit__(None, None, None)
        req.future.set_exception(exc)
        with self._lock:
            self._stats["shed_requests"] += 1
            self._stats["shed_rows"] += req.rows
            self._stats["shed.%s" % cause] += 1
        telemetry.counter_inc("serving.shed_requests")
        telemetry.counter_inc("serving.shed_rows", req.rows)
        telemetry.counter_inc("serving.shed.%s" % cause)
        telemetry.record_event("serving.shed", req_id=req.req_id,
                               cause=cause, rows=req.rows)
        if isinstance(exc, DeadlineExceeded):
            telemetry.counter_inc("serving.deadline_exceeded")

    def submit(self, *args, deadline_ms=None, **kwargs):   # mxlint: hot
        """Enqueue one request; returns a Future resolving to the list
        of per-output numpy arrays (each ``(rows, ...)``). Inputs go by
        name (``submit(data=x)``); a single-input graph also accepts one
        positional array. Each input must be ``(rows,) + row_shape``
        with 1 <= rows <= max_batch.

        ``deadline_ms`` bounds this request's whole submit→result life
        (default: the engine's ``deadline_ms``); past it the future
        resolves with ``DeadlineExceeded``. A full bounded queue sheds
        (``QueueOverflow``) or blocks, per the ``overload`` policy; an
        open breaker fast-fails with ``CircuitOpen``."""
        if self._closed:   # mxlint: disable=lock-discipline -- lock-free fast path; re-checked under the lock before enqueue
            raise EngineClosed("serving: engine is closed")
        if self._breaker_tripped():
            with self._lock:
                self._stats["breaker_fastfail"] += 1
                # capture under the SAME lock the failure path writes
                # it under — the bare read could tear against a
                # concurrent _dispatch_failed/_dispatch_succeeded
                consecutive = self._consecutive_failures
            telemetry.counter_inc("serving.breaker_fastfail")
            raise CircuitOpen(
                "serving: breaker open after %d consecutive dispatch "
                "failures — fast-failing instead of queuing onto a "
                "failing backend (retries again %.1fs after the trip)"
                % (consecutive, self._breaker_reset_s))
        if args:
            if len(args) != 1 or kwargs or len(self._input_names) != 1:
                raise MXNetError("serving: pass inputs by name "
                                 "(submit(name=array))")
            kwargs = {self._input_names[0]: args[0]}
        if set(kwargs) != set(self._input_names):
            raise MXNetError("serving: inputs %s do not match engine "
                             "inputs %s" % (sorted(kwargs),
                                            sorted(self._input_names)))
        arrays, rows = {}, None
        for n, v in kwargs.items():
            a = np.asarray(getattr(v, "asnumpy", lambda: v)())   # mxlint: disable=host-sync -- marshalling the CLIENT's payload to a host array is the request contract, not a device fetch
            want = self._row_shapes[n]
            if a.shape == want:           # a single row without batch dim
                a = a[None]
            if a.ndim != len(want) + 1 or tuple(a.shape[1:]) != want:
                raise MXNetError(
                    "serving: input %r shape %s != (rows,)+%s"
                    % (n, a.shape, want))
            if rows is None:
                rows = a.shape[0]
            elif a.shape[0] != rows:
                raise MXNetError("serving: inputs disagree on rows")
            arrays[n] = np.ascontiguousarray(
                a.astype(self._in_dtypes[n], copy=False))
        if not rows:
            raise MXNetError("serving: empty request")
        if rows > self.max_batch:
            raise MXNetError("serving: request rows %d exceed max_batch %d"
                             % (rows, self.max_batch))
        dl_s = self.deadline_s if deadline_ms is None \
            else float(deadline_ms) / 1e3
        deadline = None if dl_s is None else time.monotonic() + dl_s
        req = _Request(arrays, rows, deadline=deadline)
        # the closed-check and the enqueue share the lock with close()'s
        # flag-set + sentinel-put: a request that passes the check is
        # guaranteed to land BEFORE the shutdown sentinel, so its future
        # always resolves
        def _drop_locked(exc, shed=False, deadline_hit=False):
            # an admission-rejected request never enters the queue, but
            # its spans were entered at _Request construction: close
            # them (the rejection time is a real latency sample) and
            # account the shed. Caller holds self._lock (the _locked
            # suffix is the lint-checked contract).
            req.wait_span.__exit__(None, None, None)
            req.req_span.__exit__(None, None, None)
            if shed:
                self._stats["shed_requests"] += 1
                self._stats["shed_rows"] += rows
                self._stats["shed.admission"] += 1
                telemetry.counter_inc("serving.shed_requests")
                telemetry.counter_inc("serving.shed_rows", rows)
                telemetry.counter_inc("serving.shed.admission")
                telemetry.record_event("serving.shed",
                                       req_id=req.req_id,
                                       cause="admission", rows=rows)
                if deadline_hit:
                    telemetry.counter_inc("serving.deadline_exceeded")
            raise exc

        with self._space:
            if self._closed:
                _drop_locked(EngineClosed("serving: engine is closed"))
            # bounded admission: shed fast or backpressure (bounded by
            # the request's own deadline)
            while self.max_queue_rows is not None \
                    and self._queued_rows + rows > self.max_queue_rows:
                if self.overload == "shed":
                    _drop_locked(QueueOverflow(
                        "serving: admission queue full (%d rows "
                        "waiting, max_queue_rows=%d) — shedding"
                        % (self._queued_rows, self.max_queue_rows)),
                        shed=True)
                timeout = None if deadline is None \
                    else deadline - time.monotonic()
                if timeout is not None and timeout <= 0 \
                        or not self._space.wait(timeout):
                    _drop_locked(DeadlineExceeded(
                        "serving: deadline expired while blocked on a "
                        "full admission queue (max_queue_rows=%d)"
                        % self.max_queue_rows), shed=True,
                        deadline_hit=True)
                if self._closed:
                    _drop_locked(EngineClosed("serving: engine is closed"))
            self._stats["requests"] += 1
            self._stats["rows"] += rows
            self._queued_rows += rows
            self._q.put(req)
        telemetry.counter_inc("serving.requests")
        telemetry.counter_inc("serving.rows", rows)
        return req.future

    def predict(self, *args, **kwargs):
        """Blocking convenience: ``submit(...).result()``."""
        return self.submit(*args, **kwargs).result()

    def flush(self):
        """Ask the coalescer to dispatch whatever is pending now instead
        of waiting out the deadline. Fails fast with ``EngineClosed``
        after ``close()`` (the unguarded version put a sentinel into a
        dead queue nobody would ever drain)."""
        with self._lock:
            if self._closed:
                raise EngineClosed("serving: engine is closed")
            self._q.put(_FLUSH)

    def stats(self):
        """Engine-side counters + the request-latency percentiles: what
        a load balancer's health endpoint would export."""
        with self._lock:
            st = dict(self._stats)
            rows_hist = {str(k): v for k, v in
                         sorted(self._rows_hist.items())}
            bucket_ms = {str(b): {"count": c,
                                  "total_ms": round(t * 1e3, 3),
                                  "mean_ms": round(t / c * 1e3, 3)}
                         for b, (t, c) in sorted(self._bucket_lat.items())
                         if c}
            # snapshot here too: the coalescer bumps this Counter per
            # batch, and the bare read further down raced it
            buckets = {str(k): v for k, v in
                       sorted(self._bucket_batches.items())}
        rows = st.get("batch_rows", 0)
        pad = st.get("pad_rows", 0)
        lat = telemetry.span_stats("serve_request").get("serve_request", {})
        with self._lock:
            queued_rows = self._queued_rows
            breaker_open = self._breaker_tripped()
            consecutive = self._consecutive_failures
        return {
            # WHO is reporting: a fleet health-checker scraping N
            # engine processes joins on this block (ISSUE 18)
            "process": telemetry.process_identity(),
            "requests": st.get("requests", 0),
            "resolved": st.get("resolved", 0),
            "failed_requests": st.get("failed_requests", 0),
            # admitted requests not yet terminally resolved — the ONE
            # shared formula (TelemetryLogger and the flight sampler
            # compute the same depth from the telemetry counters)
            "queue_depth": telemetry.serving_queue_depth(st, prefix=""),
            "batches": st.get("batches", 0),
            "rows": st.get("rows", 0),
            "pad_rows": pad,
            "pad_bytes": st.get("pad_bytes", 0),
            "batch_fill": round(rows / (rows + pad), 4) if rows + pad
            else None,
            # overload-control trajectory: what the chaos lane and a
            # load balancer's health endpoint read
            "queued_rows": queued_rows,
            "max_queue_rows": self.max_queue_rows,
            "deadline_ms": None if self.deadline_s is None
            else round(self.deadline_s * 1e3, 3),
            "overload": self.overload,
            "shed_requests": st.get("shed_requests", 0),
            "shed_rows": st.get("shed_rows", 0),
            "shed_by_cause": {k[len("shed."):]: v for k, v in st.items()
                              if k.startswith("shed.")},
            "retries": st.get("retries", 0),
            "dispatch_failures": st.get("dispatch_failures", 0),
            "breaker": {
                "open": breaker_open,
                "threshold": self._breaker_threshold,
                "consecutive_failures": consecutive,
                "trips": st.get("breaker_trips", 0),
                "fastfail": st.get("breaker_fastfail", 0),
            },
            "buckets": buckets,
            # the measured serving data the card corpus persists:
            # coalesced row counts (pre-pad) and per-bucket step ms
            "rows_hist": rows_hist,
            "bucket_ms": bucket_ms,
            "max_inflight": self._max_inflight,
            "autotune_plan": self._autotune_plan,
            # the buffer ledger's by-kind view of this engine's mesh
            # context: an OOM postmortem reads WHAT is resident (model
            # weights vs a decode engine's kv_cache on the same mesh),
            # not just how much
            "device_bytes": self.device_bytes(),
            "latency_ms": {k: lat.get(k) for k in
                           ("p50_ms", "p95_ms", "p99_ms")}
            if lat else None,
        }

    def device_bytes(self):
        """Live per-shard device bytes on this engine's mesh context,
        split by ledger kind (``{"total": n, "by_kind": {...}}``): the
        figure capacity planning and OOM postmortems read. Single-
        device engines report the plain device context."""
        if self._mesh_spec is not None:
            key = "mesh(%ddev)" % self._mesh_spec.num_devices
        else:
            key = str(self._device)
        led = telemetry.ledger().get(key, {})
        return {"context": key,
                "total": int(led.get("alive_bytes", 0)),
                "by_kind": {k: int(v) for k, v in
                            led.get("by_kind", {}).items()}}

    def overload_state(self):
        """Light lock-held view of the queue/breaker state — what the
        flight recorder's sampler reads every tick and a postmortem
        embeds (``stats()`` computes span percentiles per call, too
        heavy for a 10 Hz sampler)."""
        with self._lock:
            return {
                "queued_rows": self._queued_rows,
                "max_queue_rows": self.max_queue_rows,
                "breaker_open": self._breaker_tripped(),
                "consecutive_failures": self._consecutive_failures,
                "closed": self._closed,
                "max_inflight": self._max_inflight,
            }

    def corpus_record(self):
        """One JSON-safe record of this engine's measured serving data
        for the persisted card corpus — the raw material
        ``tuner.plan_serving`` turns into the next process's bucket
        plan. None until at least one batch has dispatched (an idle
        engine has nothing to teach the autotuner)."""
        from . import compile_cache
        st = self.stats()
        if not st["batches"]:
            return None
        cards = {
            k: {kk: c.get(kk) for kk in
                ("kind", "flops", "bytes_accessed", "peak_bytes",
                 "compile_ms", "deserialize_ms", "source", "dispatches")}
            for k, c in self.program_cards().items()}
        spans = {k: v for k, v in telemetry.span_stats().items()
                 if k in telemetry.SERVE_SPANS}
        return {
            "kind": "serving",
            "ts": time.time(),
            "env": compile_cache.env_meta(),
            # graph identity: plan_serving filters on it so a shared
            # corpus never plans one model from another's traffic
            "graph": self._prog.graph_fingerprint(),
            "max_batch": self.max_batch,
            "buckets": list(self.buckets),
            # the layout this traffic was measured under: a corpus row
            # banked from an mp-sharded engine must not plan a
            # replicated one as if the step costs were comparable
            "layout": self.partition_summary(),
            "max_inflight": self._max_inflight,
            "max_wait_ms": round(self.max_wait_s * 1e3, 3),
            "requests": st["requests"],
            "batches": st["batches"],
            "batch_rows": st["rows"],
            "pad_rows": st["pad_rows"],
            "batch_fill": st["batch_fill"],
            "rows_hist": st["rows_hist"],
            "bucket_ms": st["bucket_ms"],
            "spans": spans,
            "cards": cards,
        }

    # -- lifecycle ----------------------------------------------------------
    def close(self):
        """Drain and stop: already-submitted requests (queued, pending,
        or in flight) all resolve before close() returns; later
        ``submit``/``flush`` calls raise ``EngineClosed``. Submitters
        blocked on a full queue (overload="block") are woken and fail
        the same way."""
        with self._space:
            already = self._close_done
            self._close_done = True
            if not self._closed:
                self._closed = True
                self._q.put(_SHUTDOWN)
                self._space.notify_all()
        if already:
            return
        # after a coalescer death the thread is already dead (join
        # returns immediately) and the queue is drained — but the
        # pool shutdown below still waits out in-flight resolves, and
        # the corpus/logger flush still runs: the first close() call
        # keeps its full contract either way
        self._thread.join()
        self._pool.shutdown(wait=True)
        # bank this engine's measured serving data into the persisted
        # card corpus (when one is configured) so the NEXT process's
        # autotuner plans from it — telemetry, never state: failures
        # must not turn a clean shutdown into an error
        try:
            from . import compile_cache
            if compile_cache.corpus_path() is not None:
                rec = self.corpus_record()
                if rec is not None:
                    compile_cache.corpus_append(rec)
        except Exception:
            pass
        if self._logger is not None:
            try:
                self._logger.log_serving(force=True)
            except Exception:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- coalescer ----------------------------------------------------------
    def _launch(self, batch):   # mxlint: hot
        """Release a coalesced batch from the admission queue, shed the
        stale members (their deadline passed while they waited — they
        must not pad a bucket and burn device time on an answer nobody
        reads), and dispatch the survivors. On an unexpected raise the
        released rows are RE-CHARGED before propagating: the caller
        hands the batch back to the coalescer's terminal cleanup,
        whose uniform decrement must not double-count (a negative
        queued_rows would corrupt the postmortem's engine snapshot)."""
        with self._space:
            self._queued_rows -= sum(r.rows for r in batch)
            self._space.notify_all()
        try:
            now = time.monotonic()
            live = []
            for r in batch:
                if r.expired(now):
                    self._shed(r, "coalesce", DeadlineExceeded(
                        "serving: request deadline expired in queue "
                        "(waited past %.1fms)" % (
                            0.0 if r.deadline is None
                            else (now - r.deadline) * 1e3)))
                else:
                    live.append(r)
            if live:
                self._dispatch(live)
        except BaseException:
            with self._space:
                self._queued_rows += sum(r.rows for r in batch)
            raise

    def _coalesce_loop(self):   # mxlint: hot
        pending, pending_rows = [], 0
        deadline = None

        def dispatch():
            nonlocal pending, pending_rows, deadline
            if pending:
                batch, pending = pending, []
                pending_rows = 0
                deadline = None
                try:
                    self._launch(batch)
                except BaseException:
                    # hand the dying batch back so the coalescer's
                    # terminal cleanup can fail its futures — swapped
                    # out above, it would otherwise be unreachable
                    # (_launch re-charges the rows it had released,
                    # so the cleanup's uniform decrement stays exact)
                    pending = batch + pending
                    raise

        try:
            while True:
                if pending:
                    try:
                        item = self._q.get(
                            timeout=max(0.0,
                                        deadline - time.monotonic()))
                    except queue.Empty:
                        dispatch()    # deadline flush under trickle load
                        continue
                else:
                    item = self._q.get()
                if item is _SHUTDOWN:
                    dispatch()
                    self._drain_after_shutdown()
                    break
                if item is _FLUSH:
                    dispatch()
                    continue
                if pending_rows + item.rows > self.max_batch:
                    try:
                        dispatch()    # the new request doesn't fit
                    except BaseException:
                        # the dequeued item is in neither pending nor
                        # the queue yet — hand it to the terminal
                        # cleanup with the restored batch, or its
                        # future strands
                        pending.append(item)
                        raise
                pending.append(item)
                pending_rows += item.rows
                if deadline is None:
                    deadline = time.monotonic() + self.max_wait_s
                if pending_rows >= self.max_batch:
                    dispatch()
        except BaseException as e:
            # the coalescer is the ONLY consumer of the admission
            # queue: if it dies, every queued/pending future hangs
            # forever. Fail them all instead (the zero-hung-futures
            # promise the mxlife audit checks path-by-path), close the
            # engine so later submits fast-fail rather than queue into
            # a dead queue, and leave the black box — then re-raise so
            # threading.excepthook still sees the death.
            self._coalescer_died(pending, e)
            raise

    def _coalescer_died(self, pending, exc):
        """Terminal cleanup for a dying coalescer thread (see above):
        every pending + still-queued request resolves with a
        structured error, blocked submitters wake into EngineClosed,
        and a postmortem names the count."""
        # close FIRST, under the same lock submit() enqueues under:
        # a request admitted after the drain below would sit in a
        # dead queue forever — the hung future this cleanup exists
        # to prevent
        with self._space:
            self._closed = True
            self._space.notify_all()
        left = list(pending)
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is not _SHUTDOWN and item is not _FLUSH:
                left.append(item)
        with self._space:
            self._queued_rows -= sum(r.rows for r in left)
            self._space.notify_all()
        err = MXNetError(
            "serving: coalescer thread died (%s: %s) — the engine is "
            "closed and this request was never dispatched"
            % (type(exc).__name__, exc))
        for r in left:
            self._shed(r, "coalescer_death", err)
        flight.postmortem("coalescer_death", exc=exc,
                          extra={"engine": self.overload_state(),
                                 "failed_requests": len(left)})

    def _drain_after_shutdown(self):
        """Backstop: submit() enqueues under the same lock close() uses
        to set the flag and post the sentinel, so nothing should land
        behind it — but nothing already enqueued may ever be left
        unresolved, so drain defensively anyway."""
        left = []
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is not _SHUTDOWN and item is not _FLUSH:
                left.append(item)
        while left:
            batch, rows = [], 0
            while left and rows + left[0].rows <= self.max_batch:
                r = left.pop(0)
                batch.append(r)
                rows += r.rows
            try:
                self._launch(batch)
            except BaseException:
                # hand everything not yet launched back through the
                # queue the coalescer's terminal cleanup drains — a
                # drain-time failure must not strand the rest
                for r in batch + left:
                    self._q.put(r)
                raise

    # -- breaker ------------------------------------------------------------
    def _breaker_tripped(self):
        """True while the breaker is open AND still cooling (fast-fail
        window). After ``breaker_reset_s`` the engine goes half-open:
        submits are admitted again and ONE trial batch probes the
        backend. Lock-free (monotonic reads) — stats() calls this under
        the lock."""
        opened = self._breaker_open_at   # mxlint: disable=lock-discipline -- GIL-atomic one-shot read on the submit fast path; stats() re-reads under the lock
        if opened is None:
            return False
        return (time.monotonic() - opened) < self._breaker_reset_s

    def reset_breaker(self):
        """Force the breaker closed (operator override)."""
        with self._lock:
            self._breaker_open_at = None
            self._breaker_probing = False
            self._consecutive_failures = 0

    def _fail_requests(self, reqs, exc):
        """Resolve every still-pending member future with ``exc`` and
        count them: a failed request is neither resolved nor shed, and
        without its own counter the queue-depth arithmetic would count
        it as queued forever."""
        failed = 0
        for r in reqs:
            if not r.future.done():
                # the spans entered at admission close on EVERY
                # terminal path — without this, a failed batch's
                # serve_request spans never recorded, so the latency
                # percentiles and the flight recorder silently
                # excluded exactly the interesting requests (mxlife
                # future-lifecycle). _Span.__exit__ is idempotent, so
                # the launch-failure leg (where _dispatch already
                # closed wait_span) double-exits harmlessly.
                r.wait_span.__exit__(None, None, None)
                r.req_span.__exit__(None, None, None)
                r.future.set_exception(exc)
                failed += 1
        if failed:
            with self._lock:
                self._stats["failed_requests"] += failed
            telemetry.counter_inc("serving.failed_requests", failed)

    def _dispatch_failed(self):
        """One coalesced batch's pipeline failed for good — at LAUNCH
        (retries exhausted / non-retryable) or at the RESOLUTION fetch
        (on an async backend a dead device often surfaces at
        ``np.asarray``, not at the dispatch call, so the fetch feeds
        the breaker too): bump the consecutive count and trip/re-trip
        the breaker at the threshold."""
        with self._lock:
            self._stats["dispatch_failures"] += 1
            self._consecutive_failures += 1
            self._breaker_probing = False
            consecutive = self._consecutive_failures
            trip = (self._breaker_threshold > 0
                    and consecutive >= self._breaker_threshold)
            if trip:
                self._breaker_open_at = time.monotonic()
                self._stats["breaker_trips"] += 1
        telemetry.counter_inc("serving.dispatch_failures")
        if trip:
            telemetry.counter_inc("serving.breaker_trips")
            telemetry.record_event("serving.breaker_trip",
                                   consecutive=consecutive)
            # a tripping breaker is a flight-recorder moment: the
            # backend just went from flaky to DOWN — dump the window
            # (no-op without a flight dir; throttled against flapping)
            flight.postmortem("breaker_trip",
                              extra={"engine": self.overload_state(),
                                     "consecutive": consecutive})

    def _dispatch_succeeded(self):
        with self._lock:
            self._consecutive_failures = 0
            self._breaker_open_at = None
            self._breaker_probing = False

    def _dispatch(self, reqs):   # mxlint: hot
        """Pack ``reqs`` into the smallest covering bucket, launch the
        bucket's program (async, with the transient-failure retry
        budget), and hand resolution to the pool. With the breaker open
        the batch fast-fails (``CircuitOpen``) — except the one
        half-open trial per cooldown that probes the backend."""
        with self._lock:
            opened = self._breaker_open_at
            fastfail = False
            if opened is not None:
                cooling = (time.monotonic() - opened) \
                    < self._breaker_reset_s
                if cooling or self._breaker_probing:
                    fastfail = True
                else:
                    self._breaker_probing = True    # the half-open trial
        if fastfail:
            with self._lock:
                self._stats["breaker_fastfail"] += len(reqs)
            telemetry.counter_inc("serving.breaker_fastfail", len(reqs))
            exc = CircuitOpen(
                "serving: breaker open — dispatch suppressed")
            for r in reqs:
                self._shed(r, "breaker", exc)
            return
        # the dying batch's member ids: the serve_batch/serve_d2h spans
        # carry them (flow events link each member's serve_wait ->
        # serve_batch -> serve_d2h -> serve_request across threads) and
        # a terminal failure's postmortem names them
        ids = [r.req_id for r in reqs]
        bucket = None
        self._inflight.acquire()
        try:
            rows = sum(r.rows for r in reqs)
            bucket = self.bucket_for(rows)
            for r in reqs:
                r.wait_span.__exit__(None, None, None)
            args = dict(self._param_raw)
            pad_bytes = 0
            for n in self._input_names:
                buf = np.zeros((bucket,) + self._row_shapes[n],
                               self._in_dtypes[n])
                off = 0
                for r in reqs:
                    buf[off:off + r.rows] = r.arrays[n]
                    off += r.rows
                pad_bytes += (bucket - rows) * buf[0].nbytes
                telemetry.record_transfer(buf.nbytes)
                args[n] = self._put_batch(buf)
            args.update(self._bucket_extras(bucket))
            attempt = 0
            while True:
                try:
                    record_dispatch("serve")
                    with telemetry.span("serve_batch",
                                        ctx={"req_ids": ids}):
                        outs, _ = self._forward(args, self._aux_raw,
                                                self._rng)
                    break
                except Exception as e:
                    # retry ONLY transient faults, within the budget —
                    # a program error (shape/dtype/OOM) fails the same
                    # way every time and retrying it is pure waste
                    if attempt >= self._retry_budget \
                            or not _is_transient(e):
                        raise
                    attempt += 1
                    with self._lock:
                        self._stats["retries"] += 1
                    telemetry.counter_inc("serving.retries")
                    time.sleep(self._retry_backoff_s
                               * (2 ** (attempt - 1)))
            # success (and the breaker reset / half-open close) is
            # declared in _resolve once the FETCH lands: on an async
            # backend the launch returning proves nothing yet
            with self._lock:
                self._stats["batches"] += 1
                self._stats["batch_rows"] += rows
                self._stats["pad_rows"] += bucket - rows
                self._stats["pad_bytes"] += pad_bytes
                self._bucket_batches[bucket] += 1
                self._rows_hist[rows] += 1
            telemetry.counter_inc("serving.batches")
            telemetry.counter_inc("serving.batch_rows", rows)
            telemetry.counter_inc("serving.pad_rows", bucket - rows)
            telemetry.counter_inc("serving.pad_bytes", pad_bytes)
            telemetry.record_event("serving.batch", req_ids=ids,
                                   bucket=bucket, rows=rows,
                                   pad_rows=bucket - rows)
            self._pool.submit(self._resolve, outs, reqs, bucket,
                              time.perf_counter())
        except BaseException as e:
            self._inflight.release()
            self._dispatch_failed()
            # EVERY member's future resolves with the failure — a
            # mid-flight dispatch error must never strand a pending
            # Future.result()
            self._fail_requests(reqs, e)
            telemetry.record_event("serving.batch_failed", req_ids=ids,
                                   bucket=bucket,
                                   error=type(e).__name__)
            # a TERMINAL batch failure (retries exhausted or
            # non-retryable) is exactly what the black box exists for:
            # the dump names the dying batch's member req_ids and, for
            # an injected fault, its site
            flight.postmortem("serving_dispatch_failure", exc=e,
                              extra={"req_ids": ids, "bucket": bucket,
                                     "engine": self.overload_state()})
        else:
            if self._logger is not None:
                try:
                    self._logger.log_serving()
                except Exception:
                    pass

    def _resolve(self, outs, reqs, bucket=None, t_disp=None):
        """Resolver-pool worker: blocking d2h of the whole padded batch,
        then slice each request's rows off and resolve its future — or
        resolve with ``DeadlineExceeded`` when the result arrived past
        the request's deadline (the client stopped caring; delivering
        late data as success would hide the overload the deadline
        exists to expose). The dispatch->fetched wall-time charges the
        bucket's measured step-ms tally — the corpus figure the
        autotuner's cost model interpolates over."""
        try:
            # chaos site: a raise is a failed fetch (every member future
            # resolves with it below); "nan" corrupts the host copy —
            # what the chaos lane's divergence assertions feed on
            act = faults.fire("d2h") if faults.active() else None
            with telemetry.span("serve_d2h",
                                ctx={"req_ids": [r.req_id
                                                 for r in reqs]}):
                host = [np.asarray(o) for o in outs]
            if act == "nan":
                host = faults.poison(host)
            # the fetch landing is the REAL success signal (async
            # dispatch: a dead backend surfaces here, not at launch) —
            # close the half-open trial / reset the breaker now
            self._dispatch_succeeded()
            if bucket is not None and t_disp is not None:
                dt = time.perf_counter() - t_disp
                with self._lock:
                    lat = self._bucket_lat.setdefault(bucket, [0.0, 0])
                    lat[0] += dt
                    lat[1] += 1
            now = time.monotonic()
            off = 0
            for r in reqs:
                sl = [h[off:off + r.rows] for h in host]
                off += r.rows
                if r.expired(now):
                    self._shed(r, "resolve", DeadlineExceeded(
                        "serving: result arrived %.1fms past the "
                        "request deadline"
                        % ((now - r.deadline) * 1e3)))
                    continue
                r.req_span.__exit__(None, None, None)
                with self._lock:
                    self._stats["resolved"] += 1
                telemetry.counter_inc("serving.resolved")
                r.future.set_result(sl)
        except BaseException as e:
            # a failed FETCH is a batch-pipeline failure like a failed
            # launch: it feeds the breaker's consecutive count and the
            # futures resolve with the error (never strand)
            self._dispatch_failed()
            self._fail_requests(reqs, e)
            ids = [r.req_id for r in reqs]
            telemetry.record_event("serving.batch_failed", req_ids=ids,
                                   bucket=bucket,
                                   error=type(e).__name__)
            flight.postmortem("serving_dispatch_failure", exc=e,
                              extra={"req_ids": ids, "bucket": bucket,
                                     "engine": self.overload_state()})
        finally:
            self._inflight.release()
