"""Torch interop bridge (parity: python/mxnet/torch.py + plugin/torch).

The reference embeds Torch7 tensor math and NN modules as MXNet ops via
a C plugin, exposing them as ``mx.th.*``. The modern equivalent bridges
PyTorch: any ``torch.*`` function can be applied to NDArrays — arrays
hop host-side through numpy (torch in this image is CPU-only; the TPU
compute path stays JAX). Intended for glue/validation, not hot loops.
"""
from __future__ import annotations

import numpy as np

from .base import MXNetError
from . import ndarray as nd

__all__ = ["function", "apply"]

_torch = None
_torch_tried = False


def _require():
    # lazy: PyTorch costs ~1s+ of import time and real memory — only pay
    # on first bridge call, never at `import mxnet_tpu`
    global _torch, _torch_tried
    if not _torch_tried:
        _torch_tried = True
        try:
            import torch as _t  # absolute import: the real PyTorch
            _torch = _t
        except ImportError:  # pragma: no cover - torch is in the image
            _torch = None
    if _torch is None:
        raise MXNetError("PyTorch is not available in this environment")
    return _torch


def apply(fn_name, *args, **kwargs):
    """Apply ``torch.<fn_name>`` to NDArray/scalar args, returning NDArrays.

    Example::

        y = mx.torch.apply('sigmoid', x)
    """
    _t = _require()
    fn = getattr(_t, fn_name, None)
    if fn is None:
        raise MXNetError("torch has no function %r" % fn_name)
    t_args = [
        _t.from_numpy(np.array(a.asnumpy()))
        if isinstance(a, nd.NDArray) else a for a in args]
    out = fn(*t_args, **kwargs)
    if isinstance(out, (tuple, list)):
        return type(out)(
            nd.array(o.numpy()) if _t.is_tensor(o) else o for o in out)
    if _t.is_tensor(out):
        return nd.array(out.numpy())
    return out


def function(fn_name):
    """Return an NDArray-valued wrapper of ``torch.<fn_name>``."""
    _require()

    def wrapped(*args, **kwargs):
        return apply(fn_name, *args, **kwargs)
    wrapped.__name__ = fn_name
    wrapped.__doc__ = "NDArray bridge of torch.%s" % fn_name
    return wrapped


def __getattr__(name):
    # mx.torch.sigmoid(x) style access mirrors the reference's mx.th.*
    if name.startswith("_"):
        raise AttributeError(name)
    return function(name)
