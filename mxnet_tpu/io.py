"""Data iterators.

Parity: reference ``python/mxnet/io.py`` (DataIter/DataBatch/DataDesc/
NDArrayIter/ResizeIter/PrefetchingIter/MXDataIter) and ``src/io/``
(MNISTIter, CSVIter, LibSVMIter, ImageRecordIter — SURVEY.md §2.1 "Data IO
pipeline"). TPU-native design: host-side numpy pipeline with a
background prefetch thread double-buffering batches (≙ the reference's
dmlc::ThreadedIter in iter_prefetcher.h) and device_put overlap; the heavy
image path has a C++ RecordIO reader (src/ in this repo) with a Python
fallback.
"""
from __future__ import annotations

import gzip
import os
import struct
import threading
import queue as _queue
from collections import namedtuple

import numpy as np

from .base import MXNetError, registry_create
from .ndarray import array as _nd_array
from .ndarray.ndarray import NDArray
from . import telemetry
from . import faults

__all__ = ["DataDesc", "DataBatch", "DataIter", "NDArrayIter", "ResizeIter",
           "PrefetchingIter", "MNISTIter", "CSVIter", "LibSVMIter",
           "ImageRecordIter", "MXDataIter"]

register, _alias, create_iterator, _get = registry_create("data iterator")


class DataDesc(namedtuple("DataDesc", ["name", "shape"])):
    """(parity: io.DataDesc) name/shape/dtype/layout of one input."""

    def __new__(cls, name, shape, dtype=np.float32, layout="NCHW"):
        ret = super().__new__(cls, name, tuple(shape))
        ret.dtype = dtype
        ret.layout = layout
        return ret

    @staticmethod
    def get_batch_axis(layout):
        return 0 if layout is None else layout.find("N")


class DataBatch:
    """(parity: io.DataBatch)"""

    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label


def _poison_batch(batch):
    """The ``io_next`` site's ``nan`` payload transform: corrupt the
    batch's DATA arrays (NDArray or numpy) via ``faults.poison``,
    leaving labels intact — a poisoned label would fail loudly in the
    loss layer instead of exercising the numeric-divergence path."""
    data = batch.data
    single = not isinstance(data, (list, tuple))
    items = [data] if single else list(data)
    for i, arr in enumerate(items):
        if isinstance(arr, NDArray):
            arr[:] = faults.poison([arr.asnumpy()])[0]
        elif isinstance(arr, np.ndarray):
            items[i] = faults.poison([arr])[0]
    batch.data = items[0] if single else items


class DataIter:
    """Base iterator (parity: io.DataIter)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        # batch-iteration host span: time spent PRODUCING batches (file
        # reads, decode, numpy slicing) is the io phase of the merged
        # host+device trace. Only the epoch-end StopIteration cancels
        # the sample — a mid-epoch pipeline failure still charges the
        # time it burned to the io phase before propagating
        with telemetry.span("io_next") as sp:
            try:
                batch = self.next()
            except StopIteration:
                sp.cancel()
                raise
            # chaos site: a raise is a broken input pipeline; "nan" is
            # a corrupted batch (what the divergence sentinel exists to
            # catch) — poisoning the DATA arrays, labels left intact
            if faults.active() and faults.fire("io_next") == "nan":
                _poison_batch(batch)
            return batch

    def iter_next(self):
        raise NotImplementedError

    def getdata(self):
        raise NotImplementedError

    def getlabel(self):
        raise NotImplementedError

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (parity: io.NDArrayIter — the workhorse
    of tests and small trainers).

    Last-batch semantics under the data-parallel mesh: with
    ``last_batch_handle='pad'`` (the default) a short final batch is
    padded BY WRAPPING from the epoch head, so every emitted batch keeps
    the full ``batch_size`` — divisibility over the dp axis is checked
    ONCE at bind time and holds for every batch. ``DataBatch.pad``
    reports the wrapped count: ``predict``/``iter_predict`` slice those
    rows off; ``fit`` metrics include them (reference parity — epoch
    metrics over a padded tail count the wrapped rows). ``'discard'``
    drops the short tail instead. The iterator never emits a batch whose
    size differs from ``batch_size``; a hand-built DataBatch whose
    global batch does NOT divide over the dp axis is rejected by the
    Module feed path with a clear error — never silently padded."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label", num_parts=1, part_index=0):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True, default_name=label_name)
        if num_parts > 1:
            # distributed sharding (parity: dmlc InputSplit via the
            # reference iterators' num_parts/part_index kwargs — each
            # worker reads only its own partition)
            self.data = [(k, v[part_index::num_parts]) for k, v in self.data]
            self.label = [(k, v[part_index::num_parts])
                          for k, v in self.label]
        self.num_data = self.data[0][1].shape[0]
        self.cursor = -batch_size
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle
        self._order = np.arange(self.num_data)
        if shuffle:
            np.random.shuffle(self._order)
        if last_batch_handle == "discard":
            self.num_batches = self.num_data // batch_size
        else:
            self.num_batches = (self.num_data + batch_size - 1) // batch_size

    @property
    def provide_data(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, (self.batch_size,) + v.shape[1:], v.dtype)
                for k, v in self.label]

    def reset(self):
        self.cursor = -self.batch_size
        if self.shuffle:
            np.random.shuffle(self._order)

    def iter_next(self):
        self.cursor += self.batch_size
        if self.last_batch_handle == "discard":
            return self.cursor + self.batch_size <= self.num_data
        return self.cursor < self.num_data

    def _slice(self, arrays):
        out = []
        for name, arr in arrays:
            idx = self._order[self.cursor:self.cursor + self.batch_size]
            part = arr[idx]
            if len(idx) < self.batch_size:
                # pad by wrapping from the epoch head (parity: 'pad';
                # 'roll_over' emits the same full-size batch — every
                # batch keeps batch_size, which the dp mesh requires)
                extra = self._order[:self.batch_size - len(idx)]
                part = np.concatenate([part, arr[extra]], axis=0)
            out.append(_nd_array(part))
        return out

    def getdata(self):
        return self._slice(self.data)

    def getlabel(self):
        return self._slice(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0

    def getindex(self):
        return self._order[self.cursor:self.cursor + self.batch_size]


def _init_data(data, allow_empty, default_name):
    """Normalise input to a list of (name, numpy array) (parity: io._init_data)."""
    if data is None:
        if not allow_empty:
            raise MXNetError("data cannot be None")
        return []
    if isinstance(data, (NDArray, np.ndarray)):
        data = [(default_name, data)]
    elif isinstance(data, (list, tuple)):
        data = [("%s_%d" % (default_name, i) if len(data) > 1 else default_name,
                 d) for i, d in enumerate(data)]
    elif isinstance(data, dict):
        data = sorted(data.items())
    out = []
    for name, arr in data:
        if isinstance(arr, NDArray):
            arr = arr.asnumpy()
        out.append((name, np.asarray(arr)))
    return out


class ResizeIter(DataIter):
    """Resize an iterator to a fixed number of batches (parity: io.ResizeIter)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Background-thread prefetch (parity: io.PrefetchingIter ≙ the C++
    PrefetcherIter's ThreadedIter double buffering)."""

    def __init__(self, iters, rename_data=None, rename_label=None,
                 prefetch_depth=2):
        if not isinstance(iters, (list, tuple)):
            iters = [iters]
        super().__init__(iters[0].batch_size)
        if len(iters) != 1:
            raise MXNetError("PrefetchingIter supports a single backing iter "
                             "in the TPU build")
        self.iter = iters[0]
        self._prefetch_depth = prefetch_depth
        # how long reset() waits for the old worker to die before
        # raising (it can be wedged inside backing.next(), where a
        # replacement worker could not run safely)
        self.reset_join_timeout = 5.0
        self._queue = _queue.Queue(maxsize=prefetch_depth)
        self._stop = threading.Event()
        self._thread = None
        self._start()

    @property
    def provide_data(self):
        return self.iter.provide_data

    @property
    def provide_label(self):
        return self.iter.provide_label

    def _start(self):
        # the worker owns ITS stop event and queue as locals, bound at
        # start: reset() rebinding self._stop/self._queue can never be
        # observed mid-loop by a still-draining old worker (the
        # thread-race mxsync flagged — the old worker could miss the
        # swapped-in event and keep consuming the shared backing iter
        # concurrently with its replacement)
        stop, queue = self._stop, self._queue
        backing = self.iter

        def worker():
            while not stop.is_set():
                try:
                    batch = backing.next()
                except StopIteration:
                    queue.put(None)
                    return
                queue.put(batch)
        self._thread = threading.Thread(target=worker, daemon=True)
        self._thread.start()

    def reset(self):
        import time as _time
        self._stop.set()
        # drain UNTIL the worker is dead: a worker blocked in
        # queue.put() (queue full) only wakes when a slot frees, so a
        # single drain-then-join(5) could time out and leave the old
        # worker alive to race the replacement on the backing iter.
        # Bounded overall (reset_join_timeout): a worker wedged INSIDE
        # backing.next() (stalled data source) cannot observe the stop
        # event, and reset() must not hang the epoch boundary — but it
        # must not proceed either: the wedged worker's in-flight
        # next() would complete later, concurrently with the
        # replacement worker on the same non-thread-safe backing
        # iterator (silently stealing a batch / corrupting the
        # cursor). Raising is re-entrant: once the source unblocks the
        # worker exits on its own (its closure-captured stop is set),
        # and a later reset() proceeds cleanly.
        deadline = _time.monotonic() + self.reset_join_timeout
        while self._thread is not None and self._thread.is_alive():
            try:
                while True:
                    self._queue.get_nowait()
            except _queue.Empty:
                pass
            self._thread.join(timeout=0.05)
            if _time.monotonic() > deadline and self._thread.is_alive():
                raise MXNetError(
                    "PrefetchingIter.reset(): prefetch worker did not "
                    "exit within %.1fs — it is blocked inside the "
                    "backing iterator's next() (stalled data source?), "
                    "and resetting now would race it on the shared "
                    "backing iterator. Wait for the source to unblock "
                    "(or raise .reset_join_timeout) and call reset() "
                    "again." % self.reset_join_timeout)
        self.iter.reset()
        self._stop = threading.Event()
        # keep the CONFIGURED depth (the old code silently dropped a
        # custom prefetch_depth to 2 on the first reset)
        self._queue = _queue.Queue(maxsize=self._prefetch_depth)
        self._start()

    def next(self):
        batch = self._queue.get()
        if batch is None:
            raise StopIteration
        return batch

    def iter_next(self):
        raise NotImplementedError


# ---------------------------------------------------------------------------
# File-format iterators
# ---------------------------------------------------------------------------

def _read_idx_images(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, num, rows, cols = struct.unpack(">IIII", f.read(16))
        if magic != 2051:
            raise MXNetError("bad MNIST image file %r" % path)
        data = np.frombuffer(f.read(), dtype=np.uint8)
        return data.reshape(num, rows, cols)


def _read_idx_labels(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic, num = struct.unpack(">II", f.read(8))
        if magic != 2049:
            raise MXNetError("bad MNIST label file %r" % path)
        return np.frombuffer(f.read(), dtype=np.uint8)


@register(name="MNISTIter")
class MNISTIter(NDArrayIter):
    """MNIST idx-format reader (parity: src/io/iter_mnist.cc:80-260)."""

    def __init__(self, image="train-images-idx3-ubyte",
                 label="train-labels-idx1-ubyte", batch_size=128,
                 shuffle=True, flat=False, silent=False, seed=0,
                 input_shape=None, **kwargs):
        for p in (image, label):
            if not os.path.exists(p) and not os.path.exists(p + ".gz"):
                raise MXNetError("MNIST file %r not found" % p)
        image = image if os.path.exists(image) else image + ".gz"
        label = label if os.path.exists(label) else label + ".gz"
        imgs = _read_idx_images(image).astype(np.float32) / 255.0
        lbls = _read_idx_labels(label).astype(np.float32)
        if flat:
            imgs = imgs.reshape(len(imgs), -1)
        else:
            imgs = imgs.reshape(len(imgs), 1, imgs.shape[1], imgs.shape[2])
        super().__init__(imgs, lbls, batch_size=int(batch_size),
                         shuffle=bool(shuffle),
                         num_parts=int(kwargs.get("num_parts", 1)),
                         part_index=int(kwargs.get("part_index", 0)))


@register(name="CSVIter")
class CSVIter(NDArrayIter):
    """CSV reader (parity: src/io/iter_csv.cc)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, **kwargs):
        data = np.loadtxt(data_csv, delimiter=",", dtype=np.float32)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",", dtype=np.float32)
            label = label.reshape((-1,) + tuple(label_shape))
            if label.shape[1:] == (1,):
                label = label[:, 0]
        super().__init__(data, label, batch_size=int(batch_size),
                         last_batch_handle="pad" if round_batch else "discard",
                         num_parts=int(kwargs.get("num_parts", 1)),
                         part_index=int(kwargs.get("part_index", 0)))


@register(name="LibSVMIter")
class LibSVMIter(DataIter):
    """LibSVM sparse-format reader (parity: src/io/iter_libsvm.cc). Yields
    CSR data batches for the sparse linear-classification workload."""

    def __init__(self, data_libsvm, data_shape, label_shape=(1,),
                 batch_size=1, num_parts=1, part_index=0, **kwargs):
        super().__init__(int(batch_size))
        self.feature_dim = int(data_shape[0] if isinstance(data_shape, (tuple, list))
                               else data_shape)
        rows = []
        labels = []
        with open(data_libsvm) as f:
            for line in f:
                parts = line.strip().split()
                if not parts:
                    continue
                labels.append(float(parts[0]))
                row = {}
                for kv in parts[1:]:
                    k, v = kv.split(":")
                    row[int(k)] = float(v)
                rows.append(row)
        self._labels = np.asarray(labels, np.float32)
        dense = np.zeros((len(rows), self.feature_dim), np.float32)
        for i, row in enumerate(rows):
            for k, v in row.items():
                dense[i, k] = v
        if num_parts > 1:   # dmlc InputSplit parity: per-worker shard
            dense = dense[part_index::num_parts]
            self._labels = self._labels[part_index::num_parts]
            rows = rows[part_index::num_parts]
        self._dense = dense
        self.cursor = -self.batch_size
        self.num_data = len(rows)

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size, self.feature_dim))]

    @property
    def provide_label(self):
        return [DataDesc("softmax_label", (self.batch_size,))]

    def reset(self):
        self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def getdata(self):
        from .ndarray import sparse as _sp
        sl = self._dense[self.cursor:self.cursor + self.batch_size]
        if sl.shape[0] < self.batch_size:
            sl = np.concatenate(
                [sl, self._dense[:self.batch_size - sl.shape[0]]], axis=0)
        return [_sp.csr_matrix(sl)]

    def getlabel(self):
        sl = self._labels[self.cursor:self.cursor + self.batch_size]
        if sl.shape[0] < self.batch_size:
            sl = np.concatenate(
                [sl, self._labels[:self.batch_size - sl.shape[0]]], axis=0)
        return [_nd_array(sl)]

    def getpad(self):
        over = self.cursor + self.batch_size - self.num_data
        return max(over, 0)


@register(name="ImageRecordIter")
class ImageRecordIter(DataIter):
    """RecordIO image iterator (parity: src/io/iter_image_recordio_2.cc).

    Reads packed RecordIO (see recordio.py / src/recordio.cc). Raw uint8
    payloads decode directly; encoded payloads (JPEG/PNG/...) decode via
    PIL with crop/mirror augmentation. The optional C++ pipeline
    (src/recordio.cc) accelerates the unaugmented raw path. round_batch
    wraps the final partial batch to the epoch head and reports the
    wrapped count in ``DataBatch.pad`` (reference round_batch semantics);
    round_batch=False drops the partial tail.
    """

    def __init__(self, path_imgrec, data_shape, batch_size, label_width=1,
                 shuffle=False, rand_crop=False, rand_mirror=False,
                 mean_r=0.0, mean_g=0.0, mean_b=0.0, std_r=1.0, std_g=1.0,
                 std_b=1.0, scale=1.0, preprocess_threads=4, seed=0,
                 num_parts=1, part_index=0, round_batch=True, **kwargs):
        super().__init__(int(batch_size))
        from . import recordio
        self.data_shape = tuple(int(x) for x in data_shape)
        self.label_width = int(label_width)
        self.shuffle = shuffle
        self.rand_crop = rand_crop
        self.rand_mirror = rand_mirror
        c = int(data_shape[0])
        means = [mean_r, mean_g, mean_b][:c] if c <= 3 else [mean_r] * c
        stds = [std_r, std_g, std_b][:c] if c <= 3 else [std_r] * c
        self.mean = np.array(means, np.float32).reshape(c, 1, 1)
        self.std = np.array(stds, np.float32).reshape(c, 1, 1)
        self.scale = scale
        self.rec = recordio.MXRecordIO(path_imgrec, "r")
        self._records = []
        while True:
            s = self.rec.read()
            if s is None:
                break
            self._records.append(s)
        if num_parts > 1:   # dmlc InputSplit parity: per-worker shard
            self._records = self._records[part_index::num_parts]
        self._round_batch = bool(round_batch)
        # fast path: native threaded loader (src/recordio.cc) when built and
        # no python-side augmentation is requested. Three disqualifiers keep
        # the semantics build-independent: sharded reads (no partition
        # support in the native scan), encoded payloads (recordio.cc has no
        # JPEG decode — it would read compressed bytes as pixels), and a
        # partial tail under round_batch (the native loader drops it,
        # python wraps-and-pads it).
        self._native = None
        if not rand_crop and not rand_mirror and self.label_width == 1 \
                and num_parts == 1 and not self._records_encoded() \
                and not (self._round_batch
                         and len(self._records) % self.batch_size != 0):
            try:
                from ._native import NativeRecordLoader
                self._native = NativeRecordLoader(
                    path_imgrec, int(batch_size), self.data_shape,
                    num_threads=int(preprocess_threads),
                    shuffle=bool(shuffle), seed=int(seed), scale=scale,
                    mean=(mean_r, mean_g, mean_b), std=(std_r, std_g, std_b))
            except Exception:
                self._native = None
        self._order = np.arange(len(self._records))
        self.cursor = -self.batch_size

    def _open_encoded(self, img):
        """Return a loaded PIL image if the payload is an encoded image,
        else None. The single source of truth shared by the per-record
        decoder and the native-loader eligibility scan: encoded means the
        payload starts with an image magic AND PIL accepts it (raw pixels
        that merely start with a magic byte pair fall back to raw)."""
        if not (img[:2] in self._IMG_MAGIC or img[:3] in self._IMG_MAGIC):
            return None
        import io as _pyio
        from PIL import Image
        try:
            pic = Image.open(_pyio.BytesIO(img))
            pic.load()
            return pic
        except Exception:
            return None

    def _records_encoded(self):
        """True if ANY payload is an encoded image rather than raw pixels
        (records may mix; one encoded record rules out the native raw
        loader). The magic sniff short-circuits almost every raw record;
        PIL runs only on magic collisions."""
        from . import recordio
        return any(
            self._open_encoded(recordio.unpack(r)[1]) is not None
            for r in self._records)

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 \
            else (self.batch_size, self.label_width)
        return [DataDesc("softmax_label", shape)]

    def reset(self):
        self.cursor = -self.batch_size
        if self._native is not None:
            self._native.reset()
        if self.shuffle:
            np.random.shuffle(self._order)

    def next(self):
        if self._native is not None:
            try:
                data, label = self._native.next()
            except StopIteration:
                raise
            return DataBatch([_nd_array(data)], [_nd_array(label)], pad=0)
        return super().next()

    def iter_next(self):
        self.cursor += self.batch_size
        if self._round_batch:
            # last partial batch wraps to the epoch head; pad reports the
            # wrapped count (parity: iter_image_recordio_2.cc round_batch)
            return self.cursor < len(self._records)
        return self.cursor + self.batch_size <= len(self._records)

    def _batch_indices(self):
        idx = self._order[self.cursor:self.cursor + self.batch_size]
        while len(idx) < self.batch_size:    # wrap (repeatedly, if the
            idx = np.concatenate(            # dataset is < one batch)
                [idx, self._order[:self.batch_size - len(idx)]])
        return idx

    _IMG_MAGIC = (b"\xff\xd8", b"\x89PN", b"BM", b"GIF")

    def _decode(self, s):
        from . import recordio
        header, img = recordio.unpack(s)
        c, h, w = self.data_shape
        # encoded payload (JPEG/PNG/...): PIL decode, then crop to
        # data_shape — random when rand_crop, centred otherwise
        # (parity: iter_image_recordio_2.cc's ImageAugmenter)
        pic = self._open_encoded(img)
        if pic is not None:
            if c == 1:
                pic = pic.convert("L")
            elif c == 3:
                if pic.mode != "RGB":
                    pic = pic.convert("RGB")
            else:
                raise MXNetError(
                    "encoded-image decode supports 1 or 3 channels, "
                    "data_shape has %d" % c)
            if pic.width < w or pic.height < h:
                pic = pic.resize((max(w, pic.width), max(h, pic.height)))
            dx, dy = pic.width - w, pic.height - h
            if self.rand_crop:
                x0 = np.random.randint(0, dx + 1)
                y0 = np.random.randint(0, dy + 1)
            else:
                x0, y0 = dx // 2, dy // 2
            pic = pic.crop((x0, y0, x0 + w, y0 + h))
            arr = np.asarray(pic, dtype=np.float32)
            arr = arr[:, :, None] if arr.ndim == 2 else arr
            arr = arr.transpose(2, 0, 1)
        else:
            arr = np.frombuffer(img, dtype=np.uint8)
            if arr.size >= c * h * w:
                arr = arr[:c * h * w].reshape(c, h, w).astype(np.float32)
            else:
                raise MXNetError("record payload too small for raw decode "
                                 "and not an encoded image")
        if self.rand_mirror and np.random.rand() < 0.5:
            arr = arr[:, :, ::-1]
        arr = (arr * self.scale - self.mean) / self.std
        label = header.label
        return arr, label

    def getdata(self):
        idx = self._batch_indices()
        batch = np.stack([self._decode(self._records[i])[0] for i in idx])
        return [_nd_array(batch)]

    def getlabel(self):
        from . import recordio
        idx = self._batch_indices()
        # labels live in the record header — no image decode needed
        labels = np.array(
            [np.atleast_1d(recordio.unpack(self._records[i])[0].label)
             for i in idx], np.float32)
        if self.label_width == 1:
            labels = labels[:, 0]
        return [_nd_array(labels)]

    def getpad(self):
        remain = len(self._records) - self.cursor
        return max(0, self.batch_size - remain) if remain > 0 else 0


class MXDataIter(DataIter):
    """Wrapper giving a registry-created iterator the reference's
    C-handle-iterator face (parity: io.MXDataIter — there the handle is a
    C iterator; here it wraps any registered python iterator)."""

    def __init__(self, handle, data_name="data", label_name="softmax_label",
                 **_):
        if isinstance(handle, DataIter):
            self._iter = handle
        else:
            raise MXNetError("MXDataIter wraps a created iterator; use "
                             "mx.io.<IterName>(...) or "
                             "create_iterator(name, **kwargs)")
        super().__init__(self._iter.batch_size)

    def __getattr__(self, name):
        return getattr(self.__dict__["_iter"], name)

    def reset(self):
        self._iter.reset()

    def next(self):
        return self._iter.next()

    def iter_next(self):
        return self._iter.iter_next()

    def getdata(self):
        return self._iter.getdata()

    def getlabel(self):
        return self._iter.getlabel()

    def getindex(self):
        return self._iter.getindex()

    def getpad(self):
        return self._iter.getpad()
