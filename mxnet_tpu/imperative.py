"""Imperative runtime: eager op invocation + autograd tape.

TPU-native re-design of the reference's imperative layer
(``src/imperative/imperative.cc`` — Invoke/RecordOp/Backward) and its
dependency engine. The reference needed a dataflow engine
(``src/engine/threaded_engine*.cc``) to overlap async GPU kernels; on TPU,
PJRT *is* that engine: every jax op dispatches asynchronously onto the
device stream in program order, and ``block_until_ready`` is WaitToRead.
So "engine push" collapses to a function call here, and what remains is
the tape: when recording, each op invocation stores the ``jax.vjp``
residual so ``backward()`` can walk the graph — the same role the
reference's per-node ``AGInfo`` (``imperative.h:40-77``) plays.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .base import MXNetError
from .ops import registry as _registry
from .ops import common as _common

__all__ = ["invoke", "is_recording", "is_training", "set_recording",
           "set_training", "backward", "mark_variables", "get_symbol"]


# ---------------------------------------------------------------------------
# Recording state (parity: Imperative::is_recording/is_training)
# ---------------------------------------------------------------------------

def is_recording():
    return _common.state().recording


def is_training():
    return _common.state().train_mode


def set_recording(flag):
    prev = _common.state().recording
    _common.state().recording = bool(flag)
    return prev


def set_training(flag):
    prev = _common.state().train_mode
    _common.state().train_mode = bool(flag)
    return prev


# ---------------------------------------------------------------------------
# Tape graph
# ---------------------------------------------------------------------------

class TapeNode:
    """One recorded op (parity: reference AGInfo node).

    ``parents[i]`` is ``(TapeNode | Leaf | None, out_index)`` for input i.
    ``vjp_fn`` maps output cotangents -> input cotangents.
    """

    __slots__ = ("parents", "vjp_fn", "out_avals", "op_name",
                 "pure_fn", "raw_inputs", "op", "params")

    def __init__(self, parents, vjp_fn, out_avals, op_name):
        self.pure_fn = None
        self.raw_inputs = None
        self.op = None
        self.params = None
        self.parents = parents
        self.vjp_fn = vjp_fn
        self.out_avals = out_avals
        self.op_name = op_name


class Leaf:
    """A marked variable (parity: mark_variables / attach_grad)."""

    __slots__ = ("array", "grad_req")

    def __init__(self, array, grad_req="write"):
        self.array = array  # the NDArray owning this leaf
        self.grad_req = grad_req


def mark_variables(variables, gradients, grad_reqs="write"):
    """Attach gradient buffers (parity: autograd.mark_variables,
    reference imperative.cc MarkVariables)."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for var, grad, req in zip(variables, gradients, grad_reqs):
        var._grad = grad
        var._tape = (Leaf(var, req), 0)


# ---------------------------------------------------------------------------
# Invoke
# ---------------------------------------------------------------------------

def _as_raw(x):
    from .ndarray.ndarray import NDArray
    if isinstance(x, NDArray):
        return x._data
    return x


def invoke(op, inputs, kwargs, out=None, name=None):
    """Execute one op eagerly (parity: Imperative::Invoke, imperative.cc:86).

    ``inputs`` are NDArrays (or raw arrays); ``kwargs`` the op params.
    Returns a single NDArray or a list, honouring op.visible_outputs.
    """
    from .ndarray.ndarray import NDArray, _wrap

    params = dict(op.defaults)
    params.update(kwargs)
    # prune wrapper-only kwargs the op fns don't take
    params.pop("name", None)
    if op.nin != 0:
        params.pop("ctx", None)

    if op.takes_train:
        params["_train"] = is_training()
    if op.takes_rng:
        params["_rng"] = _common.take_rng()

    nds = [x if isinstance(x, NDArray) else None for x in inputs]

    record = (is_recording() and not op.no_grad
              and any(nd is not None and nd._tape is not None for nd in nds))

    # storage-aware dispatch BEFORE any dense view is touched: sparse
    # inputs first consult the FComputeEx table (reference operator-attr
    # machinery, imperative.cc dispatch-mode selection). No native
    # kernel for the combination -> logged storage fallback, then the
    # dense path below (src/common/utils.h CastStorageDispatch role).
    # Recording takes the dense path too: sparse autograd surfaces that
    # need compressed grads (sparse.dot, embeddings) manage their own
    # tape nodes.
    if (not record and out is None
            and any(nd is not None and nd.stype != "default" for nd in nds)):
        from .ndarray import sparse as _sparse
        res = _sparse.dispatch_ex(op.name, inputs, params)
        if res is not NotImplemented:
            return res
        from .config import storage_fallback_log
        storage_fallback_log("%s(%s)" % (
            op.name,
            ", ".join(nd.stype if nd is not None else "default"
                      for nd in nds)))

    raw = [_as_raw(x) for x in inputs]

    if op.jit_cache:
        jfn, dyn = op.jitted(params)

        def _pure(*arrs):
            return jfn(arrs, dyn)
    else:
        def _pure(*arrs):
            outs = op.fn(*arrs, **params)
            return outs if isinstance(outs, tuple) else (outs,)

    if record:
        outs, vjp_fn = jax.vjp(_pure, *raw)
        parents = [nd._tape if (nd is not None and nd._tape is not None) else None
                   for nd in nds]
        node = TapeNode(parents, vjp_fn,
                        [jax.ShapeDtypeStruct(o.shape, o.dtype) for o in outs],
                        op.name)
        # replay handles for higher-order grad (autograd.grad
        # create_graph=True rebuilds a pure function from the tape) and
        # symbol reconstruction (autograd.get_symbol). Only CONSTANT
        # (off-tape) inputs are retained — replay recomputes on-tape
        # values from parents, so pinning them would inflate peak memory
        # of every eager step for a rarely-used feature
        node.pure_fn = _pure
        node.raw_inputs = [r if p is None else None
                           for r, p in zip(raw, parents)]
        node.op = op
        node.params = {k: v for k, v in params.items()
                       if k not in ("_train", "_rng")}
    else:
        outs = _pure(*raw)
        node = None

    # stateful aux updates (BatchNorm moving stats). During graph capture
    # the values are tracers: collect them for writeback-after-execution
    # instead of assigning (gluon/_CachedOp installs the collector).
    if op.stateful_update is not None:
        updates = op.stateful_update(raw, outs, params)
        collector = _common.state().aux_collector
        for idx, val in updates.items():
            if nds[idx] is not None:
                if collector is not None:
                    collector.append((nds[idx], val))
                else:
                    nds[idx]._set_data(val)

    # in-place mutation ops (optimizer updates): output j writes input mutate[j]
    if op.mutate:
        for j, idx in enumerate(op.mutate):
            if j < len(outs) and nds[idx] is not None:
                nds[idx]._set_data(outs[j])
        primary = nds[op.mutate[0]]
        if out is not None and out is not primary:
            out._set_data(outs[0])
            return out
        return primary

    vis = op.visible_outputs
    if callable(vis):
        vis = vis(params)
    n_visible = vis or len(outs)
    n_visible = min(n_visible, len(outs))
    results = []
    for i in range(n_visible):
        nd_out = _wrap(outs[i])
        if node is not None:
            nd_out._tape = (node, i)
        results.append(nd_out)

    if out is not None:
        targets = out if isinstance(out, (list, tuple)) else [out]
        for t, r in zip(targets, results):
            t._set_data(r._data)
            t._tape = r._tape
        return out
    if n_visible == 1:
        return results[0]
    return results


# ---------------------------------------------------------------------------
# Backward pass over the tape
# ---------------------------------------------------------------------------

def backward(outputs, head_grads=None, retain_graph=False, train_mode=True):
    """Run reverse-mode over recorded ops (parity: Imperative::Backward,
    reference imperative.cc:361).

    outputs: list of NDArrays to differentiate; head_grads: matching list
    of NDArrays or None (=> ones).
    """
    from .ndarray.ndarray import NDArray

    if head_grads is None:
        head_grads = [None] * len(outputs)

    # cotangent accumulator keyed by (id(node), out_idx)
    cotangents = {}
    node_of = {}

    def _acc(node, idx, val):
        key = (id(node), idx)
        node_of[id(node)] = node
        if key in cotangents:
            cotangents[key] = cotangents[key] + val
        else:
            cotangents[key] = val

    roots = []
    for y, hg in zip(outputs, head_grads):
        if y._tape is None:
            continue
        node, idx = y._tape
        g = hg._data if isinstance(hg, NDArray) else (
            jnp.ones(y.shape, y.dtype) if hg is None else jnp.asarray(hg))
        _acc(node, idx, g)
        roots.append(node)
    if not roots:
        raise MXNetError("backward: outputs are not in a recorded graph "
                         "(use autograd.record())")

    # topological order over TapeNodes (DFS, iterative)
    order = []
    state = {}
    stack = [(r, False) for r in dict.fromkeys(roots)]
    while stack:
        node, processed = stack.pop()
        if isinstance(node, Leaf) or node is None:
            continue
        if processed:
            order.append(node)
            continue
        if state.get(id(node)):
            continue
        state[id(node)] = True
        stack.append((node, True))
        for p in node.parents:
            if p is not None and isinstance(p[0], TapeNode):
                if not state.get(id(p[0])):
                    stack.append((p[0], False))

    # reverse topo: propagate; leaf cotangents accumulate here and are
    # written out once at the end (a leaf may feed many ops).
    leaf_cts = {}
    for node in reversed(order):
        outs_ct = []
        for i, aval in enumerate(node.out_avals):
            ct = cotangents.get((id(node), i))
            if ct is None:
                ct = jnp.zeros(aval.shape, aval.dtype)
            outs_ct.append(ct)
        in_cts = node.vjp_fn(tuple(outs_ct))
        for parent, ct in zip(node.parents, in_cts):
            if parent is None:
                continue
            if hasattr(ct, "dtype") and ct.dtype == jax.dtypes.float0:
                continue
            pnode, pidx = parent
            if isinstance(pnode, Leaf):
                key = id(pnode)
                if key in leaf_cts:
                    leaf_cts[key] = (pnode, leaf_cts[key][1] + ct)
                else:
                    leaf_cts[key] = (pnode, ct)
            else:
                _acc(pnode, pidx, ct)
        if not retain_graph:
            node.vjp_fn = None

    for leaf, ct in leaf_cts.values():
        _write_leaf(leaf, ct)


def _write_leaf(leaf, cotangent):
    var = leaf.array
    if var._grad is None:
        return
    if leaf.grad_req == "add":
        var._grad._set_data(var._grad._data + cotangent.astype(var._grad.dtype))
    elif leaf.grad_req != "null":
        var._grad._set_data(cotangent.astype(var._grad.dtype))


def get_symbol(x):
    """Rebuild a Symbol from the recorded graph (parity:
    autograd.get_symbol / C MXAutogradGetSymbol — the reference converts
    the tape's nnvm nodes back to a Symbol; here the tape nodes carry
    (op, params) so the same reconstruction applies). Leaves and
    constant inputs become Variables with generated names."""
    from .symbol.symbol import Symbol, _SymNode
    from .ndarray.ndarray import NDArray
    if not isinstance(x, NDArray) or x._tape is None:
        raise MXNetError("get_symbol: array is not part of a recorded "
                         "graph (use autograd.record())")
    cache = {}
    counters = {}

    def name_for(base):
        i = counters.get(base, 0)
        counters[base] = i + 1
        return "%s%d" % (base, i)

    def conv(node):
        got = cache.get(id(node))
        if got is not None:
            return got
        if isinstance(node, Leaf):
            sn = _SymNode(None, name_for("var"), {}, [])
        else:
            if node.op is None:
                raise MXNetError("get_symbol: node %r has no symbol info "
                                 "(grad-of-grad nodes are not "
                                 "symbolisable)" % node.op_name)
            inputs = []
            for j, p in enumerate(node.parents):
                if p is None:
                    inputs.append((_SymNode(None, name_for("const"), {},
                                            []), 0))
                else:
                    inputs.append((conv(p[0]), p[1]))
            sn = _SymNode(node.op, name_for(node.op.name.lower()),
                          node.params or {}, inputs)
        cache[id(node)] = sn
        return sn

    n, i = x._tape
    return Symbol([(conv(n), i)])


# ---------------------------------------------------------------------------
# Higher-order support: rebuild a pure function from the tape
# ---------------------------------------------------------------------------

def build_pure_from_tape(outputs):
    """Replay the recorded subgraph as a pure jax function of EVERY leaf
    it touches (a grad that stays differentiable w.r.t. only a subset of
    leaves would silently lose cross-derivatives). Returns
    ``(replay, leaves)`` — ``replay(*leaf_raws) -> output_raws`` and the
    ordered list of Leaf nodes matching the argument order. Powers
    autograd.grad(create_graph=True): jax differentiates the replayed
    function to any order."""
    leaves = []
    leaf_pos = {}
    seen = set()
    stack = [y._tape[0] for y in outputs if y._tape is not None]
    while stack:
        node = stack.pop()
        if id(node) in seen:
            continue
        seen.add(id(node))
        if isinstance(node, Leaf):
            leaf_pos[id(node)] = len(leaves)
            leaves.append(node)
            continue
        if node.pure_fn is None:
            raise MXNetError(
                "higher-order grad: tape node %r has no replay info"
                % node.op_name)
        for p in node.parents:
            if p is not None:
                stack.append(p[0])

    def replay(*leaf_raws):
        cache = {}

        def eval_node(node):
            got = cache.get(id(node))
            if got is not None:
                return got
            if isinstance(node, Leaf):
                val = (leaf_raws[leaf_pos[id(node)]],)
            else:
                args = []
                for j, p in enumerate(node.parents):
                    if p is None:
                        args.append(node.raw_inputs[j])
                    else:
                        pn, pi = p
                        args.append(eval_node(pn)[pi])
                val = node.pure_fn(*args)
            cache[id(node)] = val
            return val

        outs = []
        for y in outputs:
            n, i = y._tape
            outs.append(eval_node(n)[i])
        return tuple(outs)

    return replay, leaves
