"""Module API (parity: python/mxnet/module/__init__.py)."""
from .base_module import BaseModule, FusedFallback, FUSED_FALLBACK_CODES
from .module import Module
from .bucketing_module import BucketingModule
from .sequential_module import SequentialModule
from .python_module import PythonModule, PythonLossModule
from . import executor_group
from .executor_group import DataParallelExecutorGroup
