"""DataParallelExecutorGroup — compatibility facade.

Parity: reference ``python/mxnet/module/executor_group.py:128`` which
splits each batch across GPU contexts and keeps one executor per device
(decide_slices:266). TPU-native design: batch splitting across chips is a
SHARDING of one executor's program, not N executors — a multi-context
group commits the dp mesh placements (batch split over the ``dp`` axis,
params/grads replicated) on its ONE executor, and each fed batch is a
single sharded device_put of the GLOBAL batch (no decide_slices host
splitting); XLA partitions the program over the mesh and inserts the ICI
collectives (see mxnet_tpu.parallel). This class keeps the reference API
for code that instantiates it directly. The performance-critical train
loop does NOT live here: ``Module.fit``/``Module.fused_step`` compile the
whole step (forward+backward+optimizer+metric) into one donated-buffer
XLA program (``executor._GraphProgram.train_step_fn``; PERF.md "Module.fit
gap") — this facade only covers the reference's phase-by-phase surface.
"""
from __future__ import annotations

from ..base import MXNetError


def decide_slices(batch_size, work_load_list):
    """Split a batch between workers proportionally (parity:
    executor_group.decide_slices:266); retained for API compatibility —
    the TPU-native path does NOT slice on the host, it shards ONE
    device_put over the mesh (see DataParallelExecutorGroup)."""
    total = sum(work_load_list)
    slices = []
    start = 0
    for w in work_load_list:
        n = int(round(batch_size * w / total))
        slices.append(slice(start, start + n))
        start += n
    if start != batch_size and slices:
        last = slices[-1]
        slices[-1] = slice(last.start, batch_size)
    return slices


class DataParallelExecutorGroup:
    """(parity: executor_group.DataParallelExecutorGroup:128)"""

    def __init__(self, symbol, contexts, workload, data_shapes, label_shapes,
                 param_names, for_training, inputs_need_grad,
                 shared_group=None, logger=None, fixed_param_names=None,
                 grad_req="write", state_names=None):
        self.symbol = symbol
        self.contexts = contexts
        self.param_names = param_names
        self.for_training = for_training
        self.data_shapes = list(data_shapes)
        self.label_shapes = list(label_shapes) if label_shapes else []
        shape_kwargs = {name: shape for name, shape in
                        [(d[0], d[1]) for d in data_shapes]}
        if label_shapes:
            shape_kwargs.update({l[0]: l[1] for l in label_shapes})
        reqs = {}
        for name in symbol.list_arguments():
            if name in (fixed_param_names or []):
                reqs[name] = "null"
            elif name in param_names:
                reqs[name] = grad_req if for_training else "null"
            else:
                reqs[name] = "write" if inputs_need_grad else "null"
        self.execs = [symbol.simple_bind(ctx=contexts[0], grad_req=reqs,
                                         **shape_kwargs)]
        self._dp_spec = None
        if len(contexts) > 1:
            self._init_dp(shape_kwargs, state_names)

    def _init_dp(self, shape_kwargs, state_names):
        """Commit the dp-mesh placements on the single executor: the
        global batch must divide over the data axis (same clear error as
        Module.bind — no silent pad), inputs shard over ``dp``, params/
        grads replicate (the shared ``commit_dp_placements`` rule —
        Module commits the same way). GSPMD then splits every program
        this executor runs and inserts the gradient all-reduce."""
        from ..parallel import mesh as _pmesh, spmd as _spmd
        spec = _spmd.dp_spec(_pmesh.mesh_from_contexts(self.contexts))
        for shapes in (self.data_shapes, self.label_shapes):
            for d in shapes:
                shape = d[1] if isinstance(d, (list, tuple)) else d.shape
                if shape:
                    _spmd.check_batch_divisible(shape[0], spec.num_devices,
                                                "batch size")
        self._dp_spec = spec
        input_names = set(shape_kwargs) | set(state_names or ())
        _spmd.commit_dp_placements(self.execs[0], input_names, spec)

    def forward(self, data_batch, is_train=None):
        """Install the batch into bound storage and run the forward
        program (the old facade discarded the batch — any direct user
        forward-ran stale data). Executor.forward owns the copy-in and
        the ``feed``/``step`` telemetry spans; the facade counts its
        own traffic so the snapshot shows which surface drove the
        executor."""
        from .. import telemetry
        telemetry.counter_inc("exec_group.forward")
        data = data_batch.data
        if not isinstance(data, (list, tuple)):
            data = [data]
        names = [d[0] if isinstance(d, (list, tuple)) else d.name
                 for d in self.data_shapes]
        feed = dict(zip(names, data))
        label = getattr(data_batch, "label", None)
        if label is not None and self.label_shapes:
            if not isinstance(label, (list, tuple)):
                label = [label]
            lnames = [l[0] if isinstance(l, (list, tuple)) else l.name
                      for l in self.label_shapes]
            feed.update(zip(lnames, label))
        self.execs[0].forward(is_train=bool(is_train), **feed)

    def backward(self, out_grads=None):
        self.execs[0].backward(out_grads=out_grads)

    def get_outputs(self, merge_multi_context=True):
        return self.execs[0].outputs
