"""Module — symbolic training on a bound executor.

Parity: reference ``python/mxnet/module/module.py``. TPU-native design:
where the reference builds a DataParallelExecutorGroup with one executor
per GPU and reduces through KVStore (executor_group.py:128,
model.py:106-138), this Module binds ONE executor whose compiled program
covers the whole (possibly mesh-sharded) computation — multi-chip data
parallelism is expressed as sharding on the same program
(mxnet_tpu.parallel), not as replicated executors, because XLA then
schedules the ICI all-reduce inside the step. The KVStore push/pull
protocol is still honoured when a kvstore is provided
(update_on_kvstore ≙ reference semantics).
"""
from __future__ import annotations

import logging

import numpy as np

from ..base import MXNetError
from ..config import fused_fit
from ..context import Context, cpu, current_context
from ..executor import record_dispatch
from .. import telemetry
from ..initializer import Uniform, InitDesc
from ..model import _create_kvstore, save_checkpoint, load_checkpoint
from .. import optimizer as opt
from ..ndarray.ndarray import NDArray, zeros, _wrap
from .base_module import BaseModule, FusedFallback, _as_list


class Module(BaseModule):
    """(parity: module.Module)"""

    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging, context=None,
                 work_load_list=None, fixed_param_names=None,
                 state_names=None, group2ctxs=None,
                 compression_params=None, partition_rules=None,
                 mesh_axes=None):
        """``partition_rules`` (a ``parallel.partition.PartitionRules``
        tree) + ``mesh_axes`` (ordered ``{axis: size}``, e.g.
        ``{"dp": 2, "mp": 4}``; one size may be -1) lay a multi-device
        context list out as a rule-sharded dp x mp mesh: the batch
        shards over ``dp``, each parameter takes its first-matching
        rule's PartitionSpec (UNMATCHED policy: replicate or error),
        and the fused train step runs ONE donated SPMD program with
        gradients reduced over ``dp`` only and mp-sharded parameters
        never gathered. Ignored on a single-context bind."""
        super().__init__(logger=logger)
        if context is None:
            context = current_context()
        if isinstance(context, Context):
            context = [context]
        self._context = context
        self._symbol = symbol
        self._data_names = list(data_names or [])
        self._label_names = list(label_names or [])
        self._state_names = list(state_names or [])
        self._fixed_param_names = list(fixed_param_names or [])
        arg_names = symbol.list_arguments()
        input_names = self._data_names + self._label_names + self._state_names
        self._param_names = [n for n in arg_names if n not in input_names]
        self._group2ctxs = group2ctxs
        self._aux_names = symbol.list_auxiliary_states()
        self._output_names = symbol.list_outputs()
        self._arg_params = None
        self._aux_params = None
        self._params_dirty = False
        self._optimizer = None
        self._kvstore = None
        self._update_on_kvstore = None
        self._updater = None
        self._exec = None
        self._data_shapes = None
        self._label_shapes = None
        self._grad_req = None
        self._mesh = None
        self._dp_spec = None
        self._data_sharding = None
        self._repl_sharding = None
        self._partition_rules = partition_rules
        self._mesh_axes = dict(mesh_axes) if mesh_axes else None
        self._fused_fallback_reason = None
        self._fused_plan = None
        # the dist tier (multi-process dist_* kvstore): a PROCESS-
        # SPANNING dp mesh the fused step jits over, committed lazily
        # and dropped whenever a step must phase-split (the explicit
        # kvstore wire needs LOCAL gradients, not psummed ones)
        self._dist_spec = None
        self._dist_committed = False
        self._dist_synced = False
        self._step_gate = None
        self._dist_sync_handle = None

    # -- introspection -----------------------------------------------------
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        assert self.binded
        return self._data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        if self._exec.outputs:
            return [(n, o.shape) for n, o in
                    zip(self._output_names, self._exec.outputs)]
        # before the first forward, infer from the bound input shapes —
        # the reference has these available right after bind (executor
        # group infers at bind time), and SequentialModule.bind chains
        # stages through this property
        shape_kwargs = {d.name: d.shape for d in self._data_shapes}
        shape_kwargs.update({l.name: l.shape for l in self._label_shapes})
        _, out_shapes, _ = self._symbol.infer_shape(**shape_kwargs)
        return list(zip(self._output_names, out_shapes))

    # -- bind --------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        """(parity: module.py bind:363)"""
        if force_rebind:
            self._exec = None
            self.binded = False
        if self.binded:
            self.logger.warning("Already bound, ignoring bind()")
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad

        self._data_shapes = [_as_desc(d) for d in data_shapes]
        self._label_shapes = [_as_desc(l) for l in label_shapes] \
            if label_shapes else []

        shape_kwargs = {d.name: d.shape for d in self._data_shapes}
        shape_kwargs.update({l.name: l.shape for l in self._label_shapes})
        # input dtypes flow from DataDesc into the joint InferShape/Type
        # pass, so a bf16 data desc binds a bf16 executor end to end.
        # Labels included: without an explicit entry the inference pass
        # would anchor the label var to the data dtype (bf16 truncates
        # class indices > 256).
        type_dict = {d.name: d.dtype
                     for d in self._data_shapes + self._label_shapes
                     if getattr(d, "dtype", None) is not None}

        reqs = {}
        for name in self._symbol.list_arguments():
            if name in self._data_names:
                reqs[name] = "write" if inputs_need_grad else "null"
            elif name in self._label_names or name in self._state_names:
                reqs[name] = "null"
            elif name in self._fixed_param_names:
                reqs[name] = "null"
            else:
                reqs[name] = grad_req if for_training else "null"
        self._grad_req = reqs
        ctx = self._context[0]
        g2c = self._group2ctxs
        if isinstance(g2c, (list, tuple)):
            # reference group2ctxs is a per-context list; the single-exec
            # module uses the first entry
            g2c = g2c[0] if g2c else None
        if g2c and len(self._context) > 1:
            # grouped programs pin ops to concrete devices (eager
            # per-segment execution); the dp mesh shards ONE jitted
            # program — the two placements are mutually exclusive
            raise MXNetError(
                "group2ctxs cannot be combined with a multi-device "
                "context list; use a single context for model "
                "parallelism or drop group2ctxs for data parallelism")
        self._exec = self._symbol.simple_bind(ctx=ctx, grad_req=reqs,
                                              type_dict=type_dict,
                                              group2ctx=g2c,
                                              **shape_kwargs)
        if len(self._context) > 1:
            self._init_mesh()
        self.binded = True
        if shared_module is not None and shared_module.params_initialized:
            arg_p, aux_p = shared_module.get_params()
            self.set_params(arg_p, aux_p)
        elif self.params_initialized and self._arg_params is not None:
            # Module.load path: checkpointed params install at bind time
            self.set_params(self._arg_params, self._aux_params or {})

    # -- multi-device mesh (TPU-native DataParallelExecutorGroup) ----------
    def _init_mesh(self):
        """N contexts = a mesh over N chips: the reference builds one
        executor per device and reduces grads through KVStore
        (executor_group.py:128, comm.h:102-720); here the SAME single
        program is GSPMD-sharded — batch over the ``dp`` axis, params
        replicated (or rule-sharded over ``mp`` when a
        ``PartitionRules`` tree is bound) — so XLA inserts the gradient
        all-reduce over ICI inside the fused fwd+bwd step. The batch
        divisibility check is against the DP AXIS size, not the device
        count: on a 2x4 dp x mp mesh a batch of 6 divides fine."""
        from ..parallel import mesh as _pmesh, spmd as _spmd
        if self._partition_rules is not None or self._mesh_axes:
            mesh = _pmesh.mesh_from_contexts(
                self._context, axes=self._mesh_axes or {_spmd.DP_AXIS: -1})
            spec = _spmd.rule_spec(mesh, self._partition_rules)
        else:
            spec = _spmd.dp_spec(_pmesh.mesh_from_contexts(self._context))
        for d in self._data_shapes + self._label_shapes:
            if d.shape:
                _spmd.check_batch_divisible(d.shape[0], spec.dp_size,
                                            "batch size",
                                            axis=spec.data_axis)
        self._dp_spec = spec
        self._mesh = spec.mesh
        self._data_sharding = spec.data_sharding
        self._repl_sharding = spec.repl_sharding
        self._shard_exec_arrays()

    def _shard_exec_arrays(self):
        """Commit shardings: data/label batch-sharded over ``dp``;
        params/grads/aux on their rule-resolved placement (replicated
        without a rule tree). GSPMD propagates from these committed
        placements."""
        from ..parallel import spmd as _spmd
        input_names = set(self._data_names) | set(self._label_names) \
            | set(self._state_names)
        _spmd.commit_dp_placements(self._exec, input_names, self._dp_spec)

    def partition_summary(self):
        """JSON-safe layout description of this module's mesh spec (or
        None on a single-device bind): mesh axes, data axis, the rule
        tree and the resolved sharded-parameter specs — recorded into
        checkpoint meta, fused plans and program cards."""
        if self._dp_spec is None:
            return None
        from ..parallel.partition import partition_summary as _summary
        shapes = None
        if self.binded and self._exec is not None:
            arg_dict = self._exec.arg_dict
            shapes = {n: arg_dict[n].shape for n in self._param_names
                      if n in arg_dict}
        return _summary(self._dp_spec, shapes)

    # -- multi-process dist mesh (the elastic dist_* tier) -----------------
    def _input_name_set(self):
        return set(self._data_names) | set(self._label_names) \
            | set(self._state_names)

    def _init_dist_spec(self):
        """Build the PROCESS-SPANNING dp mesh for a multi-process
        ``dist_*`` sync store: every live worker's context devices
        become a slab of one global ``dp`` axis, so the SAME fused
        donated-buffer train step jits across processes and XLA
        compiles the cross-host gradient psum INTO the step (the
        kvstore wire path becomes the recovery/compression fallback,
        not the steady state). A single-process job (or the last
        survivor after re-meshes) keeps ``_dist_spec=None`` and runs
        the plain local program."""
        from .. import dist as _dist
        from ..parallel import spmd as _spmd
        kv = self._kvstore
        live = kv.live_ranks if kv is not None else (0,)
        if len(live) <= 1 or _dist.process_count() <= 1:
            self._dist_spec = None
            return
        if self._partition_rules is not None:
            # re-sharding a rule tree across worker processes is not
            # wired yet (ROADMAP: multi-host mp); the dist tier keeps
            # the replicated dp layout
            raise MXNetError(
                "partition_rules cannot be combined with a "
                "multi-process dist_* kvstore yet; drop the rules or "
                "run single-process")
        for d in self._data_shapes + self._label_shapes:
            if d.shape:
                _spmd.check_batch_divisible(
                    d.shape[0], max(1, len(self._context)),
                    "local batch size")
        self._dist_spec = _spmd.dist_dp_spec(self._context,
                                             live_ranks=live)
        self._step_gate = None

    def _dist_gate(self):
        """Per-module pre-collective liveness gate for the fused dist
        step (channel ``step``; the kvstore wire path gates on its own
        ``kv`` channel). Lazy; rebuilt after a re-mesh."""
        if self._step_gate is None:
            from .. import heartbeat
            kv = self._kvstore
            self._step_gate = heartbeat.CollectiveGate(
                kv.rank, kv.live_ranks, channel="step")
        return self._step_gate

    def _await_dist_step(self, handle):
        """Liveness-aware completion wait for the previous spanning
        step: poll readiness alongside peer heartbeats, so a member
        that dies INSIDE an in-flight exchange (SIGKILL between its
        gate crossing and its part of the collective) surfaces as
        ``DeadWorkerError`` instead of an unbounded silent block.
        Best-effort beyond that point: the wedged execution cannot be
        aborted runtime-side, so recovery may still require the
        launcher-level restart — but the death is named, postmortem'd
        and bounded.

        The time spent here is WAIT, not work: it is reported to the
        step gate (``note_wait``) so the self-time this rank publishes
        at its next crossing excludes it — otherwise a fast rank
        blocked on a slow peer's half of the collective would itself
        read as a straggler in the fleet-wide skew comparison."""
        import time as _time
        t0 = _time.monotonic()
        try:
            if not hasattr(handle, "is_ready"):
                import jax
                jax.block_until_ready(handle)
                return
            from .. import heartbeat
            kv = self._kvstore
            peers = [r for r in kv.live_ranks if r != kv.rank]
            next_liveness = _time.monotonic() + 0.25
            while not handle.is_ready():
                if _time.monotonic() >= next_liveness:
                    next_liveness = _time.monotonic() + 0.25
                    dead = heartbeat.stale_ranks(peers)
                    if dead:
                        raise heartbeat.DeadWorkerError(
                            dead, channel="step-execution",
                            generation=self._dist_gate().generation,
                            evidence={r: "died with the collective "
                                         "in flight" for r in dead})
                _time.sleep(0.002)
        finally:
            try:
                self._dist_gate().note_wait(
                    (_time.monotonic() - t0) * 1e3)
            except Exception:
                pass

    def _ensure_dist_placement(self):
        """Commit the executor's storage onto the process-spanning mesh
        (idempotent). The FIRST commit broadcasts rank 0's replicated
        state to every worker (parity: kv.init server seeding) — after
        that the SPMD discipline keeps replicas identical and
        re-commits (post-fallback, post-re-mesh) are local-only."""
        if self._dist_spec is None or self._dist_committed:
            return
        from .. import dist as _dist
        from ..parallel import spmd as _spmd
        # the broadcast spans every LAUNCHED process — after a member
        # loss it would hang on the dead ones, and the survivors'
        # values are already consistent (same checkpoint restore)
        sync = not self._dist_synced and not _dist.dead_ranks()
        # the first-commit broadcast is a cross-process collective:
        # cross the step gate before it so a peer that died during
        # startup raises DeadWorkerError instead of hanging the sync
        _spmd.commit_dp_placements(self._exec, self._input_name_set(),
                                   self._dist_spec, sync=sync,
                                   gate=self._dist_gate() if sync
                                   else None)
        self._dist_synced = True
        self._dist_committed = True

    def _drop_dist_placement(self):
        """Detach every bound array from the process-spanning mesh back
        to this worker's LOCAL placement (replicated values read
        locally, batch-sharded values keep their local rows). Runs
        before any phase-split step — the explicit kvstore wire needs
        LOCAL gradients, a globally-committed executor would psum them
        inside forward_backward and the push would double-reduce — and
        during elastic recovery, where arrays still committed to a mesh
        containing dead devices would hang any eager op."""
        if not self._dist_committed:
            return
        import jax
        from ..parallel import spmd as _spmd
        ex = self._exec
        input_names = self._input_name_set()

        def _localize(arr, name=None):
            if arr is None:
                return
            val = _spmd.local_value(arr._data)
            if self._mesh is not None:
                sh = self._data_sharding if name in input_names \
                    else self._repl_sharding
                arr._set_data(jax.device_put(val, sh))
            else:
                arr._set_data(jax.device_put(
                    val, self._context[0].jax_device()))

        for name, arr in ex.arg_dict.items():
            _localize(arr, name)
        for arr in list(ex.grad_arrays) + list(ex.aux_arrays):
            _localize(arr)
        # optimizer state lives with the updater; kvstore weight copies
        # with the store — both were donated into the spanning program
        updater = self._kvstore._updater \
            if (self._kvstore is not None and self._update_on_kvstore) \
            else self._updater
        for st in (getattr(updater, "states", None) or {}).values():
            for leaf in _flatten_state(st):
                _localize(leaf)
        if self._kvstore is not None:
            for arr in self._kvstore._store.values():
                if isinstance(arr, NDArray) \
                        and getattr(arr, "stype", "default") == "default":
                    _localize(arr)
        ex.outputs = [_wrap(jax.device_put(
            _spmd.local_value(o._data), self._context[0].jax_device()),
            o.context) for o in ex.outputs]
        self._dist_committed = False

    def _elastic_remesh(self, dead_ranks):
        """Adopt the surviving membership after a member loss: record
        the dead ranks, detach from the dead mesh, rebuild the dp spec
        over the survivors (or drop to the local program when this
        worker is the last one standing) and invalidate the fused
        plan. The caller (``fit``'s elastic path) then restores the
        last checkpoint and resumes."""
        from .. import dist as _dist
        _dist.mark_member_lost(dead_ranks)
        live = _dist.live_ranks()
        kv = self._kvstore
        if kv is not None:
            kv._remesh(live)
        self._drop_dist_placement()
        self._fused_plan = None
        self._dist_sync_handle = None
        self._step_gate = None
        self._dist_spec = None
        if kv is not None and kv.fused_dist_step:
            self._init_dist_spec()
        telemetry.counter_inc("elastic.remesh")
        telemetry.record_event("elastic.remesh",
                               dead=list(dead_ranks), live=list(live))
        self.logger.warning(
            "elastic re-mesh: worker(s) %s dead, continuing on %s "
            "(%d live)", sorted(dead_ranks), list(live), len(live))

    # -- params ------------------------------------------------------------
    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        """(parity: module.py init_params)"""
        if self.params_initialized and not force_init:
            return
        assert self.binded, "call bind before init_params"
        if arg_params is None and self._arg_params is not None:
            arg_params = self._arg_params
        if aux_params is None and self._aux_params is not None:
            aux_params = self._aux_params
        attrs = self._symbol.attr_dict()

        for name, arr in self._exec.arg_dict.items():
            if name in self._data_names or name in self._label_names \
                    or name in self._state_names:
                continue
            given = (arg_params or {}).get(name)
            if given is not None:
                given.copyto(arr) if isinstance(given, NDArray) \
                    else arr.__setitem__(slice(None), given)
            elif not allow_missing or initializer is not None:
                if initializer is None:
                    if not allow_missing:
                        raise MXNetError("no initializer and no value for %r"
                                         % name)
                    continue
                desc = InitDesc(name, attrs.get(name))
                initializer(desc, arr)
        for name, arr in self._exec.aux_dict.items():
            given = (aux_params or {}).get(name)
            if given is not None:
                given.copyto(arr)
            elif initializer is not None:
                desc = InitDesc(name, attrs.get(name))
                initializer(desc, arr)
        self.params_initialized = True
        self._params_dirty = False
        if self._mesh is not None:
            # re-commit: initializer writes land on the default device
            self._shard_exec_arrays()

    def get_params(self):
        """(parity: module.get_params) returns host copies."""
        assert self.binded and self.params_initialized
        arg_params = {n: arr.copy() for n, arr in self._exec.arg_dict.items()
                      if n in self._param_names}
        aux_params = {n: arr.copy() for n, arr in self._exec.aux_dict.items()}
        return arg_params, aux_params

    # -- optimizer ---------------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        """(parity: module.py init_optimizer:472)"""
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            return
        arg_dict = self._exec.arg_dict
        kv, update_on_kvstore = _create_kvstore(
            kvstore, len(self._context),
            {n: arg_dict[n] for n in self._param_names})

        if isinstance(optimizer, str):
            idx2name = {i: n for i, n in enumerate(self._param_names)}
            optimizer_params = dict(optimizer_params)
            optimizer_params.setdefault("rescale_grad", 1.0)
            optimizer = opt.create(optimizer, sym=self._symbol,
                                   param_idx2name=idx2name,
                                   **optimizer_params)
        self._optimizer = optimizer
        if kv is not None:
            if kv.type.startswith("dist"):
                # EVERY dist_* type runs the optimizer kvstore-side
                # (reference semantics: the server applies updates for
                # dist_sync, dist_sync_device, dist_device_sync AND
                # dist_async alike). The old predicate named only
                # "dist_sync" and let the other dist types ride
                # whatever _create_kvstore defaulted to — the same
                # outcome today, silently, and one heuristic change
                # away from divergent update paths across workers.
                update_on_kvstore = True
            for i, name in enumerate(self._param_names):
                kv.init(i, arg_dict[name])
            if update_on_kvstore:
                kv.set_optimizer(self._optimizer)
        self._kvstore = kv
        self._update_on_kvstore = update_on_kvstore
        self._updater = None
        if not update_on_kvstore:
            self._updater = opt.get_updater(optimizer)
        if kv is not None and kv.fused_dist_step:
            self._init_dist_spec()
        self.optimizer_initialized = True

    # -- compute -----------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        """(parity: module.forward)"""
        assert self.binded and self.params_initialized
        if is_train is None:
            is_train = self.for_training
        # phase-split surface: runs on LOCAL placement (score/predict
        # between dist epochs, monitors) — no-op unless the fused dist
        # step left a process-spanning commit behind
        self._drop_dist_placement()
        self._set_batch(data_batch)
        self._exec.forward(is_train=is_train)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._exec.backward(out_grads=out_grads)

    def forward_backward(self, data_batch):
        """Fused single-XLA-program step (overrides the base two-call path)."""
        assert self.binded and self.params_initialized
        self._drop_dist_placement()
        self._set_batch(data_batch)
        self._exec.forward_backward()

    def _set_batch(self, data_batch):
        with telemetry.span("feed"):
            self._set_batch_impl(data_batch)

    def _set_batch_impl(self, data_batch):
        data = data_batch.data
        if not isinstance(data, (list, tuple)):
            data = [data]
        arg_dict = self._exec.arg_dict
        # variable batch shapes (e.g. eval batch != train batch): the
        # reference reshapes its executors (executor.py reshape); here the
        # same program simply jits a second signature, so just swap storage.
        reshaped = False
        for desc, arr in zip(self._data_shapes, data):
            if tuple(arr.shape) != arg_dict[desc.name].shape:
                arg_dict[desc.name]._set_data(
                    np.zeros(arr.shape, dtype=arg_dict[desc.name].dtype))
                reshaped = True
        if reshaped and data_batch.label is not None:
            labels = data_batch.label
            if not isinstance(labels, (list, tuple)):
                labels = [labels]
            for desc, arr in zip(self._label_shapes, labels):
                if tuple(arr.shape) != arg_dict[desc.name].shape:
                    arg_dict[desc.name]._set_data(
                        np.zeros(arr.shape, dtype=arg_dict[desc.name].dtype))
        for desc, arr in zip(self._data_shapes, data):
            self._write_input(arg_dict[desc.name], arr)
        label = data_batch.label
        if label is not None:
            if not isinstance(label, (list, tuple)):
                label = [label]
            for desc, arr in zip(self._label_shapes, label):
                self._write_input(arg_dict[desc.name], arr)

    def _write_input(self, dst, src):
        if self._mesh is not None:
            # commit the batch sharded over dp so GSPMD splits the step;
            # keep the bound placeholder's dtype (as copyto/setitem do).
            # A reshaped (variable-batch) feed must stay divisible — the
            # sharded device_put would otherwise die inside XLA
            from ..parallel import spmd as _spmd
            raw = src._data if isinstance(src, NDArray) else np.asarray(src)
            if raw.shape:
                _spmd.check_batch_divisible(raw.shape[0],
                                            self._dp_spec.dp_size,
                                            "batch size",
                                            axis=self._dp_spec.data_axis)
            dt = dst._data.dtype
            if isinstance(raw, np.ndarray):
                raw = _spmd.shard_put(raw.astype(dt, copy=False),
                                      self._data_sharding)
            else:
                raw = _spmd.shard_put(raw, self._data_sharding).astype(dt)
            dst._set_data(raw)
        elif isinstance(src, NDArray):
            src.copyto(dst)
        else:
            raw = np.asarray(src)
            telemetry.record_transfer(raw.nbytes)
            dst[:] = raw

    def update(self):
        """Apply one optimizer step (parity: module.update →
        model._update_params(_on_kvstore):106-138)."""
        assert self.binded and self.params_initialized \
            and self.optimizer_initialized
        self._params_dirty = True
        arg_dict = self._exec.arg_dict
        grad_dict = self._exec.grad_dict
        # push/pull whole key LISTS: in dist mode kvstore then reduces all
        # keys in one jitted collective instead of one dispatch per param
        live = [(i, name) for i, name in enumerate(self._param_names)
                if grad_dict.get(name) is not None]
        if not live:
            return
        keys = [i for i, _ in live]
        grads = [grad_dict[name] for _, name in live]
        with telemetry.span("opt_update"):
            if self._kvstore is not None and self._update_on_kvstore:
                self._kvstore.push(keys, grads)
                self._kvstore.pull(keys,
                                   out=[arg_dict[name] for _, name in live])
            else:
                if self._kvstore is not None:
                    self._kvstore.push(keys, grads)
                    self._kvstore.pull(keys, out=grads)
                # one fused dispatch for the whole parameter set
                # (FusedUpdater)
                self._updater.update_batch(
                    keys, grads, [arg_dict[name] for _, name in live])

    # -- whole-step fused training -----------------------------------------
    def _fused_batch_step(self, data_batch, eval_metric=None):
        """Fused-step entry: run the impl, and on ANY fallback drop the
        process-spanning placement first — the phase-split oracle
        computes LOCAL gradients for the explicit kvstore wire, and a
        globally-committed executor would psum them inside
        forward_backward so the push would double-reduce."""
        ok = self._fused_batch_step_impl(data_batch, eval_metric)
        if not ok:
            self._drop_dist_placement()
        return ok

    def _fused_batch_step_impl(self, data_batch, eval_metric=None):
        """Forward + backward + optimizer update (+ metric accumulation
        when the metric has a device kernel) as ONE jitted XLA program
        with params/optimizer-state/metric/aux buffers donated
        (``executor._GraphProgram.train_step_fn``) — the whole-step
        program compilation that closes the Module.fit dispatch gap
        (PERF.md "Module.fit gap"). Batch arrays ride as jit arguments,
        so no copy into bound storage either. Returns True when the
        fused program ran; on False the caller must run the phase-split
        path (forward_backward/update/update_metric), which stays the
        correctness oracle. The reason for the last fallback is kept in
        ``_fused_fallback_reason``.

        Fallback rules (each mirrors a real constraint; the recorded
        reason is a ``FusedFallback`` — a str with a stable ``.code``):
        - ``MXNET_MODULE_FUSED_STEP=0`` — the A/B pin (``env_pin``)
        - grouped (group2ctx) programs — eager per-segment execution
        - monitor installed — per-op taps need the phase-split programs
        - ``dist_*`` kvstores (``kvstore_dist``) — push/pull crosses
          worker processes outside the compiled program — and stores
          with gradient compression (``kvstore_compression``). The
          in-process types (``local``/``device``/``nccl``) are SUBSUMED:
          on the dp mesh the gradient all-reduce rides inside the SPMD
          step program, so their push/pull is an identity round-trip
          the fused step skips (store weights are kept coherent so a
          mid-training fallback continues seamlessly)
        - optimizers without a pure batch kernel (no SPMD kernel
          mapping, centered RMSProp, inexpressible state layouts) or a
          non-Fused updater
        - ``inputs_need_grad`` — data gradients are phase-split only

        The expensive eligibility cascade + program lookup runs once and
        is cached as a per-module PLAN (``_fused_plan``), invalidated on
        any identity change (rebind, new optimizer/updater/metric);
        conditions that can flip without an identity change (the env
        pin, monitors, kvstore, hyperparameter statics, optimizer-state
        layout) are re-checked every step — they are attribute reads,
        not program rebuilds.
        """
        if not fused_fit():
            self._fused_fallback_reason = FusedFallback(
                "env_pin", "MXNET_MODULE_FUSED_STEP=0")
            return False
        ex = self._exec
        if ex is not None and ex._monitor_callback is not None:
            self._fused_fallback_reason = FusedFallback(
                "monitor", "monitor installed")
            return False
        kv = self._kvstore
        if kv is not None and not kv.fused_step_subsumable:
            if kv.fused_dist_step:
                # the dist sync tier: the SAME fused donated-buffer
                # step jits over the process-spanning dp mesh with the
                # cross-host psum inside the program (a single-process
                # job or the last survivor runs it locally) — dist_sync
                # no longer falls back. EXCEPT when this module never
                # committed a spanning mesh (borrowed optimizer /
                # bucketing switch paths skip _init_dist_spec): fusing
                # LOCALLY there would silently train divergent
                # replicas, so the explicit wire stays
                if self._dist_spec is None and len(kv.live_ranks) > 1:
                    self._fused_fallback_reason = FusedFallback(
                        "kvstore_dist", "kvstore-mediated update",
                        "multi-process dist store without a committed "
                        "process-spanning mesh (borrowed optimizer / "
                        "bucketing)")
                    return False
            elif kv.type.startswith("dist"):
                self._fused_fallback_reason = FusedFallback(
                    "kvstore_dist", "kvstore-mediated update",
                    "kvstore type %r keeps the explicit wire path "
                    "(async application is wire-emulated)" % kv.type)
                return False
            else:
                self._fused_fallback_reason = FusedFallback(
                    "kvstore_compression", "kvstore-mediated update",
                    "gradient compression changes the pushed values")
                return False
        # an in-process kvstore's reduce is subsumed by the SPMD step;
        # with update_on_kvstore the kvstore's server-side updater owns
        # the optimizer state, so the plan runs THAT updater's kernels
        updater = kv._updater if (kv is not None
                                  and self._update_on_kvstore) \
            else self._updater
        plan = self._fused_plan
        packed = None
        if (plan is None or plan["exec"] is not ex
                or plan["updater"] is not updater
                or plan["kvstore"] is not kv
                or plan["optimizer"] is not self._optimizer
                or plan["metric"] is not eval_metric
                or plan["has_label"] != (data_batch.label is not None)):
            plan = self._fused_plan = self._build_fused_plan(
                data_batch, eval_metric, updater)
        else:
            # hyperparameters baked into the program as statics can be
            # mutated on the live optimizer object — verify per step
            try:
                kname, hyper = plan["hyper_fn"](self._optimizer)
            except MXNetError as e:
                self._fused_fallback_reason = FusedFallback(
                    "optimizer_kernel", str(e))
                self._fused_plan = None
                return False
            statics = tuple(sorted(
                (k, v) for k, v in hyper.items() if k not in ("lr", "wd")))
            if kname != plan["kname"] or statics != plan["statics"]:
                plan = self._fused_plan = self._build_fused_plan(
                    data_batch, eval_metric, updater)
            else:
                # optimizer state re-gathered every step: layouts can
                # drift under the plan (load_optimizer_states swaps the
                # state NDArrays) and states for late parameters are
                # created here
                packed, mp, inner_n = updater._gather_batch(
                    plan["kname"], plan["indices"], plan["weights"])
                if packed is None or tuple(mp) != plan["mp"] \
                        or tuple(inner_n) != plan["inner_n"]:
                    packed = None
                    plan = self._fused_plan = self._build_fused_plan(
                        data_batch, eval_metric, updater)
        if plan is None:
            return False
        if packed is None:
            # a just-built plan carries the state its own gather packed
            packed = plan.pop("packed")
        return self._run_fused_step(plan, packed, data_batch, eval_metric)

    def _build_fused_plan(self, data_batch, eval_metric, updater=None):
        """Run the full fusion-eligibility cascade and assemble the
        per-module plan ``_fused_batch_step`` executes from: parameter
        ordering, the jitted whole-step program (SPMD-sharded over the
        dp mesh for a multi-context bind), and the metric device kernel.
        ``updater`` is the EFFECTIVE updater (the kvstore's server-side
        one under update_on_kvstore, else the module's). Returns None
        (with ``_fused_fallback_reason`` set) when any piece can't
        ride."""
        if not (self.binded and self.params_initialized
                and self.optimizer_initialized):
            self._fused_fallback_reason = FusedFallback(
                "not_initialised", "module not fully initialised")
            return None
        ex = self._exec
        if ex._prog.node_devices:
            self._fused_fallback_reason = FusedFallback(
                "group2ctx", "group2ctx grouped program")
            return None
        if updater is None:
            updater = self._updater
        if not isinstance(updater, opt.FusedUpdater):
            self._fused_fallback_reason = FusedFallback(
                "no_fused_updater", "updater has no fused batch path")
            return None
        if self.inputs_need_grad:
            self._fused_fallback_reason = FusedFallback(
                "inputs_need_grad", "inputs_need_grad")
            return None
        optimizer = self._optimizer
        from ..parallel import opt_kernels as _ok
        try:
            kname, hyper = _ok.hyper_from_optimizer(optimizer)
        except MXNetError as e:
            self._fused_fallback_reason = FusedFallback(
                "optimizer_kernel", str(e))
            return None
        if getattr(optimizer, "centered", False):
            self._fused_fallback_reason = FusedFallback(
                "centered_rmsprop", "centered RMSProp state layout")
            return None

        arg_dict = ex.arg_dict
        live = [(i, n) for i, n in enumerate(self._param_names)
                if self._grad_req.get(n, "null") != "null"]
        if not live:
            self._fused_fallback_reason = FusedFallback(
                "no_trainable_params", "no trainable parameters")
            return None
        indices = [i for i, _ in live]
        update_names = tuple(n for _, n in live)
        add_names = frozenset(n for _, n in live
                              if self._grad_req[n] == "add")
        weights = [arg_dict[n] for n in update_names]
        packed, mp, inner_n = updater._gather_batch(kname, indices, weights)
        if packed is None:
            self._fused_fallback_reason = FusedFallback(
                "state_layout",
                "optimizer state layout not expressible as a kernel step")
            return None

        has_label = data_batch.label is not None
        graph_args = frozenset(ex._prog.arg_names)
        bound_labels = [l.name for l in self._label_shapes] \
            if self._label_shapes else []
        # only GRAPH-CONSUMED labels ride as program inputs: a label
        # bound purely for metric use (e.g. a MakeLoss custom loss) is
        # not a graph argument, and feeding it would blow the trace
        label_inputs = [n for n in bound_labels if n in graph_args]
        # metric: fuse only a plain (no output/label renaming) metric
        # with a device kernel and a 1:1 BOUND, graph-fed label/output
        # pairing — the kernel reads the label arrays the step actually
        # feeds; anything else accumulates phase-split on the step's
        # outputs
        kernel = None
        if eval_metric is not None and has_label \
                and eval_metric.output_names is None \
                and eval_metric.label_names is None \
                and bound_labels and label_inputs == bound_labels \
                and len(bound_labels) == len(self._output_names):
            kernel = eval_metric.device_kernel()

        input_names = [d.name for d in self._data_shapes]
        if has_label:
            input_names += label_inputs
        input_names += list(self._state_names)
        if any(n not in arg_dict for n in input_names):
            self._fused_fallback_reason = FusedFallback(
                "missing_input",
                "bound input(s) missing from the executor arg dict: "
                + ", ".join(sorted(n for n in input_names
                                   if n not in arg_dict)))
            return None
        input_dtypes = {n: arg_dict[n]._data.dtype for n in input_names}

        # every graph argument must be fed (as a param or an input): a
        # label-consuming graph bound without label shapes, or handed a
        # label-less batch, cannot ride the pure-function program
        missing = graph_args.difference(self._param_names, input_names)
        if missing:
            self._fused_fallback_reason = FusedFallback(
                "unfed_graph_arg",
                "graph argument(s) not fed by the fused step: "
                + ", ".join(sorted(missing)))
            return None

        statics = tuple(sorted(
            (k, v) for k, v in hyper.items() if k not in ("lr", "wd")))
        metric_key = None if kernel is None else \
            (type(eval_metric).__module__, type(eval_metric).__qualname__,
             getattr(eval_metric, "axis", None), tuple(bound_labels))
        cache_key = (kname, statics, tuple(mp), tuple(inner_n), metric_key)
        label_names = bound_labels

        def build_metric_fn():
            def metric_fn(outs, ins, acc):
                return kernel([ins[n] for n in label_names], list(outs), acc)
            return metric_fn

        # the dist tier overrides the local dp spec: ONE program over
        # the process-spanning mesh, cross-host psum compiled inside
        spmd_spec = self._dist_spec if self._dist_spec is not None \
            else self._dp_spec

        build_shardings = None
        if spmd_spec is not None \
                and getattr(spmd_spec, "rules", None) is not None:
            spec = spmd_spec
            param_names = list(self._param_names)
            aux_pairs = [(n, a.shape)
                         for n, a in zip(ex._aux_names, ex.aux_arrays)]
            state_shapes = [tuple(tuple(x.shape) for x in tup)
                            for tup in packed]

            def build_shardings():
                # per-leaf NamedShardings from the rule tree: optimizer
                # state the shape of its weight (momenta, fp32 masters)
                # rides the weight's placement; any other leaf shape
                # replicates on the same mesh
                psh = {n: spec.param_sharding(n, arg_dict[n].shape)
                       for n in param_names}
                repl = spec.repl_sharding
                ssh = []
                for n, shapes in zip(update_names, state_shapes):
                    wshape = tuple(arg_dict[n].shape)
                    ssh.append(tuple(psh[n] if s == wshape else repl
                                     for s in shapes))
                return {
                    "params": psh,
                    "states": ssh,
                    "aux": {n: spec.param_sharding(n, s)
                            for n, s in aux_pairs},
                    "add_grads": {n: psh[n] for n in add_names},
                }
        fn = ex._prog.train_step_fn(
            update_names, add_names, input_dtypes, cache_key,
            build_update_fn=lambda: opt._make_batch_update(
                kname, dict(statics), list(mp), list(inner_n)),
            build_metric_fn=build_metric_fn if kernel is not None else None,
            spmd=spmd_spec, build_shardings=build_shardings)
        # a SUBSUMED update_on_kvstore store holds its own canonical
        # weight copies (push updates them, pull serves them); the fused
        # step keeps them coherent with zero-cost pointer swaps so a
        # mid-training fallback (or save_checkpoint via pull) continues
        # from the right values
        kv = self._kvstore
        store_sync = []
        if kv is not None and self._update_on_kvstore:
            store_sync = [(n, kv._store[i]) for i, n in live
                          if i in kv._store]
        return {
            "exec": ex, "updater": updater, "optimizer": optimizer,
            "kvstore": kv, "store_sync": store_sync,
            "metric": eval_metric, "has_label": has_label,
            "kname": kname, "statics": statics,
            "hyper_fn": _ok.hyper_from_optimizer,
            "indices": indices, "update_names": update_names,
            "add_names": add_names, "weights": weights,
            "mp": tuple(mp), "inner_n": tuple(inner_n),
            "kernel": kernel, "fn": fn,
            "label_inputs": frozenset(label_inputs),
            "spmd_spec": spmd_spec,
            # the resolved layout rides in the plan (and from there
            # into checkpoint meta / the tuner's corpus records)
            "layout": self.partition_summary(),
            # per-process gradient payload of the in-program psum (the
            # dist wire-bytes estimate bumped per spanning step)
            "dist_wire_bytes": sum(
                int(w._data.size) * w._data.dtype.itemsize
                for w in weights),
            # the state gathered above, consumed (popped) by the step
            # that built the plan — later steps re-gather fresh
            "packed": packed,
        }

    def _run_fused_step(self, plan, packed, data_batch, eval_metric):   # mxlint: hot
        """Execute one whole-step fused program from a validated plan:
        marshal raw buffers, launch, reinstall the donated results."""
        ex = self._exec
        arg_dict = ex.arg_dict
        optimizer = plan["optimizer"]
        kernel = plan["kernel"]
        data = data_batch.data
        if not isinstance(data, (list, tuple)):
            data = [data]
        label = data_batch.label
        if label is not None and not isinstance(label, (list, tuple)):
            label = [label]

        from ..parallel import spmd as _spmd
        spec = plan["spmd_spec"]
        spanning = spec is not None \
            and _spmd.is_process_spanning(spec.mesh)
        mesh = spec.mesh if spec is not None else None
        sharding = spec.data_sharding if spec is not None else None
        import jax
        dev = None if mesh is not None else self._context[0].jax_device()
        if spanning:
            self._ensure_dist_placement()
            if self._dist_sync_handle is not None:
                # complete the PREVIOUS spanning step before gating:
                # every member that crosses the gate has finished its
                # part of step N-1's collective, so a member that dies
                # at the gate can never leave peers hung inside an
                # in-flight exchange — the price is one host sync per
                # dist step (they are wire-bound anyway)
                self._await_dist_step(self._dist_sync_handle)
                self._dist_sync_handle = None
            # liveness gate BEFORE entering the collective step: a dead
            # peer raises DeadWorkerError here (elastic recovery), a
            # live job pays two tiny file writes + a poll
            self._dist_gate().arrive_and_wait()

        def _raw(arr):
            raw = arr._data if isinstance(arr, NDArray) else np.asarray(arr)   # mxlint: disable=host-sync -- feed-path marshalling of a HOST-side batch array (lists/np inputs); device arrays take the _data branch
            if spanning:
                # this worker's LOCAL rows become its shard of the
                # global batch (no host gather, no peer traffic)
                raw = _spmd.dist_shard_put(np.asarray(raw), spec)   # mxlint: disable=host-sync -- same feed-path marshalling: the process-local constructor needs the host view of the local batch
            elif mesh is not None:
                # one sharded device_put of the GLOBAL batch — each
                # device receives its shard, no host-side splitting
                if raw.shape:
                    _spmd.check_batch_divisible(
                        raw.shape[0], spec.dp_size, "batch size",
                        axis=spec.data_axis)
                raw = _spmd.shard_put(raw, sharding)
            else:
                # batch arrays ride as jit arguments without a copy into
                # bound storage, so THIS is where they must commit to
                # the module's device (a module on a non-default device
                # fed default-device arrays would otherwise crash the
                # program with mixed committed inputs; same-device puts
                # are a no-op)
                if isinstance(raw, np.ndarray):
                    telemetry.record_transfer(raw.nbytes)
                raw = jax.device_put(raw, dev)
            return raw

        with telemetry.span("feed"):
            inputs = {}
            for desc, arr in zip(self._data_shapes, data):
                inputs[desc.name] = _raw(arr)
            label_raws = []
            if label is not None and self._label_shapes:
                for desc, arr in zip(self._label_shapes, label):
                    r = _raw(arr)
                    # the jit signature carries only graph-consumed
                    # labels
                    if desc.name in plan["label_inputs"]:
                        inputs[desc.name] = r
                    label_raws.append(r)
            for name in self._state_names:
                inputs[name] = arg_dict[name]._data

        # host-side bookkeeping exactly as the phase-split update() does
        # it — same Updater states, same count/lr/wd schedule, so a
        # fallback mid-training continues seamlessly
        indices = plan["indices"]
        for i in indices:
            optimizer._update_count(i)
        counts = optimizer._index_update_count
        ts = np.asarray([counts[i] for i in indices], np.float32)
        lrs = np.asarray([optimizer._get_lr(i) for i in indices], np.float32)
        wds = np.asarray([optimizer._get_wd(i) for i in indices], np.float32)

        params_raw = {n: arg_dict[n]._data for n in self._param_names}
        states_raw = [tuple(x._data for x in tup) for tup in packed]
        aux_raw = {n: a._data for n, a in zip(ex._aux_names, ex.aux_arrays)}
        grad_dict = ex.grad_dict
        add_names = plan["add_names"]
        add_grads = {n: grad_dict[n]._data for n in add_names}
        acc = None
        if kernel is not None:
            acc = getattr(eval_metric, "_dev_sum", None)
            if acc is None:
                import jax.numpy as jnp
                # a fresh accumulator commits to the module's placement
                # (the mesh program reshards via in_shardings; a single-
                # device module must not introduce a default-device
                # operand)
                acc = jnp.zeros((), jnp.float32)
                if spanning:
                    acc = _spmd.put_replicated_local(acc, spec)
                elif dev is not None:
                    acc = jax.device_put(acc, dev)
        rng = ex._step_key()
        if spanning:
            # per-step scalars install as replicated WITHOUT a
            # collective (every worker computes identical values —
            # the SPMD discipline put_replicated_local documents);
            # letting jit auto-commit them would pay a cross-host
            # equality collective per array per step
            rng = _spmd.put_replicated_local(rng, spec)
            lrs = _spmd.put_replicated_local(lrs, spec)
            wds = _spmd.put_replicated_local(wds, spec)
            ts = _spmd.put_replicated_local(ts, spec)

        record_dispatch("train_step")
        with telemetry.span("step"):
            new_params, new_states, new_acc, new_aux, outs, grads_out = \
                plan["fn"](params_raw, states_raw, acc, aux_raw, inputs, rng,   # mxlint: donates 0-3
                           lrs, wds, ts, add_grads)
        if spanning:
            # the in-program cross-host psum IS the dist wire now:
            # account it next to the explicit push path's counters, and
            # keep a handle for the pre-gate sync of the NEXT step
            self._dist_sync_handle = \
                new_params[plan["update_names"][0]] \
                if plan["update_names"] else None
            telemetry.counter_inc("kvstore.dist.fused_steps")
            telemetry.counter_inc("kvstore.dist.collectives")
            telemetry.counter_inc("kvstore.dist.wire_bytes",
                                  plan["dist_wire_bytes"])
            telemetry.counter_inc("kvstore.dist.wire_bytes_raw",
                                  plan["dist_wire_bytes"])

        # donation invalidated the old buffers — reinstall everything
        for n in self._param_names:
            arg_dict[n]._set_data(new_params[n])
        for tup, ntup in zip(packed, new_states):
            for x, nx in zip(tup, ntup):
                x._set_data(nx)
        for n, a in zip(ex._aux_names, ex.aux_arrays):
            a._set_data(new_aux[n])
        # only 'add' accumulators come back (next step's input); 'write'
        # grads are consumed inside the program and never materialized
        # (add_grads above already established every 'add' grad exists)
        for n in add_names:
            grad_dict[n]._set_data(grads_out[n])
        # subsumed update_on_kvstore: refresh the store's canonical
        # weight copies (pointer swaps — no device work)
        for n, store_arr in plan["store_sync"]:
            store_arr._set_data(new_params[n])
        ex.outputs = [_wrap(o, ex._out_ctx(i)) for i, o in enumerate(outs)]
        if kernel is not None:
            n_inst = sum(int(r.size) for r in label_raws)
            eval_metric._install_fused(new_acc, n_inst)
        elif eval_metric is not None:
            self.update_metric(eval_metric, data_batch.label)
        self._params_dirty = True
        self._fused_fallback_reason = None
        return True

    def get_outputs(self, merge_multi_context=True):
        assert self.binded
        return list(self._exec.outputs)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.inputs_need_grad
        gd = self._exec.grad_dict
        return [gd[n] for n in self._data_names]

    def update_metric(self, eval_metric, labels):
        # "metric_update" — the NON-blocking per-batch accumulate; the
        # blocking host fetch records separately as "metric_fetch"
        # (EvalMetric._flush_device), so the fetch histogram stays the
        # stall detector PERF.md reads
        with telemetry.span("metric_update"):
            eval_metric.update(labels if isinstance(labels, (list, tuple))
                               else [labels], self.get_outputs())

    def finite_check(self):
        """Device-side divergence sentinel (overrides the base host
        fold): ONE jitted program (``executor.finite_fold_fn``) folds
        ``isfinite`` over the last step's outputs (the loss head),
        every materialised gradient, and every parameter — a NaN
        gradient poisons the params on the step it appears, so a
        periodic check over params catches mid-interval divergence —
        then fetches the single scalar verdict."""
        from ..executor import finite_fold_fn
        assert self.binded and self.params_initialized
        ex = self._exec
        leaves = [o._data for o in ex.outputs]
        leaves += [g._data for g in ex.grad_dict.values()
                   if g is not None]
        leaves += [ex.arg_dict[n]._data for n in self._param_names]
        if not leaves:
            return True
        record_dispatch("finite_check")
        with telemetry.span("divergence_check"):
            verdict = finite_fold_fn()(leaves)
            return bool(np.asarray(verdict))

    # -- checkpoints -------------------------------------------------------
    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        """(parity: module.py save_checkpoint:164)"""
        arg_params, aux_params = self.get_params()
        save_checkpoint(prefix, epoch, self._symbol, arg_params, aux_params)
        if save_optimizer_states:
            self.save_optimizer_states("%s-%04d.states" % (prefix, epoch))

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        """(parity: module.py Module.load:126)"""
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        mod = Module(symbol, **kwargs)
        mod._arg_params = arg_params
        mod._aux_params = aux_params
        # reference Module.load marks params initialised; bind() installs
        # them into the executor (module.py:126-183)
        mod.params_initialized = True
        mod._preloaded_params = (arg_params, aux_params)
        if load_optimizer_states:
            mod._preload_opt_states = "%s-%04d.states" % (prefix, epoch)
        return mod

    def borrow_optimizer(self, shared_module):
        """Share another Module's optimizer/updater (parity:
        module.borrow_optimizer)."""
        assert shared_module.optimizer_initialized
        self._optimizer = shared_module._optimizer
        self._kvstore = shared_module._kvstore
        self._update_on_kvstore = shared_module._update_on_kvstore
        self._updater = shared_module._updater
        self.optimizer_initialized = True

    def get_input_grads(self, merge_multi_context=True):
        """Gradients w.r.t. inputs from the last backward (parity:
        module.get_input_grads — requires inputs_need_grad)."""
        assert self.binded and self.params_initialized
        assert self.inputs_need_grad
        grads = self._exec.grad_dict
        return [grads[name] for name in self._data_names if name in grads]

    def save_optimizer_states(self, fname):
        """(parity: module.save_optimizer_states:759) — atomic
        (temp+fsync+rename) so a preemption mid-save never truncates
        the previous states file."""
        from ..checkpoint import atomic_write
        assert self.optimizer_initialized
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname)
        else:
            atomic_write(fname, self._updater.get_states())

    def load_optimizer_states(self, fname):
        assert self.optimizer_initialized
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
        else:
            with open(fname, "rb") as f:
                self._updater.set_states(f.read())

    def install_monitor(self, mon):
        assert self.binded
        mon.install(self._exec)

    def reshape(self, data_shapes, label_shapes=None):
        """(parity: module.reshape) — on TPU just a new jit signature."""
        assert self.binded
        arg_p, aux_p = self.get_params() if self.params_initialized else (None, None)
        self.binded = False
        self._exec = None
        self.bind(data_shapes, label_shapes, self.for_training,
                  self.inputs_need_grad, force_rebind=True)
        if arg_p is not None:
            self.set_params(arg_p, aux_p)

    def init_params_from_preloaded(self):
        if getattr(self, "_preloaded_params", None) and self.binded:
            arg_p, aux_p = self._preloaded_params
            self.set_params(arg_p, aux_p)


def _flatten_state(st):
    """NDArray leaves of one updater state entry (states are NDArrays,
    tuples of them — multi-precision nests master weights — or None)."""
    if st is None:
        return []
    if isinstance(st, (list, tuple)):
        out = []
        for x in st:
            out.extend(_flatten_state(x))
        return out
    return [st] if isinstance(st, NDArray) else []


def _as_desc(d):
    from ..io import DataDesc
    if isinstance(d, DataDesc):
        return d
    name, shape = d[0], d[1]
    return DataDesc(name, shape)
