"""Module — symbolic training on a bound executor.

Parity: reference ``python/mxnet/module/module.py``. TPU-native design:
where the reference builds a DataParallelExecutorGroup with one executor
per GPU and reduces through KVStore (executor_group.py:128,
model.py:106-138), this Module binds ONE executor whose compiled program
covers the whole (possibly mesh-sharded) computation — multi-chip data
parallelism is expressed as sharding on the same program
(mxnet_tpu.parallel), not as replicated executors, because XLA then
schedules the ICI all-reduce inside the step. The KVStore push/pull
protocol is still honoured when a kvstore is provided
(update_on_kvstore ≙ reference semantics).
"""
from __future__ import annotations

import logging

import numpy as np

from ..base import MXNetError
from ..context import Context, cpu, current_context
from ..initializer import Uniform, InitDesc
from ..model import _create_kvstore, save_checkpoint, load_checkpoint
from .. import optimizer as opt
from ..ndarray.ndarray import NDArray, zeros
from .base_module import BaseModule, _as_list


class Module(BaseModule):
    """(parity: module.Module)"""

    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging, context=None,
                 work_load_list=None, fixed_param_names=None,
                 state_names=None, group2ctxs=None,
                 compression_params=None):
        super().__init__(logger=logger)
        if context is None:
            context = current_context()
        if isinstance(context, Context):
            context = [context]
        self._context = context
        self._symbol = symbol
        self._data_names = list(data_names or [])
        self._label_names = list(label_names or [])
        self._state_names = list(state_names or [])
        self._fixed_param_names = list(fixed_param_names or [])
        arg_names = symbol.list_arguments()
        input_names = self._data_names + self._label_names + self._state_names
        self._param_names = [n for n in arg_names if n not in input_names]
        self._group2ctxs = group2ctxs
        self._aux_names = symbol.list_auxiliary_states()
        self._output_names = symbol.list_outputs()
        self._arg_params = None
        self._aux_params = None
        self._params_dirty = False
        self._optimizer = None
        self._kvstore = None
        self._update_on_kvstore = None
        self._updater = None
        self._exec = None
        self._data_shapes = None
        self._label_shapes = None
        self._grad_req = None
        self._mesh = None
        self._data_sharding = None
        self._repl_sharding = None

    # -- introspection -----------------------------------------------------
    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        assert self.binded
        return self._data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        if self._exec.outputs:
            return [(n, o.shape) for n, o in
                    zip(self._output_names, self._exec.outputs)]
        # before the first forward, infer from the bound input shapes —
        # the reference has these available right after bind (executor
        # group infers at bind time), and SequentialModule.bind chains
        # stages through this property
        shape_kwargs = {d.name: d.shape for d in self._data_shapes}
        shape_kwargs.update({l.name: l.shape for l in self._label_shapes})
        _, out_shapes, _ = self._symbol.infer_shape(**shape_kwargs)
        return list(zip(self._output_names, out_shapes))

    # -- bind --------------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        """(parity: module.py bind:363)"""
        if force_rebind:
            self._exec = None
            self.binded = False
        if self.binded:
            self.logger.warning("Already bound, ignoring bind()")
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad

        self._data_shapes = [_as_desc(d) for d in data_shapes]
        self._label_shapes = [_as_desc(l) for l in label_shapes] \
            if label_shapes else []

        shape_kwargs = {d.name: d.shape for d in self._data_shapes}
        shape_kwargs.update({l.name: l.shape for l in self._label_shapes})
        # input dtypes flow from DataDesc into the joint InferShape/Type
        # pass, so a bf16 data desc binds a bf16 executor end to end.
        # Labels included: without an explicit entry the inference pass
        # would anchor the label var to the data dtype (bf16 truncates
        # class indices > 256).
        type_dict = {d.name: d.dtype
                     for d in self._data_shapes + self._label_shapes
                     if getattr(d, "dtype", None) is not None}

        reqs = {}
        for name in self._symbol.list_arguments():
            if name in self._data_names:
                reqs[name] = "write" if inputs_need_grad else "null"
            elif name in self._label_names or name in self._state_names:
                reqs[name] = "null"
            elif name in self._fixed_param_names:
                reqs[name] = "null"
            else:
                reqs[name] = grad_req if for_training else "null"
        self._grad_req = reqs
        ctx = self._context[0]
        g2c = self._group2ctxs
        if isinstance(g2c, (list, tuple)):
            # reference group2ctxs is a per-context list; the single-exec
            # module uses the first entry
            g2c = g2c[0] if g2c else None
        if g2c and len(self._context) > 1:
            # grouped programs pin ops to concrete devices (eager
            # per-segment execution); the dp mesh shards ONE jitted
            # program — the two placements are mutually exclusive
            raise MXNetError(
                "group2ctxs cannot be combined with a multi-device "
                "context list; use a single context for model "
                "parallelism or drop group2ctxs for data parallelism")
        self._exec = self._symbol.simple_bind(ctx=ctx, grad_req=reqs,
                                              type_dict=type_dict,
                                              group2ctx=g2c,
                                              **shape_kwargs)
        if len(self._context) > 1:
            self._init_mesh()
        self.binded = True
        if shared_module is not None and shared_module.params_initialized:
            arg_p, aux_p = shared_module.get_params()
            self.set_params(arg_p, aux_p)
        elif self.params_initialized and self._arg_params is not None:
            # Module.load path: checkpointed params install at bind time
            self.set_params(self._arg_params, self._aux_params or {})

    # -- multi-device mesh (TPU-native DataParallelExecutorGroup) ----------
    def _init_mesh(self):
        """N contexts = a dp mesh over N chips: the reference builds one
        executor per device and reduces grads through KVStore
        (executor_group.py:128, comm.h:102-720); here the SAME single
        program is GSPMD-sharded — batch over the ``dp`` axis, params
        replicated — so XLA inserts the gradient all-reduce over ICI
        inside the fused fwd+bwd step."""
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        devs = [c.jax_device() for c in self._context]
        if len(set(devs)) != len(devs):
            raise MXNetError("duplicate devices in context list %s"
                             % (self._context,))
        for d in self._data_shapes + self._label_shapes:
            if d.shape and d.shape[0] % len(devs) != 0:
                raise MXNetError(
                    "batch size %d not divisible by %d devices"
                    % (d.shape[0], len(devs)))
        self._mesh = Mesh(np.array(devs), ("dp",))
        self._data_sharding = NamedSharding(self._mesh, P("dp"))
        self._repl_sharding = NamedSharding(self._mesh, P())
        self._shard_exec_arrays()

    def _shard_exec_arrays(self):
        """Commit shardings: data/label batch-sharded, params/grads/aux
        replicated. GSPMD propagates from these committed placements."""
        import jax
        input_names = set(self._data_names) | set(self._label_names) \
            | set(self._state_names)
        for name, arr in self._exec.arg_dict.items():
            sh = self._data_sharding if name in input_names \
                else self._repl_sharding
            arr._set_data(jax.device_put(arr._data, sh))
        for arr in self._exec.grad_arrays:
            if arr is not None:
                arr._set_data(jax.device_put(arr._data, self._repl_sharding))
        for arr in self._exec.aux_arrays:
            arr._set_data(jax.device_put(arr._data, self._repl_sharding))

    # -- params ------------------------------------------------------------
    def init_params(self, initializer=Uniform(0.01), arg_params=None,
                    aux_params=None, allow_missing=False, force_init=False,
                    allow_extra=False):
        """(parity: module.py init_params)"""
        if self.params_initialized and not force_init:
            return
        assert self.binded, "call bind before init_params"
        if arg_params is None and self._arg_params is not None:
            arg_params = self._arg_params
        if aux_params is None and self._aux_params is not None:
            aux_params = self._aux_params
        attrs = self._symbol.attr_dict()

        for name, arr in self._exec.arg_dict.items():
            if name in self._data_names or name in self._label_names \
                    or name in self._state_names:
                continue
            given = (arg_params or {}).get(name)
            if given is not None:
                given.copyto(arr) if isinstance(given, NDArray) \
                    else arr.__setitem__(slice(None), given)
            elif not allow_missing or initializer is not None:
                if initializer is None:
                    if not allow_missing:
                        raise MXNetError("no initializer and no value for %r"
                                         % name)
                    continue
                desc = InitDesc(name, attrs.get(name))
                initializer(desc, arr)
        for name, arr in self._exec.aux_dict.items():
            given = (aux_params or {}).get(name)
            if given is not None:
                given.copyto(arr)
            elif initializer is not None:
                desc = InitDesc(name, attrs.get(name))
                initializer(desc, arr)
        self.params_initialized = True
        self._params_dirty = False
        if self._mesh is not None:
            # re-commit: initializer writes land on the default device
            self._shard_exec_arrays()

    def get_params(self):
        """(parity: module.get_params) returns host copies."""
        assert self.binded and self.params_initialized
        arg_params = {n: arr.copy() for n, arr in self._exec.arg_dict.items()
                      if n in self._param_names}
        aux_params = {n: arr.copy() for n, arr in self._exec.aux_dict.items()}
        return arg_params, aux_params

    # -- optimizer ---------------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        """(parity: module.py init_optimizer:472)"""
        assert self.binded and self.params_initialized
        if self.optimizer_initialized and not force_init:
            return
        arg_dict = self._exec.arg_dict
        kv, update_on_kvstore = _create_kvstore(
            kvstore, len(self._context),
            {n: arg_dict[n] for n in self._param_names})

        if isinstance(optimizer, str):
            idx2name = {i: n for i, n in enumerate(self._param_names)}
            optimizer_params = dict(optimizer_params)
            optimizer_params.setdefault("rescale_grad", 1.0)
            optimizer = opt.create(optimizer, sym=self._symbol,
                                   param_idx2name=idx2name,
                                   **optimizer_params)
        self._optimizer = optimizer
        self._kvstore = kv
        self._update_on_kvstore = update_on_kvstore
        self._updater = None
        if kv is not None:
            if kv.type == "dist_sync" or update_on_kvstore:
                pass
            for i, name in enumerate(self._param_names):
                kv.init(i, arg_dict[name])
            if update_on_kvstore:
                kv.set_optimizer(self._optimizer)
        if not update_on_kvstore:
            self._updater = opt.get_updater(optimizer)
        self.optimizer_initialized = True

    # -- compute -----------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        """(parity: module.forward)"""
        assert self.binded and self.params_initialized
        if is_train is None:
            is_train = self.for_training
        self._set_batch(data_batch)
        self._exec.forward(is_train=is_train)

    def backward(self, out_grads=None):
        assert self.binded and self.params_initialized
        self._exec.backward(out_grads=out_grads)

    def forward_backward(self, data_batch):
        """Fused single-XLA-program step (overrides the base two-call path)."""
        assert self.binded and self.params_initialized
        self._set_batch(data_batch)
        self._exec.forward_backward()

    def _set_batch(self, data_batch):
        data = data_batch.data
        if not isinstance(data, (list, tuple)):
            data = [data]
        arg_dict = self._exec.arg_dict
        # variable batch shapes (e.g. eval batch != train batch): the
        # reference reshapes its executors (executor.py reshape); here the
        # same program simply jits a second signature, so just swap storage.
        reshaped = False
        for desc, arr in zip(self._data_shapes, data):
            if tuple(arr.shape) != arg_dict[desc.name].shape:
                arg_dict[desc.name]._set_data(
                    np.zeros(arr.shape, dtype=arg_dict[desc.name].dtype))
                reshaped = True
        if reshaped and data_batch.label is not None:
            labels = data_batch.label
            if not isinstance(labels, (list, tuple)):
                labels = [labels]
            for desc, arr in zip(self._label_shapes, labels):
                if tuple(arr.shape) != arg_dict[desc.name].shape:
                    arg_dict[desc.name]._set_data(
                        np.zeros(arr.shape, dtype=arg_dict[desc.name].dtype))
        for desc, arr in zip(self._data_shapes, data):
            self._write_input(arg_dict[desc.name], arr)
        label = data_batch.label
        if label is not None:
            if not isinstance(label, (list, tuple)):
                label = [label]
            for desc, arr in zip(self._label_shapes, label):
                self._write_input(arg_dict[desc.name], arr)

    def _write_input(self, dst, src):
        if self._mesh is not None:
            # commit the batch sharded over dp so GSPMD splits the step;
            # keep the bound placeholder's dtype (as copyto/setitem do)
            import jax
            dt = dst._data.dtype
            raw = src._data if isinstance(src, NDArray) else np.asarray(src)
            if isinstance(raw, np.ndarray):
                raw = jax.device_put(raw.astype(dt, copy=False),
                                     self._data_sharding)
            else:
                raw = jax.device_put(raw, self._data_sharding).astype(dt)
            dst._set_data(raw)
        elif isinstance(src, NDArray):
            src.copyto(dst)
        else:
            dst[:] = np.asarray(src)

    def update(self):
        """Apply one optimizer step (parity: module.update →
        model._update_params(_on_kvstore):106-138)."""
        assert self.binded and self.params_initialized \
            and self.optimizer_initialized
        self._params_dirty = True
        arg_dict = self._exec.arg_dict
        grad_dict = self._exec.grad_dict
        # push/pull whole key LISTS: in dist mode kvstore then reduces all
        # keys in one jitted collective instead of one dispatch per param
        live = [(i, name) for i, name in enumerate(self._param_names)
                if grad_dict.get(name) is not None]
        if not live:
            return
        keys = [i for i, _ in live]
        grads = [grad_dict[name] for _, name in live]
        if self._kvstore is not None and self._update_on_kvstore:
            self._kvstore.push(keys, grads)
            self._kvstore.pull(keys, out=[arg_dict[name] for _, name in live])
        else:
            if self._kvstore is not None:
                self._kvstore.push(keys, grads)
                self._kvstore.pull(keys, out=grads)
            # one fused dispatch for the whole parameter set (FusedUpdater)
            self._updater.update_batch(
                keys, grads, [arg_dict[name] for _, name in live])

    def get_outputs(self, merge_multi_context=True):
        assert self.binded
        return list(self._exec.outputs)

    def get_input_grads(self, merge_multi_context=True):
        assert self.binded and self.inputs_need_grad
        gd = self._exec.grad_dict
        return [gd[n] for n in self._data_names]

    def update_metric(self, eval_metric, labels):
        eval_metric.update(labels if isinstance(labels, (list, tuple))
                           else [labels], self.get_outputs())

    # -- checkpoints -------------------------------------------------------
    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        """(parity: module.py save_checkpoint:164)"""
        arg_params, aux_params = self.get_params()
        save_checkpoint(prefix, epoch, self._symbol, arg_params, aux_params)
        if save_optimizer_states:
            self.save_optimizer_states("%s-%04d.states" % (prefix, epoch))

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        """(parity: module.py Module.load:126)"""
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        mod = Module(symbol, **kwargs)
        mod._arg_params = arg_params
        mod._aux_params = aux_params
        # reference Module.load marks params initialised; bind() installs
        # them into the executor (module.py:126-183)
        mod.params_initialized = True
        mod._preloaded_params = (arg_params, aux_params)
        if load_optimizer_states:
            mod._preload_opt_states = "%s-%04d.states" % (prefix, epoch)
        return mod

    def borrow_optimizer(self, shared_module):
        """Share another Module's optimizer/updater (parity:
        module.borrow_optimizer)."""
        assert shared_module.optimizer_initialized
        self._optimizer = shared_module._optimizer
        self._kvstore = shared_module._kvstore
        self._update_on_kvstore = shared_module._update_on_kvstore
        self._updater = shared_module._updater
        self.optimizer_initialized = True

    def get_input_grads(self, merge_multi_context=True):
        """Gradients w.r.t. inputs from the last backward (parity:
        module.get_input_grads — requires inputs_need_grad)."""
        assert self.binded and self.params_initialized
        assert self.inputs_need_grad
        grads = self._exec.grad_dict
        return [grads[name] for name in self._data_names if name in grads]

    def save_optimizer_states(self, fname):
        """(parity: module.save_optimizer_states:759)"""
        assert self.optimizer_initialized
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname)
        else:
            with open(fname, "wb") as f:
                f.write(self._updater.get_states())

    def load_optimizer_states(self, fname):
        assert self.optimizer_initialized
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
        else:
            with open(fname, "rb") as f:
                self._updater.set_states(f.read())

    def install_monitor(self, mon):
        assert self.binded
        mon.install(self._exec)

    def reshape(self, data_shapes, label_shapes=None):
        """(parity: module.reshape) — on TPU just a new jit signature."""
        assert self.binded
        arg_p, aux_p = self.get_params() if self.params_initialized else (None, None)
        self.binded = False
        self._exec = None
        self.bind(data_shapes, label_shapes, self.for_training,
                  self.inputs_need_grad, force_rebind=True)
        if arg_p is not None:
            self.set_params(arg_p, aux_p)

    def init_params_from_preloaded(self):
        if getattr(self, "_preloaded_params", None) and self.binded:
            arg_p, aux_p = self._preloaded_params
            self.set_params(arg_p, aux_p)


def _as_desc(d):
    from ..io import DataDesc
    if isinstance(d, DataDesc):
        return d
    name, shape = d[0], d[1]
    return DataDesc(name, shape)
