"""BaseModule: the high-level train/eval loop.

Parity: reference ``python/mxnet/module/base_module.py`` (fit:376,
forward_backward:189, score, predict). The training loop is unchanged at
the API level; the speed comes from Module's fused jitted step underneath.
"""
from __future__ import annotations

import logging
import time

import numpy as np

from ..base import MXNetError
from .. import metric as _metric
from .. import telemetry
from ..model import BatchEndParam
from ..initializer import Uniform


# stable fallback reason codes -> what they mean. Bench lanes and tests
# assert on CODES; the human-readable message may reword freely.
FUSED_FALLBACK_CODES = {
    "env_pin": "MXNET_MODULE_FUSED_STEP=0 pins the phase-split A/B leg",
    "monitor": "per-op monitor taps need the phase-split programs",
    "kvstore_dist": "dist_* kvstore push/pull crosses worker processes",
    "kvstore_compression": "gradient compression changes pushed values",
    "group2ctx": "grouped (group2ctx) programs run eagerly per segment",
    "no_fused_updater": "updater has no fused batch path",
    "inputs_need_grad": "data gradients are phase-split only",
    "optimizer_kernel": "optimizer has no pure SPMD batch kernel",
    "centered_rmsprop": "centered RMSProp state layout",
    "no_trainable_params": "nothing to update",
    "state_layout": "optimizer state layout not expressible as a kernel",
    "missing_input": "bound input missing from the executor arg dict",
    "unfed_graph_arg": "graph argument not fed by the fused step",
    "not_initialised": "module not fully initialised",
}


class FusedFallback(str):
    """Why one step ran phase-split instead of fused. A ``str`` subclass
    so every existing message-text consumer (tests, bench JSON, logs)
    keeps working unchanged; ``code`` is the STABLE enumerable identity
    (one of ``FUSED_FALLBACK_CODES``) for bench lanes and tests to
    assert on, and ``detail`` carries the free-form specifics."""
    __slots__ = ("code", "detail")

    def __new__(cls, code, message, detail=None):
        assert code in FUSED_FALLBACK_CODES, code
        self = str.__new__(cls, message)
        self.code = code
        self.detail = message if detail is None else detail
        return self


class BaseModule:
    """(parity: base_module.BaseModule)"""

    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.inputs_need_grad = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None
        self._total_exec_bytes = 0

    # -- things subclasses implement --------------------------------------
    def forward(self, data_batch, is_train=None):
        raise NotImplementedError

    def backward(self, out_grads=None):
        raise NotImplementedError

    def update(self):
        raise NotImplementedError

    def get_outputs(self, merge_multi_context=True):
        raise NotImplementedError

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError

    def bind(self, *args, **kwargs):
        raise NotImplementedError

    def init_params(self, *args, **kwargs):
        raise NotImplementedError

    def init_optimizer(self, *args, **kwargs):
        raise NotImplementedError

    # -- composite ---------------------------------------------------------
    def forward_backward(self, data_batch):
        """(parity: base_module.forward_backward:189)"""
        self.forward(data_batch, is_train=True)
        self.backward()

    def _fused_batch_step(self, data_batch, eval_metric=None):
        """Whole-train-step fusion hook: run forward+backward+optimizer
        (+metric) as ONE compiled program and return True, or return
        False when the caller must use the phase-split path. Subclasses
        with a fused program override (Module, BucketingModule); the
        base class always phase-splits."""
        return False

    def _note_fused_fallback(self):
        """Account one phase-split step: count the fallback event in the
        telemetry registry (keyed by the stable ``FusedFallback.code``)
        and log it through log.py as a structured warning ONCE per
        module per code — the reason used to sit silently in
        ``_fused_fallback_reason``."""
        reason = getattr(self, "_fused_fallback_reason", None)
        if reason is None:
            return
        code = getattr(reason, "code", "unknown")
        telemetry.record_fallback(code)
        logged = self.__dict__.setdefault("_fused_fallback_logged", set())
        if code not in logged:
            logged.add(code)
            from .. import log as _log
            _log.get_logger("mxnet_tpu.module").warning(
                "fused-step fallback code=%s: %s (detail: %s) — this "
                "module trains phase-split (see "
                "mx.mod.FUSED_FALLBACK_CODES)",
                code, str(reason), getattr(reason, "detail", str(reason)))

    def telemetry_snapshot(self):
        """The process-wide ``telemetry.snapshot()`` (dispatch counts,
        jit compiles vs. cache hits, fused-fallback codes, transfer
        bytes, blocking host syncs, span p50/p95/p99, the PROGRAM CARDS
        of every compiled XLA program with their cost/memory figures,
        the online MFU estimate and the device-buffer ledger) plus this
        module's last fused-fallback reason/code. JSON-serializable end
        to end — bench/probe artifacts embed it per leg."""
        snap = telemetry.snapshot()
        reason = getattr(self, "_fused_fallback_reason", None)
        snap["fused_fallback_reason"] = None if reason is None else str(reason)
        snap["fused_fallback_code"] = getattr(reason, "code", None)
        return snap

    def fused_step(self, data, label=None, eval_metric=None):
        """Run ONE whole training step — forward, backward, optimizer
        update, and (when ``eval_metric`` can accumulate on device)
        metric update — as a single compiled XLA program with parameter /
        optimizer-state / metric buffers donated. This is the
        ``Module.fit`` inner loop exposed for manual training loops:

            for batch in train_iter:
                mod.fused_step(batch, eval_metric=metric)

        ``data`` may be a DataBatch (then ``label`` is ignored) or an
        NDArray/list of NDArrays with ``label`` alongside. When any
        piece cannot fuse (see Module._fused_batch_step for the rules)
        the step still runs — phase-split — and False is returned;
        True means the single fused program ran."""
        from ..io import DataBatch
        if not isinstance(data, DataBatch):
            d = list(data) if isinstance(data, (list, tuple)) else [data]
            lab = None if label is None else (
                list(label) if isinstance(label, (list, tuple)) else [label])
            data = DataBatch(data=d, label=lab)
        if self._fused_batch_step(data, eval_metric):
            return True
        self._note_fused_fallback()
        self.forward_backward(data)
        self.update()
        if eval_metric is not None:
            self.update_metric(eval_metric, data.label)
        return False

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None, reset=True,
              epoch=0, sparse_row_id_fn=None):
        """(parity: base_module.score)"""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        if not isinstance(eval_metric, _metric.EvalMetric):
            eval_metric = _metric.create(eval_metric)
        eval_metric.reset()
        actual_num_batch = 0
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            self.update_metric(eval_metric, eval_batch.label)
            if batch_end_callback is not None:
                param = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                      eval_metric=eval_metric, locals=locals())
                for cb in _as_list(batch_end_callback):
                    cb(param)
            actual_num_batch += 1
        if score_end_callback:
            param = BatchEndParam(epoch=epoch, nbatch=actual_num_batch,
                                  eval_metric=eval_metric, locals=locals())
            for cb in _as_list(score_end_callback):
                cb(param)
        return eval_metric.get_name_value()

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False,
                sparse_row_id_fn=None):
        """(parity: base_module.predict)"""
        from ..ndarray import concatenate
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        output_list = []
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad or 0
            outs = [out[0:out.shape[0] - pad] for out in self.get_outputs()]
            output_list.append(outs)
        if len(output_list) == 0:
            return output_list
        if merge_batches:
            num_outputs = len(output_list[0])
            for out in output_list:
                if len(out) != num_outputs:
                    raise MXNetError("Cannot merge batches: different number "
                                     "of outputs per batch")
            output_list2 = [concatenate([out[i] for out in output_list])
                            for i in range(num_outputs)]
            if num_outputs == 1 and not always_output_list:
                return output_list2[0]
            return output_list2
        return output_list

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            optimizer="sgd", optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=Uniform(0.01), arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, sparse_row_id_fn=None,
            checkpoint=None, resume=None,
            divergence_check_every=0, divergence_policy="halt"):
        """Train (parity: base_module.fit:376 — bind → init_params →
        init_optimizer → per-batch forward_backward/update/metric loop).

        Fault-tolerance extensions (no reference counterpart):

        - ``checkpoint``: a ``CheckpointManager`` (or prefix string)
          that (a) saves an atomic keep-last-K checkpoint at every
          epoch end and (b) ARMS SIGTERM/SIGINT for the duration of
          fit: a signal sets a flag checked at batch boundaries, the
          in-flight batch completes, a mid-epoch checkpoint
          (epoch, nbatch) is written, and ``TrainingPreempted`` is
          raised — the preemption grace window buys one atomic save,
          not a stack unwind.
        - ``resume``: ``True`` (resume from ``checkpoint``'s latest),
          or a ``CheckpointManager``/prefix. Restores params,
          optimizer states + per-parameter update counts, and the
          global RNG key, then continues from the recorded
          epoch+batch (already-applied batches of the resumed epoch
          are consumed from the iterator without compute). No
          checkpoint found = fresh start, not an error.
        - ``divergence_check_every`` / ``divergence_policy``: every N
          batches run the divergence sentinel (``finite_check()`` — a
          device-side isfinite fold over the step outputs and, for
          Module, every parameter). On non-finite values the policy
          applies: ``"halt"`` raises ``DivergenceError``, ``"skip"``
          logs + counts and keeps training, ``"rollback"`` restores
          the ``checkpoint`` manager's latest checkpoint and
          continues (halts when there is nothing to roll back to).
        """
        from ..checkpoint import CheckpointManager, TrainingPreempted
        assert num_epoch is not None, "please specify number of epochs"
        if divergence_policy not in ("halt", "skip", "rollback"):
            raise MXNetError("divergence_policy must be halt|skip|"
                             "rollback, got %r" % (divergence_policy,))
        ckpt = checkpoint
        if isinstance(ckpt, str):
            ckpt = CheckpointManager(ckpt)
        rmgr = None
        if resume is not None and resume is not False:
            rmgr = ckpt if resume is True else resume
            if isinstance(rmgr, str):
                rmgr = CheckpointManager(rmgr)
            if rmgr is None:
                raise MXNetError("fit(resume=True) needs checkpoint=")
        resume_meta = rmgr.latest() if rmgr is not None else None
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label, for_training=True,
                  force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)
        skip_batches = 0
        if resume_meta is not None:
            rmgr.restore(self, resume_meta)
            begin_epoch = int(resume_meta["epoch"])
            skip_batches = int(resume_meta.get("nbatch", 0))
            self.logger.info(
                "Resuming from checkpoint %s: epoch=%d nbatch=%d",
                rmgr.prefix, begin_epoch, skip_batches)
        if validation_metric is None:
            validation_metric = eval_metric
        if not isinstance(eval_metric, _metric.EvalMetric):
            eval_metric = _metric.create(eval_metric)

        from ..heartbeat import DeadWorkerError
        if ckpt is not None:
            ckpt.clear_preempt()
            ckpt.arm_signals()
        try:
            while True:
                try:
                    self._fit_loop(train_data, eval_data, eval_metric,
                                   validation_metric, epoch_end_callback,
                                   batch_end_callback, eval_end_callback,
                                   eval_batch_end_callback, monitor,
                                   sparse_row_id_fn, begin_epoch,
                                   num_epoch, skip_batches, ckpt,
                                   divergence_check_every,
                                   divergence_policy)
                    break
                except DeadWorkerError as e:
                    # ELASTIC RECOVERY: a peer died before a collective
                    # (the liveness gate aborted the step — nothing is
                    # hung). Postmortem the death, re-mesh over the
                    # survivors, restore the last atomic checkpoint and
                    # continue the SAME fit call from its (epoch,
                    # nbatch). Work since that checkpoint is lost —
                    # that is the recovery contract (README
                    # "Distributed training").
                    meta = self._elastic_recover(e, ckpt)
                    begin_epoch = int(meta["epoch"])
                    skip_batches = int(meta.get("nbatch", 0))
        finally:
            if ckpt is not None:
                ckpt.disarm_signals()

    # mxlint: hot
    def _fit_loop(self, train_data, eval_data, eval_metric,
                  validation_metric, epoch_end_callback,
                  batch_end_callback, eval_end_callback,
                  eval_batch_end_callback, monitor, sparse_row_id_fn,
                  begin_epoch, num_epoch, skip_batches, ckpt,
                  divergence_check_every, divergence_policy):
        from ..checkpoint import TrainingPreempted
        from ..heartbeat import DeadWorkerError
        train_data.reset()
        for epoch in range(begin_epoch, num_epoch):
            tic = time.time()
            eval_metric.reset()
            nbatch = 0
            data_iter = iter(train_data)
            end_of_batch = False
            if epoch == begin_epoch and skip_batches:
                # mid-epoch resume: the checkpoint already holds these
                # batches' updates — consume them without compute so
                # the remaining epoch sees the SAME data the
                # interrupted run would have
                for _ in range(skip_batches):
                    try:
                        next(data_iter)
                    except StopIteration:
                        break
                nbatch = skip_batches
            try:
                next_data_batch = next(data_iter)
            except StopIteration:
                end_of_batch = True
                next_data_batch = None
            while not end_of_batch:
                data_batch = next_data_batch
                if monitor is not None:
                    monitor.tic()
                # whole-step fused program when every piece can ride
                # (one device dispatch, buffers donated, metric
                # accumulated in-program); phase-split otherwise — see
                # Module._fused_batch_step for the fallback rules. The
                # loop itself never blocks on device values: batch N+1
                # dispatches while batch N executes, metric values are
                # fetched lazily (sync happens only at epoch end and in
                # callbacks that read the metric).
                # The causal() scope stamps (epoch, nbatch) step ids on
                # every span this batch records (fit_batch, feed, step,
                # opt_update, ...) so the merged chrome trace links one
                # step's spans with flow arrows and a postmortem's ring
                # says which step each interval served.
                try:
                    with telemetry.causal(epoch=epoch, nbatch=nbatch), \
                            telemetry.span("fit_batch"):
                        fused = self._fused_batch_step(data_batch,
                                                       eval_metric)
                        if not fused:
                            self._note_fused_fallback()
                            self.forward_backward(data_batch)
                            self.update()
                        try:
                            next_data_batch = next(data_iter)
                            self.prepare(next_data_batch,
                                         sparse_row_id_fn=sparse_row_id_fn)
                        except StopIteration:
                            end_of_batch = True
                        if not fused:
                            self.update_metric(eval_metric,
                                               data_batch.label)
                except DeadWorkerError as e:
                    # stamp the step the death aborted — the elastic
                    # handler's postmortem names it
                    if e.epoch is None:
                        e.epoch, e.nbatch = epoch, nbatch
                    raise
                if monitor is not None:
                    monitor.toc_print()
                if divergence_check_every > 0 \
                        and (nbatch + 1) % divergence_check_every == 0 \
                        and not self.finite_check():   # mxlint: disable=host-sync -- opt-in divergence sentinel: the user asked for a blocking verdict once per divergence_check_every batches
                    self._handle_divergence(divergence_policy, ckpt,
                                            epoch, nbatch)
                if batch_end_callback is not None:
                    with telemetry.span("callbacks"):
                        param = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                              eval_metric=eval_metric,
                                              locals=locals())
                        for cb in _as_list(batch_end_callback):
                            cb(param)
                nbatch += 1
                # batch-boundary preemption point: the armed signal set
                # the flag; nbatch batches of this epoch are applied, so
                # (epoch, nbatch) resumes exactly here
                if ckpt is not None and ckpt.preempt_requested:
                    source = ckpt.preempt_requested
                    ckpt.save(self, epoch, nbatch)
                    telemetry.counter_inc("training.preempted")
                    telemetry.record_event("training.preempted",
                                           source=source, epoch=epoch,
                                           nbatch=nbatch)
                    from .. import flight as _flight
                    _flight.postmortem(
                        "training_preempted",
                        extra={"source": source, "epoch": epoch,
                               "nbatch": nbatch,
                               "prefix": ckpt.prefix})
                    raise TrainingPreempted(
                        "training preempted by %s at epoch %d batch %d; "
                        "checkpoint saved under %r — fit(resume=...) "
                        "continues from here" % (source, epoch, nbatch,
                                                 ckpt.prefix),
                        epoch=epoch, nbatch=nbatch, prefix=ckpt.prefix)

            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                             time.time() - tic)

            # epoch-end host param sync ONLY at a callback boundary: the
            # executor already holds the canonical values, so the
            # reference's unconditional get_params→set_params round trip
            # (every parameter through the host, every epoch — multiple
            # ms/epoch on a relayed PJRT backend) buys nothing without a
            # consumer
            if epoch_end_callback is not None:
                with telemetry.span("epoch_sync"):
                    arg_p, aux_p = self.get_params()
                with telemetry.span("callbacks"):
                    for cb in _as_list(epoch_end_callback):
                        cb(epoch, self.symbol, arg_p, aux_p)
            if ckpt is not None:
                # epoch complete: resume point is the NEXT epoch's start
                ckpt.save(self, epoch + 1, 0)

            if eval_data is not None:
                res = self.score(eval_data, validation_metric,
                                 score_end_callback=eval_end_callback,
                                 batch_end_callback=eval_batch_end_callback,
                                 epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f", epoch,
                                     name, val)
            train_data.reset()

    def _elastic_remesh(self, dead_ranks):
        """Adopt the surviving membership after a member loss. The base
        class has no mesh to rebuild; ``Module`` overrides with the
        real detach + re-mesh."""
        from .. import dist as _dist
        _dist.mark_member_lost(dead_ranks)

    def _elastic_recover(self, e, ckpt):
        """Handle a :class:`heartbeat.DeadWorkerError` raised by the
        pre-collective liveness gate: write the postmortem naming the
        dead rank(s) and the step they died on, re-mesh over the
        survivors, restore the last atomic checkpoint and return its
        meta (the resume point). Re-raises when there is no checkpoint
        to recover from — a member loss without a checkpoint is fatal
        by design (there is nothing consistent to resume)."""
        telemetry.counter_inc("elastic.dead_workers", len(e.ranks))
        telemetry.record_event("elastic.dead_worker",
                               dead=list(e.ranks), channel=e.channel,
                               generation=e.generation, epoch=e.epoch,
                               nbatch=e.nbatch,
                               timed_out=bool(e.timed_out))
        from .. import flight as _flight
        from .. import dist as _dist
        _flight.postmortem(
            "dead_worker", exc=e,
            extra={"dead_ranks": list(e.ranks),
                   "channel": e.channel,
                   "generation": e.generation,
                   "epoch": e.epoch, "nbatch": e.nbatch,
                   "timed_out": bool(e.timed_out),
                   "survivor_rank": _dist.rank(),
                   "live_ranks": [r for r in _dist.live_ranks()
                                  if r not in e.ranks],
                   # every reachable peer's newest dump from the shared
                   # flight dir — a dying rank banks a worker_abort on
                   # its way through dist.abort, so the cluster view
                   # shows the VICTIM's last seconds too, not just this
                   # survivor's keyhole
                   "peer_postmortems": _flight.gather_peer_postmortems()})
        from .. import log as _log
        logger = _log.get_logger("mxnet_tpu.module")
        if ckpt is None or ckpt.latest() is None:
            logger.error(
                "worker(s) %s died at epoch %s batch %s and no "
                "checkpoint manager (fit(checkpoint=...)) is armed — "
                "cannot re-mesh without a consistent state to resume "
                "from", list(e.ranks), e.epoch, e.nbatch)
            raise e
        logger.warning(
            "worker(s) %s died at epoch %s batch %s — re-meshing over "
            "the survivors and resuming from the last checkpoint",
            list(e.ranks), e.epoch, e.nbatch)
        self._elastic_remesh(e.ranks)
        meta = ckpt.restore(self)
        telemetry.counter_inc("elastic.resumed")
        telemetry.record_event("elastic.resumed",
                               epoch=int(meta["epoch"]),
                               nbatch=int(meta.get("nbatch", 0)))
        return meta

    def finite_check(self):
        """The divergence sentinel's predicate: True when the last
        step's values are all finite. Base implementation folds the
        OUTPUT heads on the host; ``Module`` overrides with a
        device-side fold that also covers every parameter (a NaN
        gradient poisons the params on the very step it appears, so
        the fold catches it at the next check)."""
        for o in self.get_outputs():
            a = o.asnumpy()
            if np.issubdtype(a.dtype, np.floating) \
                    and not np.isfinite(a).all():
                return False
        return True

    def _handle_divergence(self, policy, ckpt, epoch, nbatch):
        """Apply the divergence policy after ``finite_check()`` failed:
        count it, then skip / rollback / halt."""
        from ..checkpoint import DivergenceError
        telemetry.counter_inc("divergence.detected")
        telemetry.record_event("divergence.detected", epoch=epoch,
                               nbatch=nbatch, policy=policy)
        where = "epoch %d batch %d" % (epoch, nbatch)
        from .. import log as _log
        logger = _log.get_logger("mxnet_tpu.module")
        if policy == "skip":
            telemetry.counter_inc("divergence.skipped")
            logger.warning(
                "divergence sentinel: non-finite loss/params at %s — "
                "policy=skip, continuing (the next finite batches may "
                "recover, or may not: consider policy=rollback)", where)
            return
        if policy == "rollback":
            if ckpt is not None and ckpt.latest() is not None:
                meta = ckpt.restore(self)
                telemetry.counter_inc("divergence.rollback")
                logger.warning(
                    "divergence sentinel: non-finite loss/params at %s "
                    "— rolled back to checkpoint epoch=%d nbatch=%d",
                    where, meta["epoch"], meta.get("nbatch", 0))
                return
            logger.warning(
                "divergence sentinel: policy=rollback but no checkpoint "
                "to roll back to — halting")
        err = DivergenceError(
            "divergence sentinel: non-finite loss/params at %s "
            "(policy=%s)" % (where, policy))
        from .. import flight as _flight
        _flight.postmortem("divergence", exc=err,
                           extra={"epoch": epoch, "nbatch": nbatch,
                                  "policy": policy})
        raise err

    def prepare(self, data_batch, sparse_row_id_fn=None):
        pass

    def install_monitor(self, mon):
        pass

    # -- params ------------------------------------------------------------
    def iter_predict(self, eval_data, num_batch=None, reset=True):
        """Iterate over (outputs, batch_index, batch) during prediction
        (parity: base_module.iter_predict)."""
        assert self.binded and self.params_initialized
        if reset:
            eval_data.reset()
        for nbatch, eval_batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(eval_batch, is_train=False)
            pad = eval_batch.pad
            outputs = [out[0:out.shape[0] - (pad or 0)]
                       for out in self.get_outputs()]
            yield outputs, nbatch, eval_batch

    def get_states(self, merge_multi_context=True):
        """States of stateful modules — none here (parity:
        base_module.get_states; mirrors the reference default)."""
        assert self.binded and self.params_initialized
        return []

    def set_states(self, states=None, value=None):
        """(parity: base_module.set_states — no-op for stateless)"""
        assert self.binded and self.params_initialized
        assert not states and not value

    def get_input_grads(self, merge_multi_context=True):
        """Gradients w.r.t. the input data (parity:
        base_module.get_input_grads)."""
        raise NotImplementedError()

    def get_params(self):
        raise NotImplementedError

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init, allow_extra=allow_extra)

    def save_params(self, fname):
        from ..ndarray import save
        arg_params, aux_params = self.get_params()
        save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
        save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
        save(fname, save_dict)

    def load_params(self, fname):
        from ..ndarray import load
        save_dict = load(fname)
        arg_params, aux_params = {}, {}
        for k, value in save_dict.items():
            arg_type, name = k.split(":", 1)
            if arg_type == "arg":
                arg_params[name] = value
            elif arg_type == "aux":
                aux_params[name] = value
            else:
                raise MXNetError("Invalid param file: " + fname)
        self.set_params(arg_params, aux_params)

    @property
    def symbol(self):
        return self._symbol

    # introspection defaults
    @property
    def data_names(self):
        raise NotImplementedError

    @property
    def output_names(self):
        raise NotImplementedError

    @property
    def data_shapes(self):
        raise NotImplementedError

    @property
    def label_shapes(self):
        raise NotImplementedError

    @property
    def output_shapes(self):
        raise NotImplementedError


def _as_list(obj):
    if obj is None:
        return []
    if isinstance(obj, (list, tuple)):
        return list(obj)
    return [obj]
