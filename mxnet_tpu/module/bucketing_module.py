"""BucketingModule — variable-length sequence training.

Parity: reference ``python/mxnet/module/bucketing_module.py:35``. The
reference binds one executor per bucket sharing one memory pool; here each
bucket is simply a distinct jit signature of the same weights — XLA caches
one compiled program per bucket (the CachedOp per-signature re-plan,
SURVEY.md §7 "Dynamic shapes"), and parameters are shared by reference.
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from .base_module import BaseModule, FusedFallback
from .module import Module


class BucketingModule(BaseModule):
    """(parity: bucketing_module.BucketingModule)"""

    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, group2ctxs=None, compression_params=None):
        super().__init__(logger=logger)
        assert default_bucket_key is not None
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._context = context
        self._fixed_param_names = fixed_param_names
        self._state_names = state_names
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None
        self._params_dirty = False

    @property
    def data_names(self):
        if self.binded:
            return self._curr_module.data_names
        _, data_names, _ = self._call_sym_gen(self._default_bucket_key)
        return data_names

    @property
    def output_names(self):
        if self.binded:
            return self._curr_module.output_names
        symbol, _, _ = self._call_sym_gen(self._default_bucket_key)
        return symbol.list_outputs()

    @property
    def data_shapes(self):
        assert self.binded
        return self._curr_module.data_shapes

    @property
    def label_shapes(self):
        assert self.binded
        return self._curr_module.label_shapes

    @property
    def output_shapes(self):
        assert self.binded
        return self._curr_module.output_shapes

    @property
    def symbol(self):
        assert self.binded
        return self._curr_module.symbol

    def _call_sym_gen(self, bucket_key):
        return self._sym_gen(bucket_key)

    def _get_module(self, bucket_key, data_shapes=None, label_shapes=None):
        if bucket_key not in self._buckets:
            symbol, data_names, label_names = self._call_sym_gen(bucket_key)
            module = Module(symbol, data_names, label_names,
                            logger=self.logger, context=self._context,
                            fixed_param_names=self._fixed_param_names,
                            state_names=self._state_names)
            self._buckets[bucket_key] = module
        return self._buckets[bucket_key]

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        module = self._get_module(self._default_bucket_key)
        module.bind(data_shapes, label_shapes, for_training, inputs_need_grad,
                    force_rebind=force_rebind, grad_req=grad_req)
        self._curr_module = module
        self._curr_bucket_key = self._default_bucket_key
        self.binded = True

    def get_states(self, merge_multi_context=True):
        """(parity: bucketing_module.get_states — delegates)"""
        assert self.binded and self.params_initialized
        return self._curr_module.get_states(merge_multi_context)

    def set_states(self, states=None, value=None):
        assert self.binded and self.params_initialized
        self._curr_module.set_states(states, value)

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        """(parity: bucketing_module.switch_bucket)"""
        assert self.binded
        module = self._get_module(bucket_key)
        if not module.binded:
            module.bind(data_shapes, label_shapes, self.for_training,
                        self.inputs_need_grad)
            if self._curr_module.params_initialized:
                arg_p, aux_p = self._curr_module.get_params()
                module.init_params(arg_params=arg_p, aux_params=aux_p,
                                   allow_missing=False, force_init=True)
                module.params_initialized = True
            if self._curr_module.optimizer_initialized:
                module._optimizer = self._curr_module._optimizer
                module._updater = self._curr_module._updater
                module._kvstore = self._curr_module._kvstore
                module._update_on_kvstore = self._curr_module._update_on_kvstore
                module.optimizer_initialized = True
        else:
            # share the latest params
            if self._curr_module is not module and \
                    self._curr_module.params_initialized:
                arg_p, aux_p = self._curr_module.get_params()
                module.init_params(arg_params=arg_p, aux_params=aux_p,
                                   force_init=True)
        self._curr_module = module
        self._curr_bucket_key = bucket_key

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        assert self.binded
        from ..initializer import Uniform
        self._curr_module.init_params(
            initializer=initializer if initializer is not None else Uniform(0.01),
            arg_params=arg_params, aux_params=aux_params,
            allow_missing=allow_missing, force_init=force_init,
            allow_extra=allow_extra)
        self.params_initialized = True

    def get_params(self):
        assert self.binded and self.params_initialized
        return self._curr_module.get_params()

    def init_optimizer(self, **kwargs):
        assert self.binded and self.params_initialized
        self._curr_module.init_optimizer(**kwargs)
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        assert self.binded and self.params_initialized
        self.switch_bucket(data_batch.bucket_key, data_batch.provide_data,
                           data_batch.provide_label)
        self._curr_module.forward(data_batch, is_train=is_train)

    def forward_backward(self, data_batch):
        assert self.binded and self.params_initialized
        self.switch_bucket(data_batch.bucket_key, data_batch.provide_data,
                           data_batch.provide_label)
        self._curr_module.forward_backward(data_batch)

    def _fused_batch_step(self, data_batch, eval_metric=None):
        """Whole-train-step fusion, PER BUCKET: switch to the batch's
        bucket (the shared optimizer/updater state rides across — update
        counts stay uniform), then delegate to that bucket Module's fused
        program. A bucket whose graph can't fuse falls back for ITS
        batches only; fusible buckets keep their one-dispatch step, and
        each bucket caches its own compiled signature."""
        assert self.binded and self.params_initialized
        self.switch_bucket(data_batch.bucket_key, data_batch.provide_data,
                           data_batch.provide_label)
        fused = self._curr_module._fused_batch_step(data_batch, eval_metric)
        if fused:
            self._params_dirty = True
        return fused

    @property
    def _fused_fallback_reason(self):
        """Why the CURRENT bucket's last step phase-split (None = fused);
        a ``FusedFallback`` str carrying the stable reason ``code``."""
        if self._curr_module is None:
            return FusedFallback("not_initialised",
                                 "module not fully initialised")
        return self._curr_module._fused_fallback_reason

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads=out_grads)

    def update(self):
        self._params_dirty = True
        self._curr_module.update()

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        return self._curr_module.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        self._curr_module.update_metric(eval_metric, labels)

    def install_monitor(self, mon):
        for module in self._buckets.values():
            if module.binded:
                module.install_monitor(mon)
