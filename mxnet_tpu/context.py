"""Device contexts.

Parity: reference ``include/mxnet/base.h:142-247`` (Context) and
``python/mxnet/context.py``. TPU-first redesign: a Context names a JAX
device. ``tpu()`` is the native accelerator context; ``gpu()`` is kept as
an alias for accelerator so reference scripts run unmodified; ``cpu()``
maps to the host platform. ``cpu_pinned()`` maps to host memory used for
staging (PJRT manages pinned transfer buffers itself, so it is an alias
of cpu for placement purposes).
"""
from __future__ import annotations

import threading

import jax

from .base import MXNetError

__all__ = ["Context", "cpu", "gpu", "tpu", "cpu_pinned", "current_context", "num_gpus"]


class Context:
    """A device context. Comparable/hashable; usable as a ``with`` scope."""

    devtype2str = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 4: "cpu_shared", 5: "tpu"}
    devstr2type = {v: k for k, v in devtype2str.items()}

    _default = threading.local()

    def __init__(self, device_type, device_id=0):
        if isinstance(device_type, Context):
            self.device_typeid = device_type.device_typeid
            self.device_id = device_type.device_id
        else:
            if device_type not in Context.devstr2type:
                raise MXNetError("unknown device type %r" % (device_type,))
            self.device_typeid = Context.devstr2type[device_type]
            self.device_id = device_id
        self._old = None

    @property
    def device_type(self):
        return Context.devtype2str[self.device_typeid]

    # -- JAX mapping --------------------------------------------------
    def jax_device(self):
        """Resolve this context to a concrete jax.Device. Always a LOCAL
        (process-addressable) device: under multi-process SPMD,
        jax.devices() lists the whole job's devices and rank r must not
        resolve cpu(0) to rank 0's device."""
        if self.device_type in ("cpu", "cpu_pinned", "cpu_shared"):
            devs = jax.local_devices(backend="cpu") if _has_platform("cpu") \
                else jax.local_devices()
        else:
            # tpu and the gpu alias both mean "the accelerator"
            devs = _accelerator_devices()
        if not devs:
            raise MXNetError("no devices for context %r" % (self,))
        return devs[self.device_id % len(devs)]

    # -- dunder -------------------------------------------------------
    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_typeid == other.device_typeid
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_typeid, self.device_id))

    def __repr__(self):
        return "%s(%d)" % (self.device_type, self.device_id)

    __str__ = __repr__

    def __enter__(self):
        self._old = getattr(Context._default, "value", None)
        Context._default.value = self
        return self

    def __exit__(self, *exc):
        Context._default.value = self._old
        self._old = None


def _has_platform(name):
    try:
        return bool(jax.devices(name))
    except RuntimeError:
        return False


def _accelerator_devices():
    """Local non-CPU devices if any; else all local devices (CPU-only
    test runs)."""
    devs = jax.local_devices()
    accel = [d for d in devs if d.platform != "cpu"]
    return accel or devs


def cpu(device_id=0):
    return Context("cpu", device_id)


def gpu(device_id=0):
    """Alias for the accelerator so reference code using mx.gpu() runs on TPU."""
    return Context("gpu", device_id)


def tpu(device_id=0):
    return Context("tpu", device_id)


def cpu_pinned(device_id=0):
    return Context("cpu_pinned", device_id)


def num_gpus():
    """Number of accelerator chips visible (parity: mx.context.num_gpus)."""
    devs = [d for d in jax.devices() if d.platform != "cpu"]
    return len(devs)


def current_context():
    ctx = getattr(Context._default, "value", None)
    if ctx is None:
        # Default to the accelerator when present, else cpu — the TPU-native
        # twist on the reference default of cpu(0).
        ctx = tpu(0) if num_gpus() > 0 else cpu(0)
    return ctx
