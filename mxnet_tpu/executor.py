"""Executor — compiled execution of Symbol graphs.

Parity: reference ``src/executor/graph_executor.cc`` + ``python/mxnet/
executor.py``. TPU-native design: instead of the reference's pipeline
(nnvm Gradient pass → PlanMemory → per-node OpExecutors → engine pushes,
graph_executor.cc:956-1490), the whole forward graph is ONE traced JAX
function; ``jax.vjp`` over it is the Gradient pass; ``jax.jit`` is
PlanMemory + op fusion + scheduling. One executor therefore makes at most
three XLA programs: forward(train), forward(infer), forward+backward —
each fully fused and memory-planned by XLA for the MXU/HBM.

Random ops get their keys from an explicit key argument folded per-node
(ops/common.rng_scope), keeping compiled programs pure. BatchNorm-style
aux updates come back as extra outputs and are written into aux arrays,
mirroring the reference's in-place aux mutation.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

import itertools
import time

from .base import MXNetError
from .context import current_context
from .ops.common import rng_scope, mx_dtype
from . import random as _random
from . import telemetry
from . import faults

__all__ = ["Executor", "infer_graph_shapes", "record_dispatch",
           "card_from_compiled", "DeviceMemoryError"]


# ---------------------------------------------------------------------------
# Dispatch accounting
# ---------------------------------------------------------------------------
# One call per jitted-program execution (NOT per eager op): the number of
# device dispatches per train batch is a load-bearing performance
# property on a remoted PJRT backend, so tests pin it. Every dispatch
# fans out through ``telemetry.dispatch_event`` — the counter registry
# plus every ``telemetry.on_dispatch(cb)`` subscriber. ``dispatch_hook``
# remains as the LEGACY single-slot shim (monkeypatch with a callable
# taking one tag string); prefer the multi-subscriber registry, which
# doesn't clobber other listeners.
dispatch_hook = None


def record_dispatch(kind):
    """Report one jitted-program execution to the telemetry dispatch
    registry (and the legacy single-slot ``dispatch_hook`` shim). The
    ONE dispatch-reporting entry point — tools/run_checks.sh lints that
    no other module grows a raw hook call."""
    if dispatch_hook is not None:
        dispatch_hook(kind)
    telemetry.dispatch_event(kind)


# ---------------------------------------------------------------------------
# Instrumented program compilation (program cards)
# ---------------------------------------------------------------------------
# Every jitted entry point in this module compiles through
# ``_InstrumentedProgram`` — explicit ``lower().compile()`` with the
# trace and compile phases timed as telemetry spans and the compiled
# executable's own cost/memory analysis captured into a PROGRAM CARD in
# ``telemetry.programs()``. The card is the online counterpart of an
# offline xprof capture: per-program FLOPs, bytes accessed, HBM
# footprint, compile wall-time and dispatch count, available at every
# ``telemetry.snapshot()`` — exactly the per-program features TPU cost
# models are built on (Kaufman et al. arXiv:2008.01040, TVM
# arXiv:1802.04799).

_PROG_SEQ = itertools.count(1)

# once-per-cause recompile warnings: (entry, path, change-kind) pairs
# already reported through log.py
_RECOMPILE_WARNED = set()


class DeviceMemoryError(MXNetError):
    """A device allocation failure (RESOURCE_EXHAUSTED / OOM) re-raised
    with the live buffer ledger and the failing program's memory card
    stitched into the message. The original backend error rides as
    ``__cause__``."""


_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "RESOURCE EXHAUSTED",
                "Out of memory", "out of memory", "OOM")


def _is_oom(exc):
    s = str(exc)
    return any(m in s for m in _OOM_MARKERS)


def _fmt_bytes(n):
    if n is None:
        return "?"
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return "%.1f%s" % (n, unit) if unit != "B" else "%dB" % n
        n /= 1024.0
    return "%d" % n


def _enriched_oom(exc, card):
    """Build the DeviceMemoryError for one dispatch-time OOM: the
    failing program's memory card + the ledger's per-context totals and
    top live buffers + PJRT device stats where the platform exposes
    them. The raw backend message stays first so existing matching on
    it keeps working."""
    lines = ["device memory exhausted dispatching program %r: %s"
             % (card.get("id", "?"), exc)]
    lines.append(
        "program memory card: peak_bytes=%s argument_bytes=%s "
        "output_bytes=%s temp_bytes=%s generated_code_bytes=%s "
        "flops=%s bytes_accessed=%s" % (
            _fmt_bytes(card.get("peak_bytes")),
            _fmt_bytes(card.get("argument_bytes")),
            _fmt_bytes(card.get("output_bytes")),
            _fmt_bytes(card.get("temp_bytes")),
            _fmt_bytes(card.get("generated_code_bytes")),
            card.get("flops"), card.get("bytes_accessed")))
    led = telemetry.ledger()
    if led:
        lines.append("live device-buffer ledger:")
        for ctx, st in sorted(led.items()):
            lines.append("  %s: %d buffers alive / %s (peak %s)"
                         % (ctx, st["alive_count"],
                            _fmt_bytes(st["alive_bytes"]),
                            _fmt_bytes(st["peak_bytes"])))
    top = telemetry.ledger_top(8)
    if top:
        lines.append("top live buffers:")
        for b in top:
            lines.append("  %s %s %s %s [%s]"
                         % (_fmt_bytes(b["nbytes"]),
                            tuple(b["shape"] or ()), b["dtype"], b["ctx"],
                            b["kind"]))
    try:
        from .storage import Storage
        stats = Storage.device_stats()
        if stats:
            lines.append("pjrt device stats: %s" % stats)
    except Exception:
        pass
    return DeviceMemoryError("\n".join(lines))


def _leaf_key(leaf):
    """Hashable (shape, dtype) of one argument leaf — the per-dispatch
    cache key component. Python scalars key by type (jax weak-types
    them; the value never changes the signature)."""
    try:
        return (leaf.shape, leaf.dtype)
    except AttributeError:
        return ((), type(leaf))


def _compiled_cost(compiled):
    """``Compiled.cost_analysis()`` normalised to one flat dict (older
    jaxlibs return a one-element list). Raising backends propagate to
    the caller's graceful-degradation path."""
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca or {})


def _compiled_memory(compiled):
    """``Compiled.memory_analysis()`` as a plain dict of byte counts."""
    ma = compiled.memory_analysis()
    return {
        "generated_code_bytes": int(ma.generated_code_size_in_bytes),
        "argument_bytes": int(ma.argument_size_in_bytes),
        "output_bytes": int(ma.output_size_in_bytes),
        "alias_bytes": int(ma.alias_size_in_bytes),
        "temp_bytes": int(ma.temp_size_in_bytes),
    }


def card_from_compiled(kind, compiled, entry=None, signature=None,
                       donated=(), extra=None):
    """Build one JSON-safe program card from an AOT-compiled
    executable. The ONE card builder — the executor's instrumented
    wrapper and bench.py's AOT step both use it, so the card schema
    cannot drift between the user path and the bench lane. Cost and
    memory analysis failures degrade to ``None`` fields (older jaxlib /
    backend quirks must never break dispatch)."""
    card = {
        "id": entry or "%s@p%d" % (kind, next(_PROG_SEQ)),
        "kind": kind,
        "signature": signature,
        "donated": sorted(donated),
        "dispatches": 0,
        "flops": None, "bytes_accessed": None, "transcendentals": None,
        "peak_bytes": None, "argument_bytes": None, "output_bytes": None,
        "alias_bytes": None, "temp_bytes": None,
        "generated_code_bytes": None,
    }
    if extra:
        card.update(extra)
    try:
        ca = _compiled_cost(compiled)
        for field, key in (("flops", "flops"),
                           ("bytes_accessed", "bytes accessed"),
                           ("transcendentals", "transcendentals")):
            if key in ca:
                card[field] = float(ca[key])
    except Exception:
        pass
    try:
        mem = _compiled_memory(compiled)
        card.update(mem)
        # peak HBM while the program runs: arguments + outputs + XLA's
        # temp arena + the program text itself, minus donated aliases
        card["peak_bytes"] = (mem["argument_bytes"] + mem["output_bytes"]
                              + mem["temp_bytes"]
                              + mem["generated_code_bytes"]
                              - mem["alias_bytes"])
    except Exception:
        pass
    return card


def _path_str(path, argnames):
    """Human arg path for one signature entry: the top-level tuple
    index renders as the entry point's argument NAME, the rest as
    jax's keystr — so a recompile cause reads ``inputs['data']``, not
    ``[4]['data']``."""
    from jax.tree_util import keystr
    head = ""
    rest = path
    if path and argnames:
        idx = getattr(path[0], "idx", None)
        if idx is not None and idx < len(argnames):
            head = argnames[idx]
            rest = path[1:]
    return head + keystr(tuple(rest))


class _InstrumentedProgram:
    """One jitted entry point, compiled through explicit
    ``lower().compile()`` with full introspection:

    * per-signature AOT executables cached on (treedef, leaf
      shapes/dtypes) — the same key jax's own dispatch cache uses,
      minus sharding (an input moving devices under an unchanged
      shape raises from the strict AOT executable and degrades that
      signature to the plain jit path instead of mis-executing);
    * trace and compile phases timed as ``jit_trace``/``jit_compile``
      telemetry spans AND recorded on the card;
    * a PROGRAM CARD per compile in ``telemetry.programs()``;
    * a structured once-per-cause RECOMPILE warning through log.py
      when a cache miss follows a prior compile, naming exactly which
      argument's shape/dtype (or the signature structure) changed;
    * dispatch-time RESOURCE_EXHAUSTED/OOM errors re-raised as
      ``DeviceMemoryError`` enriched with the buffer ledger and the
      program's memory card.
    """

    __slots__ = ("kind", "entry", "argnames", "_jitted", "_donate",
                 "_cache", "_card", "_meta", "_graph_key",
                 "warn_recompile", "on_compile")

    def __init__(self, kind, fn, jit_kwargs=None, argnames=None,
                 meta=None, graph_key=None):
        self.kind = kind
        self.entry = "%s@p%d" % (kind, next(_PROG_SEQ))
        self.argnames = argnames or ()
        kw = dict(jit_kwargs or {})
        self._donate = tuple(kw.get("donate_argnums", ()) or ())
        self._jitted = jax.jit(fn, **kw)   # the ONE instrumented jit site
        self._cache = {}    # dispatch sig -> [callable, card, aot_bool]
        self._card = None   # last-compiled card: the recompile-diff base
        self._meta = dict(meta or {})
        # JSON-safe fingerprint of everything the traced graph depends
        # on besides the arguments (the owner's symbol hash + entry-
        # point statics): enables the persisted cache's TRACE-SKIP tier
        # (compile_cache.quick_key). None = content-key tier only. A
        # CALLABLE defers the (symbol-JSON hashing) work until the
        # first build WITH the cache enabled — programs in cache-less
        # processes must not pay for fingerprints nobody reads.
        self._graph_key = graph_key
        # deliberate multi-signature callers (the serving engine compiles
        # one program per batch bucket BY DESIGN) flip this off so their
        # planned compiles don't read as recompile storms in the log and
        # the recompile.* counters
        self.warn_recompile = True
        # optional owner hook fired with the fresh card after every
        # signature build (compile OR disk-cache load — the card's
        # "source" field says which): engines that account their own
        # planned compiles (decode counts prefill-bucket builds) attach
        # here instead of re-deriving it from the card registry
        self.on_compile = None

    # -- compile -----------------------------------------------------------
    def _signature_cards(self, args):
        """Full named signature for the card: [[path, shape, dtype,
        sharding], ...] — computed only at compile time."""
        from jax.tree_util import tree_flatten_with_path
        flat, _ = tree_flatten_with_path(args)
        sig = []
        for path, leaf in flat:
            try:
                shape = list(leaf.shape)
                dtype = str(leaf.dtype)
            except AttributeError:
                shape, dtype = [], type(leaf).__name__
            sh = getattr(leaf, "sharding", None)
            sig.append([_path_str(path, self.argnames), shape, dtype,
                        None if sh is None else str(sh)])
        return sig

    def _diff_signature(self, old, new):
        """(path, change-kind, detail) tuples describing why the new
        signature missed the cache against the prior card's."""
        old_map = {e[0]: e for e in (old or [])}
        new_map = {e[0]: e for e in (new or [])}
        causes = []
        for path, e in new_map.items():
            o = old_map.get(path)
            if o is None:
                causes.append((path, "added", "new argument %s %s"
                               % (tuple(e[1]), e[2])))
                continue
            if e[1] != o[1]:
                causes.append((path, "shape", "shape %s -> %s"
                               % (tuple(o[1]), tuple(e[1]))))
            if e[2] != o[2]:
                causes.append((path, "dtype", "dtype %s -> %s"
                               % (o[2], e[2])))
            if e[3] != o[3]:
                causes.append((path, "sharding", "sharding %s -> %s"
                               % (o[3], e[3])))
        for path in old_map:
            if path not in new_map:
                causes.append((path, "removed", "argument gone"))
        return causes

    def _warn_recompile(self, card):
        """The recompile-cause diagnosis: diff against the prior card
        and report each changed field ONCE per (entry, field, kind)
        through log.py — the recompile-storm detector's counters can
        finally say WHY."""
        telemetry.counter_inc("recompile.%s" % self.kind)
        causes = self._diff_signature(self._card.get("signature"),
                                      card.get("signature"))
        if not causes:
            causes = [("<unknown>", "unknown",
                       "signature changed outside the argument list")]
        card["recompile_causes"] = ["%s: %s" % (p, d)
                                    for p, _, d in causes]
        fresh = [(p, k, d) for p, k, d in causes
                 if (self.entry, p, k) not in _RECOMPILE_WARNED]
        if not fresh:
            return
        for p, k, _ in fresh:
            _RECOMPILE_WARNED.add((self.entry, p, k))
        from . import log as _log
        _log.get_logger("mxnet_tpu.executor").warning(
            "recompile entry=%s kind=%s cause=%s — the cached program "
            "cannot serve the new signature; if this repeats every "
            "batch, pad or bucket the offending input "
            "(see telemetry.programs()[%r])",
            self.entry, self.kind,
            "; ".join("%s: %s" % (p, d) for p, _, d in fresh),
            card["id"])

    def _build(self, sig, args):
        """Cache miss: explicit lower().compile(), card capture,
        recompile diagnosis. AOT failures (backend quirks) degrade to
        the plain jitted callable with a card whose analysis fields
        stay None — dispatch must never break on introspection.

        With the persisted tier on (``MXNET_COMPILE_CACHE``), the
        program is looked up in the on-disk executable store
        (mxnet_tpu/compile_cache.py) and a hit DESERIALIZES instead of
        invoking XLA (``jit_deserialize`` span, zero ``jit_compile``
        spans — the warm-start contract): first via the trace-skip
        quick key (graph fingerprint; no ``lower()`` at all), then via
        the content key over the lowered StableHLO. A miss compiles
        and persists the fresh executable (plus the quick-key index
        entry) for the next process. Cache load/store failures degrade
        inside compile_cache — only lower()/compile() errors reach the
        AOT-fallback path here."""
        from . import compile_cache
        card_sig = self._signature_cards(args)
        entry_id = "%s/s%d" % (self.entry, len(self._cache))
        aot = True
        compiled = None
        source = "compiled"
        cc_on = compile_cache.enabled() \
            and compile_cache.persistable(self._donate)
        qkey = None
        if cc_on:
            if callable(self._graph_key):
                self._graph_key = self._graph_key()
            qkey = compile_cache.quick_key(
                self.kind, self._graph_key, signature=card_sig,
                donated=self._donate)
        trace_ms = compile_ms = deser_ms = 0.0
        t0 = time.perf_counter()
        try:
            if qkey is not None:
                ikey = compile_cache.index_get(qkey)
                if ikey is not None:
                    compiled = compile_cache.load(ikey, kind=self.kind)
                    if compiled is not None:
                        source = "disk_cache"   # no trace ran at all
                        deser_ms = (time.perf_counter() - t0) * 1e3
            if compiled is None:
                with telemetry.span("jit_trace"):
                    lowered = self._jitted.lower(*args)
                trace_ms = (time.perf_counter() - t0) * 1e3
                ckey = None
                if cc_on:
                    ckey = compile_cache.lowered_key(
                        self.kind, lowered, signature=card_sig,
                        donated=self._donate)
                    if ckey is not None:
                        t1 = time.perf_counter()
                        compiled = compile_cache.load(ckey, kind=self.kind)
                        if compiled is not None:
                            source = "disk_cache"
                            deser_ms = (time.perf_counter() - t1) * 1e3
                            compile_cache.index_put(qkey, ckey)
                if compiled is None:
                    t1 = time.perf_counter()
                    with telemetry.span("jit_compile"):
                        compiled = lowered.compile()
                    compile_ms = (time.perf_counter() - t1) * 1e3
                    if ckey is not None:
                        compile_cache.store(ckey, compiled,
                                            kind=self.kind,
                                            entry=entry_id,
                                            signature=card_sig)
                        compile_cache.index_put(qkey, ckey)
        except Exception as e:
            aot = False
            aot_err = "%s: %s" % (type(e).__name__, e)
        if aot:
            card = card_from_compiled(
                self.kind, compiled, entry=entry_id, signature=card_sig,
                donated=self._donate, extra=self._meta)
        else:
            card = card_from_compiled(
                self.kind, _NoAnalysis(), entry=entry_id,
                signature=card_sig, donated=self._donate,
                extra=dict(self._meta, aot_fallback=aot_err))
        card["trace_ms"] = round(trace_ms, 3)
        card["compile_ms"] = round(compile_ms, 3)
        card["source"] = source
        if source == "disk_cache":
            # the XLA compile never ran (compile_ms stays 0): the
            # disk-load cost is its own figure
            card["deserialize_ms"] = round(deser_ms, 3)
        if self._card is not None and self.warn_recompile:
            self._warn_recompile(card)
        self._card = card
        telemetry.record_program(card)
        if self.on_compile is not None:
            try:
                self.on_compile(card)
            except Exception:
                pass      # an accounting hook must never break a build
        rec = [compiled if aot else self._jitted, card, aot]
        self._cache[sig] = rec
        return rec

    def lower(self, *args):
        """AOT passthrough (jax.stages signature): callers that lower
        for HLO inspection (tests, tuners) see the same program the
        wrapper would compile."""
        return self._jitted.lower(*args)

    def build(self, *args):
        """Ensure this signature's executable exists (disk-cache load
        or fresh compile + card) WITHOUT dispatching it — the warmup
        path: an engine pre-building its bucket programs should not pay
        one execution per bucket just to force the compiles."""
        leaves, treedef = jax.tree_util.tree_flatten(args)
        sig = (treedef, tuple(_leaf_key(l) for l in leaves))
        if sig not in self._cache:
            self._build(sig, args)

    # -- dispatch ----------------------------------------------------------
    def _invoke(self, fn, args):
        """The one launch site (tests monkeypatch this to fake device
        errors)."""
        return fn(*args)

    def __call__(self, *args):
        leaves, treedef = jax.tree_util.tree_flatten(args)
        sig = (treedef, tuple(_leaf_key(l) for l in leaves))
        rec = self._cache.get(sig)
        if rec is None:
            rec = self._build(sig, args)
        telemetry.program_dispatch(rec[1])
        # chaos site: an injected raise here looks exactly like a
        # backend dispatch failure to every caller (the serving retry
        # budget, the breaker, the fit loop) — which is the point
        faults.fire("dispatch")
        try:
            return self._invoke(rec[0], args)
        except Exception as e:
            if _is_oom(e):
                err = _enriched_oom(e, rec[1])
                # flight-recorder moment: the ledger/card evidence in
                # the enriched error evaporates with the process — dump
                # the window too (no-op without a flight dir)
                from . import flight
                flight.postmortem("device_memory_error", exc=err,
                                  extra={"program": rec[1].get("id"),
                                         "kind": self.kind})
                raise err from e
            if rec[2] and isinstance(e, (TypeError, ValueError)):
                # strict AOT input check (an input moved devices under
                # an unchanged shape/dtype): degrade this signature to
                # the plain jit path, which re-commits inputs itself.
                # The card is registered and shared — mutate it under
                # the registry lock
                rec[0], rec[2] = self._jitted, False
                telemetry.card_update(rec[1],
                                      aot_fallback="input mismatch: %s" % e)
                return self._invoke(rec[0], args)
            raise


class _NoAnalysis:
    """Stand-in 'compiled' whose analyses always fail — the degraded
    card keeps every cost/memory field at None."""

    def cost_analysis(self):
        raise NotImplementedError

    memory_analysis = cost_analysis


# ---------------------------------------------------------------------------
# Divergence sentinel kernel
# ---------------------------------------------------------------------------

_FINITE_PROG = None


def finite_fold_fn():
    """The divergence sentinel's device kernel: one jitted program
    folding ``isfinite(x).all()`` over a list of arrays (loss heads,
    gradients, parameters) into a single scalar bool — the whole check
    ships ONE dispatch and fetches ONE byte, instead of pulling every
    buffer to the host. Compiled through the instrumented wrapper like
    every other program (card, OOM enrichment); one cached program per
    leaf-signature, shared process-wide."""
    global _FINITE_PROG
    if _FINITE_PROG is None:
        def _fold(leaves):
            acc = jnp.asarray(True)
            for x in leaves:
                if jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact):
                    acc = jnp.logical_and(acc, jnp.isfinite(x).all())
            return acc
        _FINITE_PROG = _InstrumentedProgram("finite_check", _fold)
    return _FINITE_PROG


# differentiable cross-device copy with static endpoints: the plain
# device_put transpose leaves cotangents on the DESTINATION device, so
# the backward of a grouped graph would mix devices mid-computation
_XFER_CACHE = {}


def _context_for_device(dev):
    """Map a concrete jax.Device back to a Context. Index by position in
    the local device list (not ``dev.id``, a GLOBAL id that need not be
    aligned with local indices in multi-process runs) so the round trip
    through ``Context.jax_device()`` lands on the same device."""
    from .context import Context, _accelerator_devices
    if dev.platform == "cpu":
        local = jax.local_devices(backend="cpu")
        return Context("cpu", local.index(dev))
    return Context("tpu", _accelerator_devices().index(dev))


def _device_transfer(v, src, dst):
    key = (src, dst)
    fn = _XFER_CACHE.get(key)
    if fn is None:
        @jax.custom_vjp
        def t(x):
            return jax.device_put(x, dst)

        def t_fwd(x):
            return jax.device_put(x, dst), None

        def t_bwd(_, g):
            return (jax.device_put(g, src),)

        t.defvjp(t_fwd, t_bwd)
        fn = _XFER_CACHE[key] = t   # mxlint: disable=trace-purity -- idempotent memoization of a per-(src,dst) transfer callable; the value is trace-independent
    return fn(v)


# ---------------------------------------------------------------------------
# Graph program: symbol -> pure jax function
# ---------------------------------------------------------------------------

class _GraphProgram:
    """Caches the traced/jitted callables for one Symbol.

    With ``group2dev`` (the reference's group2ctx model parallelism,
    AssignContext + cross-device copy nodes, graph_executor.cc:318-440):
    each op node resolves a device from its ``ctx_group`` attribute and
    inputs crossing a group boundary are ``jax.device_put`` to the
    consumer's device — the cross-device copy. Grouped programs run
    eagerly per segment (arbitrary per-op device pinning is not a GSPMD
    program; data-parallel scaling uses the mesh path instead)."""

    def __init__(self, symbol, group2dev=None, default_device=None):
        self.symbol = symbol
        self.nodes = symbol._topo_nodes()
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        self.output_entries = list(symbol._outputs)
        self._jit_cache = {}
        self.node_devices = None
        self.default_device = default_device
        if group2dev:
            self.node_devices = {}
            for node in self.nodes:
                g = (node._extra_attrs.get("ctx_group")
                     or node._extra_attrs.get("__ctx_group__"))
                if g is not None and g in group2dev:
                    self.node_devices[id(node)] = group2dev[g]
            # variables without their own ctx_group live on their first
            # consumer's device (reference AssignContext pulls inputs to
            # the consuming op's group, graph_executor.cc:318-440)
            for node in self.nodes:
                if node.op is None:
                    continue
                ndev = self.node_devices.get(id(node))
                if ndev is None:
                    continue
                for child, _ in node.inputs:
                    if child.op is None and \
                            id(child) not in self.node_devices:
                        self.node_devices[id(child)] = ndev

    def graph_fingerprint(self):
        """JSON-safe fingerprint of this program's GRAPH content for
        the persisted compile cache's trace-skip tier: the symbol's
        JSON plus the ambient layout default (ops consult it at trace
        time). Everything else trace-time-relevant (op source code,
        MXNET_* knobs, backend identity, the abstract signature) is
        folded in by ``compile_cache.quick_key`` itself. None (tier
        disabled) for grouped programs and symbols that cannot
        serialize."""
        cached = self.__dict__.get("_graph_fp", False)
        if cached is not False:
            return cached
        fp = None
        if not self.node_devices:
            try:
                import hashlib
                from . import layout
                js = self.symbol.tojson()
                fp = [hashlib.sha256(js.encode()).hexdigest(),
                      layout.get_default_layout()]
            except Exception:
                fp = None
        self.__dict__["_graph_fp"] = fp
        return fp

    def _entry_graph_key(self, *statics):
        """Graph key for one jitted entry point: the graph fingerprint
        plus the entry's own statics (train flag, grad names, ...),
        deep-normalised to JSON-safe values. Non-primitive statics fall
        back to repr — a repr that varies per process (object
        addresses) degrades to a quick-tier miss, never a false hit
        (the content key still matches after the trace)."""
        fp = self.graph_fingerprint()
        if fp is None:
            return None

        def norm(s):
            if isinstance(s, (str, int, float, bool, type(None))):
                return s
            if isinstance(s, (tuple, list)):
                return [norm(x) for x in s]
            return repr(s)
        return [fp] + [norm(s) for s in statics]

    @property
    def uses_rng(self):
        """True iff any node consumes randomness. RNG-free graphs (most
        inference/training graphs without dropout) skip the per-step
        eager ``jax.random.split`` — one device dispatch per step on a
        remoted PJRT backend."""
        cached = self.__dict__.get("_uses_rng")
        if cached is None:
            cached = any(n.op is not None and n.op.takes_rng
                         for n in self.nodes)
            self.__dict__["_uses_rng"] = cached
        return cached

    # ---- pure evaluation -------------------------------------------------
    def _apply_node(self, node, raw_in, train, aux_dict, aux_updates):
        """Apply one op node; records aux updates into ``aux_updates``."""
        if self.node_devices:
            dev = self.node_devices.get(id(node), self.default_device)
            fixed = []
            for r, (c, _) in zip(raw_in, node.inputs):
                src = self.node_devices.get(id(c), self.default_device)
                if src is not dev:
                    # cross-device copy at the group boundary
                    # (reference cross_device_copy.cc node)
                    r = _device_transfer(r, src, dev)
                fixed.append(r)
            raw_in = fixed
        params = dict(node.op.defaults)
        params.update(node.attrs)
        params.pop("num_args", None)
        params.pop("name", None)
        if node.op.takes_train:
            params["_train"] = train
        if node.op.takes_rng:
            from .ops.common import take_rng
            params["_rng"] = take_rng()
        outs = node.op.apply(raw_in, params)
        if train and node.op.stateful_update is not None:
            ups = node.op.stateful_update(raw_in, outs, params)
            for in_idx, val in ups.items():
                child, _ = node.inputs[in_idx]
                if child.op is None and child.name in aux_dict:
                    aux_updates[child.name] = val
        return outs

    def _bind_variable(self, node, arg_dict, aux_dict):
        if node.name in arg_dict:
            return arg_dict[node.name]
        if node.name in aux_dict:
            return aux_dict[node.name]
        raise MXNetError("unbound variable %r" % node.name)

    def eval_graph(self, arg_dict, aux_dict, rng_key, train):
        """Evaluate the graph. Returns (outputs, aux_updates)."""
        env = {}
        aux_updates = {}
        with rng_scope(rng_key):
            for node in self.nodes:
                if node.op is None:
                    env[id(node)] = (self._bind_variable(
                        node, arg_dict, aux_dict),)
                    continue
                raw_in = [env[id(c)][idx] for c, idx in node.inputs]
                env[id(node)] = self._apply_node(node, raw_in, train,
                                                 aux_dict, aux_updates)
        outputs = [env[id(n)][idx] for n, idx in self.output_entries]
        return outputs, aux_updates

    def can_segment(self):
        """Whether mirrored evaluation can split this graph into
        checkpoint segments: needs a jitted single-device program with
        enough op nodes to be worth cutting. The ONE owner of the
        decision — fwd_bwd_fn's whole-graph-checkpoint fallback and
        eval_graph_mirrored's internal guard both call this."""
        return not self.node_devices and \
            sum(1 for n in self.nodes if n.op is not None) >= 4

    def eval_graph_mirrored(self, arg_dict, aux_dict, rng_key, train):
        """MXNET_BACKWARD_DO_MIRROR evaluation: the op graph is split
        into ~sqrt(N) contiguous segments and each runs under
        ``jax.checkpoint``, so the backward pass keeps only segment
        BOUNDARY values resident and recomputes interior activations —
        the reference's per-node mirror policy
        (graph_executor.cc:282-305) recast as TPU-first checkpointing.
        (One checkpoint around the whole graph would save nothing: the
        recomputed forward and the backward would hold every activation
        live at once.)"""
        import math

        if not self.can_segment():
            # callers (fwd_bwd_fn) handle these cases with one
            # whole-graph checkpoint instead; segmentation needs a
            # jitted single-device program
            return self.eval_graph(arg_dict, aux_dict, rng_key, train)
        ops = [n for n in self.nodes if n.op is not None]
        k = max(2, int(round(math.sqrt(len(ops)))))
        step = (len(ops) + k - 1) // k
        chunks = [ops[i:i + step] for i in range(0, len(ops), step)]

        # val_env: (id(node), out_index) -> traced value
        val_env = {}
        aux_updates = {}
        for node in self.nodes:
            if node.op is None:
                val_env[(id(node), 0)] = self._bind_variable(
                    node, arg_dict, aux_dict)

        with rng_scope(rng_key):
            for ci, chunk in enumerate(chunks):
                chunk_ids = {id(n) for n in chunk}
                # external inputs: produced before this chunk
                ext, seen = [], set()
                for n in chunk:
                    for c, idx in n.inputs:
                        key = (id(c), idx)
                        if id(c) not in chunk_ids and key not in seen:
                            seen.add(key)
                            ext.append(key)
                # values later chunks / graph outputs need from here
                needed, nseen = [], set()
                for later in chunks[ci + 1:]:
                    for n in later:
                        for c, idx in n.inputs:
                            key = (id(c), idx)
                            if id(c) in chunk_ids and key not in nseen:
                                nseen.add(key)
                                needed.append(key)
                for n, idx in self.output_entries:
                    key = (id(n), idx)
                    if id(n) in chunk_ids and key not in nseen:
                        nseen.add(key)
                        needed.append(key)

                def chunk_fn(ext_vals, _chunk=chunk,
                             _chunk_ids=chunk_ids, _ext=ext,
                             _needed=needed):
                    local = dict(zip(_ext, ext_vals))
                    ups = {}
                    for n in _chunk:
                        raw_in = []
                        for c, idx in n.inputs:
                            raw_in.append(local[(id(c), idx)])
                        outs = self._apply_node(n, raw_in, train,
                                                aux_dict, ups)
                        for i, v in enumerate(outs):
                            local[(id(n), i)] = v
                    return [local[key] for key in _needed], ups

                out_vals, ups = jax.checkpoint(chunk_fn)(
                    [val_env[key] for key in ext])
                aux_updates.update(ups)
                for key, v in zip(needed, out_vals):
                    val_env[key] = v
        outputs = [val_env[(id(n), idx)] for n, idx in self.output_entries]
        return outputs, aux_updates

    # ---- jitted entry points --------------------------------------------
    def forward_fn(self, train):
        key = ("fwd", bool(train))
        hit = key in self._jit_cache
        telemetry.record_jit("forward", hit)
        if not hit:
            def fn(args, aux, rng):
                return self.eval_graph(args, aux, rng, train)
            # grouped programs pin ops to concrete devices — eager
            # execution (per-op dispatch), not one jitted program
            self._jit_cache[key] = fn if self.node_devices else \
                _InstrumentedProgram(
                    "forward", fn,
                    argnames=("args", "aux", "rng"),
                    meta={"train": bool(train)},
                    graph_key=lambda: self._entry_graph_key(
                        "fwd", bool(train)))
        return self._jit_cache[key]

    def _vjp_over_graph(self, grad_args, rest, aux, rng, train):
        """``jax.vjp`` over the whole graph under the mirror policy —
        the ONE forward/backward scaffold both the phase-split
        ``fwd_bwd_fn`` and the whole-step ``train_step_fn`` trace, so
        the checkpointing choice and gradient partitioning stay
        identical by construction."""
        from .config import do_mirror
        mirror = do_mirror()
        segmented = mirror and self.can_segment()

        def f(ga):
            ev = self.eval_graph_mirrored if segmented \
                else self.eval_graph
            outs, aux_up = ev({**rest, **ga}, aux, rng, train)
            return tuple(outs), aux_up
        if mirror and not segmented:
            # grouped (eager per-device) or tiny graphs can't be
            # segment-checkpointed; one checkpoint around the whole
            # graph still frees activation buffers between forward and
            # backward
            f = jax.checkpoint(f)
        return jax.vjp(f, grad_args, has_aux=True)

    def fwd_bwd_fn(self, train, grad_names):
        key = ("fwdbwd", bool(train), tuple(grad_names))
        hit = key in self._jit_cache
        telemetry.record_jit("fwd_bwd", hit)
        if not hit:
            def fn(args, aux, rng, head_grads):
                grad_args = {k: args[k] for k in grad_names}
                rest = {k: v for k, v in args.items() if k not in grad_names}
                outs, vjp, aux_up = self._vjp_over_graph(
                    grad_args, rest, aux, rng, train)
                hg = tuple(
                    head_grads[i] if head_grads[i] is not None
                    else jnp.ones(outs[i].shape, outs[i].dtype)
                    for i in range(len(outs)))
                if self.node_devices:
                    # head gradients must enter the backward committed to
                    # their output node's device
                    hg = tuple(
                        jax.device_put(g, self.node_devices.get(
                            id(n), self.default_device))
                        for g, (n, _) in zip(hg, self.output_entries))
                grads = vjp(hg)[0]
                return outs, grads, aux_up
            self._jit_cache[key] = fn if self.node_devices else \
                _InstrumentedProgram(
                    "fwd_bwd", fn,
                    argnames=("args", "aux", "rng", "head_grads"),
                    meta={"train": bool(train)},
                    graph_key=lambda: self._entry_graph_key(
                        "fwdbwd", bool(train), tuple(grad_names)))
        return self._jit_cache[key]

    def train_step_fn(self, update_names, add_names, input_dtypes, cache_key,
                      build_update_fn, build_metric_fn, spmd=None,
                      build_shardings=None):
        """Whole-training-step program: forward + backward + optimizer
        update (+ metric accumulation when ``build_metric_fn`` is given)
        traced into ONE jitted XLA function, with the parameter,
        optimizer-state, metric-accumulator, and aux buffers DONATED —
        the step updates weights in place instead of round-tripping every
        parameter buffer (the end-to-end program compilation the TVM /
        Julia-to-TPU line of work keeps proving out; closes the
        Module.fit dispatch gap, PERF.md "Module.fit gap").

        ``update_names`` orders the trained parameters (matching the
        per-parameter ``lrs``/``wds``/``ts`` arrays and the packed state
        list); ``add_names`` marks ``grad_req='add'`` parameters whose
        incoming gradient accumulator rides as a non-donated input.
        ``build_update_fn``/``build_metric_fn`` are invoked only on a
        cache miss; ``cache_key`` must capture everything their closures
        depend on (optimizer statics, state layout, metric identity).
        Grouped (group2ctx) programs cannot ride — callers fall back to
        the phase-split path.

        ``spmd`` (a ``parallel.spmd.DataParallelSpec``) selects the SPMD
        variant: the SAME step is jitted with explicit NamedShardings —
        batch inputs split over the data axis, params/optimizer state/
        metric accumulator/aux replicated (still donated) — so XLA GSPMD
        compiles ONE program over the whole mesh with the cross-replica
        gradient psum, the optimizer update and the metric reduction
        fused INSIDE the step (no software kvstore staging, no host-side
        batch splitting: the global batch arrives via one sharded
        device_put). The replicated metric accumulator comes back already
        psummed across replicas, so fetching it needs no extra program.

        ``build_shardings`` (rule-sharded dp x mp meshes — a spec whose
        ``rules`` is a ``PartitionRules`` tree) is invoked on a cache
        miss like ``build_update_fn`` and returns the PER-LEAF
        NamedSharding pytrees ``{"params": {name: sh}, "states":
        [tuple(sh, ...)], "aux": {name: sh}, "add_grads": {name: sh}}``
        threaded into ``in_shardings`` — mp-sharded parameters and
        their optimizer state stay sharded INSIDE the donated step
        (never all-gathered), while GSPMD still reduces gradients over
        ``dp`` only because each gradient carries its parameter's mp
        placement. The batch inputs/step scalars keep the dp/replicated
        layout above.
        """
        if self.node_devices:
            raise MXNetError("train_step_fn: grouped programs run eagerly "
                             "per segment and cannot fuse the train step")
        key = ("train_step", tuple(update_names), tuple(sorted(add_names)),
               tuple(sorted(input_dtypes.items(), key=lambda kv: kv[0])),
               cache_key, spmd)
        fn = self._jit_cache.get(key)
        telemetry.record_jit("train_step", fn is not None)
        if fn is not None:
            return fn
        update_fn = build_update_fn()
        metric_fn = build_metric_fn() if build_metric_fn is not None else None
        grad_set = frozenset(update_names)

        def step(params, opt_states, metric_acc, aux, inputs, rng,
                 lrs, wds, ts, add_grads):
            # inputs adopt the bound argument dtypes (a bf16 DataDesc
            # keeps binding a bf16 program even though the batch arrays
            # are fed functionally, without a copy into bound storage)
            ins = {k: (v.astype(input_dtypes[k])
                       if v.dtype != input_dtypes[k] else v)
                   for k, v in inputs.items()}
            grad_args = {k: params[k] for k in update_names}
            rest = {k: v for k, v in params.items() if k not in grad_set}
            rest.update(ins)
            outs, vjp, aux_up = self._vjp_over_graph(
                grad_args, rest, aux, rng, True)
            hg = tuple(jnp.ones(o.shape, o.dtype) for o in outs)
            grads = vjp(hg)[0]
            # gradients pass through the bound grad-array dtype (the
            # phase-split path stores them there before the optimizer
            # reads them — bit-parity demands the same rounding). Only
            # ``grad_req='add'`` accumulators are MATERIALIZED as program
            # outputs (they feed the next step); 'write' grads live and
            # die inside the program — emitting them would be pure
            # output-buffer overhead nothing consumes
            gs, grads_out = [], {}
            for k in update_names:
                g = grads[k].astype(params[k].dtype)
                if k in add_names:
                    g = add_grads[k] + g
                    grads_out[k] = g
                gs.append(g)
            ws = [params[k] for k in update_names]
            new_ws, new_states = update_fn(ws, opt_states, gs, lrs, wds, ts)
            new_params = dict(params)
            new_params.update(zip(update_names, new_ws))
            new_aux = dict(aux)
            new_aux.update({k: v for k, v in aux_up.items() if k in aux})
            new_acc = metric_fn(outs, ins, metric_acc) if metric_fn \
                else metric_acc
            return new_params, new_states, new_acc, new_aux, outs, grads_out

        step_argnames = ("params", "opt_states", "metric_acc", "aux",
                         "inputs", "rng", "lrs", "wds", "ts", "add_grads")
        # cache_key captures the optimizer/metric closure statics — its
        # repr rides in the graph key (a per-process repr degrades to a
        # quick-tier miss, never a false hit)
        def step_graph_key():
            if spmd is None:
                layout = None
            else:
                # mesh shape + rule-tree identity: two layouts over the
                # same graph must key distinct persisted programs (the
                # repr degrades to a quick-tier miss at worst, never a
                # false hit)
                layout = (spmd.num_devices,
                          repr(sorted(dict(spmd.mesh.shape).items())),
                          repr(getattr(spmd, "rules", None)))
            return self._entry_graph_key(
                "train_step", tuple(update_names),
                tuple(sorted(add_names)),
                tuple("%s=%s" % (k, v) for k, v in
                      sorted(input_dtypes.items())), cache_key, layout)
        if spmd is None:
            fn = _InstrumentedProgram(
                "train_step", step,
                jit_kwargs={"donate_argnums": (0, 1, 2, 3)},
                argnames=step_argnames,
                graph_key=step_graph_key)
        else:
            repl, dsh = spmd.repl_sharding, spmd.data_sharding
            # args: (params, opt_states, metric_acc, aux, inputs, rng,
            #        lrs, wds, ts, add_grads) — each entry is a pytree
            # PREFIX broadcast over its subtree. The batch-sharded inputs
            # plus replicated (or rule-sharded, below) params force GSPMD
            # to insert the gradient all-reduce (psum over the dp axis)
            # inside the step; output shardings are propagated (params/
            # state/acc come out on their input placement, per-example
            # outputs batch-sharded), which keeps donation
            # buffer-compatible.
            param_sh = state_sh = aux_sh = ag_sh = repl
            meta = {"spmd_devices": spmd.num_devices}
            if getattr(spmd, "rules", None) is not None \
                    and build_shardings is not None:
                shs = build_shardings()
                param_sh, state_sh = shs["params"], shs["states"]
                aux_sh, ag_sh = shs["aux"], shs["add_grads"]
                base_step = step

                def step(params, opt_states, metric_acc, aux, inputs,
                         rng, lrs, wds, ts, add_grads):
                    # pin the DONATED outputs to their declared input
                    # placements: GSPMD would otherwise propagate
                    # whatever layout the body implies (e.g. BatchNorm
                    # moving stats derived from mp-sharded activations
                    # drift to an mp sharding), and the NEXT call's
                    # explicit in_shardings would reject the donated
                    # buffer it just produced
                    wsc = jax.lax.with_sharding_constraint
                    new_params, new_states, new_acc, new_aux, outs, \
                        grads_out = base_step(
                            params, opt_states, metric_acc, aux,
                            inputs, rng, lrs, wds, ts, add_grads)
                    new_params = wsc(new_params, param_sh)
                    new_states = [wsc(s, sh) for s, sh in
                                  zip(new_states, state_sh)]
                    if new_acc is not None:     # metric-less step
                        new_acc = wsc(new_acc, repl)
                    new_aux = wsc(new_aux, aux_sh)
                    grads_out = {k: wsc(v, ag_sh[k])
                                 for k, v in grads_out.items()}
                    return (new_params, new_states, new_acc, new_aux,
                            outs, grads_out)
                n_sharded = sum(1 for s in param_sh.values()
                                if tuple(s.spec))
                meta["partition"] = {
                    "mesh_axes": {str(k): int(v)
                                  for k, v in spmd.mesh.shape.items()},
                    "data_axis": spmd.data_axis,
                    "sharded_params": n_sharded,
                    "replicated_params": len(param_sh) - n_sharded,
                    "rules": spmd.rules.describe(),
                }
            fn = _InstrumentedProgram(
                "train_step", step,
                jit_kwargs={"in_shardings": (param_sh, state_sh, repl,
                                             aux_sh, dsh, repl, repl,
                                             repl, repl, ag_sh),
                            "donate_argnums": (0, 1, 2, 3)},
                argnames=step_argnames,
                meta=meta,
                graph_key=step_graph_key)
        self._jit_cache[key] = fn
        return fn


# ---------------------------------------------------------------------------
# Shape inference over the graph
# ---------------------------------------------------------------------------

def infer_graph_attrs(symbol, known_shapes, known_types=None, partial=False,
                      default_dtype=np.float32):
    """Joint shape+dtype inference (parity: the reference's InferShape AND
    InferType passes, src/executor/infer_graph_attr_pass.cc — one walk
    here because jax.eval_shape propagates both attributes at once).

    Variable dtypes resolve in priority order: ``known_types`` (the
    simple_bind ``type_dict``) > a ``__dtype__`` attr on the Variable >
    dtype filled by the consuming op (learnable inputs follow the op's
    first float input — the reference's per-op InferType rule — unless
    the op has a ``param_dtype_infer`` hook, e.g. BatchNorm pins its
    scale/shift/moving stats to fp32) > ``default_dtype``.
    """
    nodes = symbol._topo_nodes()
    var_shape = dict(known_shapes)
    var_type = {k: np.dtype(v) for k, v in (known_types or {}).items()}
    shapes = {}  # id(node) -> tuple of output shapes
    types = {}   # id(node) -> tuple of output dtypes

    for node in nodes:
        if node.op is None:
            shp = var_shape.get(node.name)
            if shp is None and "__shape__" in node._extra_attrs:
                import ast
                shp = tuple(ast.literal_eval(node._extra_attrs["__shape__"]))
                var_shape[node.name] = shp
            dt = var_type.get(node.name)
            if dt is None and "__dtype__" in node._extra_attrs:
                dt = np.dtype(node._extra_attrs["__dtype__"])
                var_type[node.name] = dt
            shapes[id(node)] = (shp,)
            types[id(node)] = (dt,)
            continue
        in_shapes = [shapes[id(c)][idx] for c, idx in node.inputs]
        in_types = [types[id(c)][idx] for c, idx in node.inputs]
        params = dict(node.op.defaults)
        params.update(node.attrs)
        params.pop("num_args", None)
        # fill unknown learnable-input shapes
        if node.op.param_shape_infer is not None and in_shapes[0] is not None:
            fills = node.op.param_shape_infer(in_shapes, params)
            for i, shp in fills.items():
                if i < len(node.inputs) and in_shapes[i] is None:
                    child, _ = node.inputs[i]
                    if child.op is None:
                        var_shape[child.name] = tuple(shp)
                        shapes[id(child)] = (tuple(shp),)
                        in_shapes[i] = tuple(shp)
        # fill unknown input dtypes: per-op hook first, then the op's
        # first known float input, then the session default
        dtype_fills = {}
        if node.op.param_dtype_infer is not None:
            dtype_fills = node.op.param_dtype_infer(in_types, params)
        # jnp.issubdtype, not np: bfloat16 is an ml_dtypes extension type
        # that numpy does not classify under np.floating
        anchor = next((t for t in in_types
                       if t is not None and jnp.issubdtype(t, jnp.floating)),
                      np.dtype(default_dtype))
        for i in range(len(in_types)):
            if in_types[i] is None:
                dt = np.dtype(dtype_fills.get(i, anchor))
                child, idx = node.inputs[i]
                if child.op is None:
                    var_type[child.name] = dt
                    types[id(child)] = (dt,)
                in_types[i] = dt
        if any(s is None for s in in_shapes):
            if partial:
                shapes[id(node)] = tuple([None] * node.num_outputs())
                # dtype-only propagation still works without shapes (the
                # reference InferType pass is shape-independent): outputs
                # follow the promoted float input dtype; Cast follows its
                # param.
                if node.op.name == "Cast":
                    dt = np.dtype(params.get("dtype", "float32"))
                elif node.op.param_dtype_infer is not None:
                    # ops that pin param dtypes (BatchNorm's fp32 stats)
                    # still emit the DATA dtype — don't promote across the
                    # pinned fp32 params
                    dt = anchor
                else:
                    floats = [t for t in in_types
                              if t is not None
                              and jnp.issubdtype(t, jnp.floating)]
                    dt = np.dtype(jnp.result_type(*floats)) if floats else None
                types[id(node)] = tuple([dt] * node.num_outputs())
                continue
            missing = [node.inputs[i][0].name for i, s in enumerate(in_shapes)
                       if s is None]
            raise MXNetError("infer_shape: cannot infer %r (missing inputs %s)"
                             % (node.name, missing))
        # eval_shape through the op function: XLA's abstract evaluation is
        # both FInferShape and FInferType
        if node.op.takes_train:
            params["_train"] = False
        if node.op.takes_rng:
            params["_rng"] = jax.random.key(0)
        structs = [jax.ShapeDtypeStruct(s, t)
                   for s, t in zip(in_shapes, in_types)]
        try:
            out = jax.eval_shape(lambda *a: node.op.fn(*a, **params), *structs)
        except Exception as e:
            if partial:
                shapes[id(node)] = tuple([None] * node.num_outputs())
                types[id(node)] = tuple([None] * node.num_outputs())
                continue
            raise MXNetError("infer_shape failed at %s(%s): %s"
                             % (node.op.name, node.name, e))
        outs = out if isinstance(out, tuple) else (out,)
        shapes[id(node)] = tuple(tuple(o.shape) for o in outs)
        types[id(node)] = tuple(np.dtype(o.dtype) for o in outs)

    arg_shapes = [var_shape.get(n) for n in symbol.list_arguments()]
    aux_shapes = [var_shape.get(n) for n in symbol.list_auxiliary_states()]
    arg_types = [var_type.get(n) for n in symbol.list_arguments()]
    aux_types = [var_type.get(n) for n in symbol.list_auxiliary_states()]
    out_shapes, out_types = [], []
    for n, idx in symbol._outputs:
        s = shapes.get(id(n))
        t = types.get(id(n))
        out_shapes.append(None if s is None or idx >= len(s) else s[idx])
        out_types.append(None if t is None or idx >= len(t) else t[idx])
    return (arg_shapes, out_shapes, aux_shapes,
            arg_types, out_types, aux_types)


def infer_graph_shapes(symbol, known_shapes, partial=False,
                       default_dtype=np.float32):
    """Shape-only view of infer_graph_attrs (kept for existing callers)."""
    res = infer_graph_attrs(symbol, known_shapes, partial=partial,
                            default_dtype=default_dtype)
    return res[0], res[1], res[2]


# ---------------------------------------------------------------------------
# Executor
# ---------------------------------------------------------------------------

class Executor:
    """Bound, compiled graph (parity: python/mxnet/executor.py)."""

    def __init__(self, symbol, ctx, arg_arrays, grad_arrays, grad_req,
                 aux_arrays, program=None, group2ctx=None,
                 owns_arrays=False, out_shapes=None):
        from .ndarray.ndarray import NDArray
        self._symbol = symbol
        self._ctx = ctx or current_context()
        group2dev = {g: c.jax_device() for g, c in group2ctx.items()} \
            if group2ctx else None
        default_dev = self._ctx.jax_device() if group2dev else None
        self._prog = program or _GraphProgram(
            symbol, group2dev=group2dev, default_device=default_dev)
        if self._prog.node_devices:
            # commit parameter/aux storage to its group device so weights
            # are NOT re-copied across the boundary every step; retag the
            # NDArray's context too, so subsequent writes (x[:] = ...,
            # copyto) keep the placement instead of pulling the storage
            # back to the bind context. Only arrays this executor
            # allocated (simple_bind) may be moved; caller-owned arrays
            # on the wrong device raise instead of being mutated behind
            # the caller's back (reference AssignContext CHECKs
            # placement, graph_executor.cc:318-440). owns_arrays may
            # also be a collection naming the movable subset (e.g. the
            # aux arrays _bind auto-allocates).
            if owns_arrays is True:
                movable = None          # everything movable
            else:
                movable = frozenset(owns_arrays or ())
            by_name = {n.name: self._prog.node_devices[id(n)]
                       for n in self._prog.nodes
                       if n.op is None and id(n) in self._prog.node_devices}
            for name, arr in list(zip(self._prog.arg_names, arg_arrays)) + \
                    list(zip(self._prog.aux_names, aux_arrays)) + \
                    list(zip(self._prog.arg_names, grad_arrays)):
                dev = by_name.get(name)
                if dev is None or arr is None:
                    continue
                if list(arr._data.devices())[0] == dev:
                    continue
                if movable is not None and name not in movable:
                    raise MXNetError(
                        "bind: array %r lives on %s but its ctx_group "
                        "maps to %s; allocate it on the group's context"
                        % (name, arr.context, _context_for_device(dev)))
                arr._set_data(jax.device_put(arr._data, dev))
                arr._ctx = _context_for_device(dev)
        self.arg_arrays = list(arg_arrays)
        self.grad_arrays = list(grad_arrays)
        self.aux_arrays = list(aux_arrays)
        self._arg_names = self._prog.arg_names
        self._aux_names = self._prog.aux_names
        if isinstance(grad_req, str):
            grad_req = {n: grad_req for n in self._arg_names}
        elif isinstance(grad_req, (list, tuple)):
            grad_req = dict(zip(self._arg_names, grad_req))
        self._grad_req = {n: grad_req.get(n, "null") for n in self._arg_names}
        self.outputs = []
        self._monitor_callback = None
        self._monitor_all = False
        # reference parity: outputs are allocated (zero) NDArrays from
        # bind time, readable before the first forward. out_shapes may be
        # threaded in by the bind paths that already ran shape inference.
        try:
            if out_shapes is None:
                shapes = {n: a.shape for n, a in
                          zip(self._arg_names, self.arg_arrays)
                          if a is not None}
                _, out_shapes, _ = self._symbol.infer_shape_partial(**shapes)
            from .ndarray import zeros as _zeros
            self.outputs = [_zeros(s, ctx=self._ctx) if s is not None
                            else None for s in out_shapes]
            if any(o is None for o in self.outputs):
                self.outputs = []    # unknown head shape: defer to forward
        except Exception:
            pass

    # -- dict views --------------------------------------------------------
    @property
    def arg_dict(self):
        return dict(zip(self._arg_names, self.arg_arrays))

    @property
    def grad_dict(self):
        return dict(zip(self._arg_names, self.grad_arrays))

    @property
    def aux_dict(self):
        return dict(zip(self._aux_names, self.aux_arrays))

    @property
    def output_dict(self):
        return dict(zip(self._symbol.list_outputs(), self.outputs))

    # -- binding helpers (called from Symbol) ------------------------------
    @staticmethod
    def _simple_bind(symbol, ctx, grad_req, type_dict, shape_kwargs,
                     group2ctx=None):
        from .ndarray import zeros
        (arg_shapes, out_shapes, aux_shapes, arg_types, _, aux_types) = \
            infer_graph_attrs(symbol, shape_kwargs, known_types=type_dict)
        arg_names = symbol.list_arguments()
        arg_arrays = [zeros(s, ctx=ctx, dtype=t if t is not None else "float32")
                      for s, t in zip(arg_shapes, arg_types)]
        if isinstance(grad_req, str):
            reqs = {n: grad_req for n in arg_names}
        elif isinstance(grad_req, (list, tuple)):
            reqs = dict(zip(arg_names, grad_req))
        else:
            reqs = {n: grad_req.get(n, "null") for n in arg_names}
        # gradients carry the dtype of their argument (reference InferType:
        # grad entries share the arg entry's dtype)
        grad_arrays = [zeros(s, ctx=ctx, dtype=t if t is not None else "float32")
                       if reqs.get(n, "null") != "null" else None
                       for n, s, t in zip(arg_names, arg_shapes, arg_types)]
        aux_arrays = [zeros(s, ctx=ctx, dtype=t if t is not None else "float32")
                      for s, t in zip(aux_shapes, aux_types)]
        return Executor(symbol, ctx, arg_arrays, grad_arrays, reqs,
                        aux_arrays, group2ctx=group2ctx, owns_arrays=True,
                        out_shapes=out_shapes)

    @staticmethod
    def _bind(symbol, ctx, args, args_grad, grad_req, aux_states,
              group2ctx=None, shared_exec=None):
        from .ndarray.ndarray import NDArray
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        # shared_exec (reference bind parity): the new executor reuses
        # the donor's _GraphProgram, so every signature already traced/
        # compiled for the donor (the _InstrumentedProgram per-shape AOT
        # cache) is a cache HIT for the new binding — this is what makes
        # Predictor.reshape and the serving engine's bucket cache free of
        # silent re-traces. Only valid when both executors run the same
        # graph; grouped (group2ctx) programs pin concrete devices and
        # cannot be shared across binds.
        program = None
        if shared_exec is not None and group2ctx is None \
                and shared_exec._symbol is symbol \
                and not shared_exec._prog.node_devices:
            program = shared_exec._prog

        def _as_list(spec, names, what):
            if spec is None:
                return [None] * len(names)
            if isinstance(spec, dict):
                return [spec.get(n) for n in names]
            if isinstance(spec, (list, tuple)):
                if len(spec) != len(names):
                    raise MXNetError("%s length mismatch: %d vs %d"
                                     % (what, len(spec), len(names)))
                return list(spec)
            raise MXNetError("%s must be list or dict" % what)

        arg_arrays = _as_list(args, arg_names, "args")
        if any(a is None for a in arg_arrays):
            missing = [n for n, a in zip(arg_names, arg_arrays) if a is None]
            raise MXNetError("bind: missing arguments %s" % missing)
        grad_arrays = _as_list(args_grad, arg_names, "args_grad")
        aux_arrays = _as_list(aux_states, aux_names, "aux_states")
        auto_aux = set()
        if any(a is None for a in aux_arrays):
            # allocate zeros for missing aux; these are executor-owned,
            # so grouped binds may move them to their group device
            from .ndarray import zeros as _z
            auto_aux = {n for n, a in zip(aux_names, aux_arrays)
                        if a is None}
            shapes = {n: a.shape for n, a in zip(arg_names, arg_arrays)}
            _, _, aux_shapes = symbol.infer_shape_partial(**shapes)
            aux_arrays = [a if a is not None else _z(s, ctx=ctx)
                          for a, s in zip(aux_arrays, aux_shapes)]
        return Executor(symbol, ctx, arg_arrays, grad_arrays, grad_req,
                        aux_arrays, program=program, group2ctx=group2ctx,
                        owns_arrays=auto_aux)

    # -- execution ---------------------------------------------------------
    def _raw_args(self):
        return {n: a._data for n, a in zip(self._arg_names, self.arg_arrays)}

    def _raw_aux(self):
        return {n: a._data for n, a in zip(self._aux_names, self.aux_arrays)}

    def _out_ctx(self, out_index):
        """Context for output i: in grouped mode, the output node's group
        device (so NDArray.context reports where the data actually
        lives); otherwise the bind context. Static per executor — cached
        so the per-step hot path skips the device-list lookups."""
        cache = self.__dict__.setdefault("_out_ctx_cache", {})
        ctx = cache.get(out_index)
        if ctx is not None:
            return ctx
        nd_map = self._prog.node_devices
        if not nd_map:
            ctx = self._ctx
        else:
            node, _ = self._prog.output_entries[out_index]
            dev = nd_map.get(id(node), self._prog.default_device)
            if dev is None or dev == self._ctx.jax_device():
                ctx = self._ctx
            else:
                ctx = _context_for_device(dev)
        cache[out_index] = ctx
        return ctx

    def _step_key(self):
        """Fresh RNG key for one step — but only graphs that actually
        consume randomness (dropout etc.) pay the eager ``split``
        dispatch; RNG-free graphs reuse one cached, already-committed
        key so the hot loop ships no new buffer for it."""
        if self._prog.uses_rng:
            return _random.take_key()
        k = getattr(self, "_static_key", None)
        if k is None:
            k = self._static_key = _random.take_key()
        return k

    def forward(self, is_train=False, **kwargs):
        """Run forward (parity: executor.py forward:113)."""
        from .ndarray.ndarray import NDArray, _wrap
        if kwargs:
            with telemetry.span("feed"):
                self._feed_kwargs(kwargs)
        self._last_key = self._step_key()
        fn = self._prog.forward_fn(bool(is_train))
        if not self._prog.node_devices:
            record_dispatch("forward")
        with telemetry.span("step"):
            outs, aux_up = fn(self._raw_args(), self._raw_aux(),
                              self._last_key)
        self._write_aux(aux_up)
        self.outputs = [_wrap(o, self._out_ctx(i))
                        for i, o in enumerate(outs)]
        if self._monitor_callback is not None:
            self._emit_monitor(is_train)
        return self.outputs

    def _emit_monitor(self, is_train):
        """Feed the monitor callback EVERY op's output, not just the graph
        heads (parity: the engine-level monitor tap — reference
        graph_executor.cc monitor_callback_ fires per op). Runs a cached
        internals program; monitoring is a debug lane, so the extra
        compile/execute cost is acceptable."""
        from .ndarray.ndarray import _wrap
        if self._prog.node_devices:
            # grouped (group2ctx) executors: the internals program has no
            # device map — emit the graph heads only
            for name, arr in zip(self._symbol.list_outputs(), self.outputs):
                self._monitor_callback(name, arr)
            return
        if getattr(self, "_mon_prog", None) is None:
            from .symbol.symbol import Group
            internals = self._symbol.get_internals()
            # op outputs only (incl. multi-output "%s_output%d" names) —
            # variable echoes aren't computed nodes
            var_names = set(internals.list_arguments()) | \
                set(internals.list_auxiliary_states())
            self._mon_names = [n for n in internals.list_outputs()
                               if n not in var_names]
            self._mon_prog = _GraphProgram(
                Group([internals[n] for n in self._mon_names]))
        fn = self._mon_prog.forward_fn(bool(is_train))
        record_dispatch("monitor")
        args = {n: self.arg_dict[n]._data for n in self._mon_prog.arg_names}
        aux = {n: self.aux_dict[n]._data for n in self._mon_prog.aux_names}
        key = getattr(self, "_last_key", None)
        if key is None:
            key = self._step_key()
        outs, _ = fn(args, aux, key)
        for name, o in zip(self._mon_names, outs):
            self._monitor_callback(name, _wrap(o, self._ctx))
        if self._monitor_all:        # inputs/params too (reference
            for name, arr in self.arg_dict.items():   # monitor_all=True)
                self._monitor_callback(name, arr)
            for name, arr in self.aux_dict.items():
                self._monitor_callback(name, arr)

    def backward(self, out_grads=None, is_train=True):
        """Run backward (parity: executor.py backward:154). Recomputes the
        forward inside the fused fwd+bwd XLA program (rematerialisation is
        cheaper than keeping all activations resident in HBM; XLA CSEs what
        it can)."""
        self._run_fwd_bwd(out_grads, is_train=is_train, update_outputs=False)

    def forward_backward(self, out_grads=None, is_train=True, **kwargs):
        """Fused forward+backward in one compiled call — the Module fast
        path (one XLA program per train step)."""
        if kwargs:
            with telemetry.span("feed"):
                self._feed_kwargs(kwargs)
        self._last_key = self._step_key()
        self._run_fwd_bwd(out_grads, is_train=is_train, update_outputs=True)
        return self.outputs

    def _feed_kwargs(self, kwargs):
        """Install keyword-fed inputs into bound storage (the ONE
        kwargs copy-in both forward and forward_backward use); numpy
        feeds count toward the telemetry h2d register."""
        from .ndarray.ndarray import NDArray
        for k, v in kwargs.items():
            if k in self.arg_dict:
                if isinstance(v, NDArray):
                    v.copyto(self.arg_dict[k])
                else:
                    raw = np.asarray(v)
                    telemetry.record_transfer(raw.nbytes)
                    self.arg_dict[k][:] = raw

    def _run_fwd_bwd(self, out_grads, is_train, update_outputs):
        from .ndarray.ndarray import NDArray, _wrap
        grad_names = tuple(n for n in self._arg_names
                           if self._grad_req[n] != "null")
        if not grad_names:
            if update_outputs:
                self.forward(is_train=is_train)
            return
        key = getattr(self, "_last_key", None)
        if key is None:
            key = self._step_key()
        fn = self._prog.fwd_bwd_fn(bool(is_train), grad_names)
        if out_grads is None:
            hg = [None] * self.output_entries_len()
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            hg = [g._data if isinstance(g, NDArray) else
                  (jnp.asarray(g) if g is not None else None)
                  for g in out_grads]
        # None head grads must be static for jit: substitute ones at trace
        # time; pass a tuple with None markers replaced lazily
        hg_concrete = []
        for i, g in enumerate(hg):
            hg_concrete.append(g)
        if not self._prog.node_devices:
            record_dispatch("fwd_bwd")
        with telemetry.span("step"):
            outs, grads, aux_up = fn(self._raw_args(), self._raw_aux(), key,
                                     tuple(hg_concrete))
        self._write_aux(aux_up)
        if update_outputs:
            self.outputs = [_wrap(o, self._out_ctx(i))
                            for i, o in enumerate(outs)]
        gdict = dict(zip(self._arg_names, self.grad_arrays))
        for n in grad_names:
            garr = gdict[n]
            if garr is None:
                continue
            if self._grad_req[n] == "add":
                garr._set_data(garr._data + grads[n].astype(garr._data.dtype))
            else:
                garr._set_data(grads[n].astype(garr._data.dtype))

    def output_entries_len(self):
        return len(self._prog.output_entries)

    def _write_aux(self, aux_up):
        if not aux_up:
            return
        d = self.aux_dict
        for name, val in aux_up.items():
            if name in d:
                d[name]._set_data(val)

    # -- misc --------------------------------------------------------------
    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        """(parity: executor.py copy_params_from)"""
        for name, arr in (arg_params or {}).items():
            if name in self.arg_dict:
                arr.copyto(self.arg_dict[name])
            elif not allow_extra_params:
                raise MXNetError("unknown argument %r" % name)
        for name, arr in (aux_params or {}).items():
            if name in self.aux_dict:
                arr.copyto(self.aux_dict[name])
            elif not allow_extra_params:
                raise MXNetError("unknown aux state %r" % name)

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Return a new executor for new input shapes (parity: executor
        reshape; on TPU this is simply a new jit signature — compilation is
        cached per shape like CachedOp)."""
        shapes = dict(kwargs)
        arg_shapes, _, aux_shapes = self._symbol.infer_shape_partial(**shapes)
        from .ndarray import zeros
        new_args = []
        for name, arr, s in zip(self._arg_names, self.arg_arrays, arg_shapes):
            if s is None or tuple(s) == arr.shape:
                new_args.append(arr)
            else:
                new_args.append(zeros(s, ctx=self._ctx))
        new_grads = []
        for arr, s in zip(self.grad_arrays, arg_shapes):
            if arr is None:
                new_grads.append(None)
            elif s is None or tuple(s) == arr.shape:
                new_grads.append(arr)
            else:
                new_grads.append(zeros(s, ctx=self._ctx))
        return Executor(self._symbol, self._ctx, new_args, new_grads,
                        self._grad_req, self.aux_arrays, program=self._prog,
                        owns_arrays=True)

    def set_monitor_callback(self, callback, monitor_all=False):
        self._monitor_callback = callback
        self._monitor_all = bool(monitor_all)

    def debug_str(self):
        lines = ["Symbol outputs: %s" % self._symbol.list_outputs()]
        for n in self._prog.nodes:
            lines.append("%s%s" % (n.name, "" if n.op is None
                                   else " = %s" % n.op.name))
        return "\n".join(lines)
