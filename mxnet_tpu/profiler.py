"""Profiler — chrome-trace output of device execution.

Parity: reference ``src/engine/profiler.{h,cc}`` + ``python/mxnet/
profiler.py`` (SURVEY.md §5.1; chrome://tracing JSON output). TPU-native
design: wraps the JAX/XLA profiler, which records real device op spans
(the reference stamped engine-op spans). ``dump()`` writes a
chrome-trace-compatible ``.trace.json.gz`` plus TensorBoard-compatible
artifacts in the output directory.
"""
from __future__ import annotations

import glob
import os
import time

import jax

from .base import MXNetError, get_env

__all__ = ["profiler_set_config", "profiler_set_state", "set_config",
           "set_state", "dump", "pause", "resume"]

_state = {"running": False, "filename": "profile.json", "dir": None}


def set_config(profile_all=None, profile_symbolic=None,
               profile_imperative=None, profile_memory=None, profile_api=None,
               filename="profile_output.json", **kwargs):
    """(parity: mx.profiler.set_config / MXSetProcessProfilerConfig)"""
    _state["filename"] = filename


profiler_set_config = set_config


def set_state(state="stop", profile_process="worker"):
    """(parity: mx.profiler.set_state — 'run' starts tracing, 'stop' dumps)"""
    if state == "run":
        if not _state["running"]:
            out_dir = os.path.splitext(_state["filename"])[0] + "_trace"
            os.makedirs(out_dir, exist_ok=True)
            jax.profiler.start_trace(out_dir)
            _state["dir"] = out_dir
            _state["running"] = True
    elif state == "stop":
        if _state["running"]:
            jax.profiler.stop_trace()
            _state["running"] = False
            _link_chrome_trace()
    else:
        raise MXNetError("state must be 'run' or 'stop'")


profiler_set_state = set_state


def _link_chrome_trace():
    """Surface the chrome trace at the configured filename as plain JSON —
    the reference emits an uncompressed chrome://tracing file (profiler.cc:161)."""
    out_dir = _state["dir"]
    if not out_dir:
        return
    matches = glob.glob(os.path.join(out_dir, "**", "*.trace.json.gz"),
                        recursive=True)
    if matches:
        import gzip
        import shutil
        with gzip.open(sorted(matches)[-1], "rb") as src, \
                open(_state["filename"], "wb") as dst:
            shutil.copyfileobj(src, dst)


def dump(finished=True, profile_process="worker"):
    """(parity: mx.profiler.dump)"""
    if _state["running"]:
        set_state("stop")


def pause(profile_process="worker"):
    pass


def resume(profile_process="worker"):
    pass


class Scope:
    """Annotate a region so it shows up in the device trace
    (jax.profiler.TraceAnnotation under the hood)."""

    def __init__(self, name):
        self._ann = jax.profiler.TraceAnnotation(name)

    def __enter__(self):
        self._ann.__enter__()
        return self

    def __exit__(self, *exc):
        self._ann.__exit__(*exc)


def dump_profile():
    """Deprecated alias of dump() (parity: profiler.dump_profile)."""
    dump(True)
