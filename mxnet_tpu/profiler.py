"""Profiler — chrome-trace output of device execution + host spans.

Parity: reference ``src/engine/profiler.{h,cc}`` + ``python/mxnet/
profiler.py`` (SURVEY.md §5.1; chrome://tracing JSON output). TPU-native
design: wraps the JAX/XLA profiler, which records real device op spans
(the reference stamped engine-op spans), and MERGES the telemetry host
spans (feed/shard_put/step/metric_fetch/io_next/...) into the same
chrome-trace JSON, so ``dump()`` yields ONE perfetto-loadable file where
the host timeline (what Python dispatched when) lines up against the
device timeline (what XLA executed when) — the view that found the 14x
``Module.fit`` gap (PERF.md). TensorBoard-compatible artifacts stay in
the output directory.
"""
from __future__ import annotations

import glob
import json
import os
import time

import jax

from .base import MXNetError, get_env

__all__ = ["profiler_set_config", "profiler_set_state", "set_config",
           "set_state", "dump", "pause", "resume"]

_state = {"running": False, "filename": "profile.json", "dir": None}


def set_config(profile_all=None, profile_symbolic=None,
               profile_imperative=None, profile_memory=None, profile_api=None,
               filename="profile_output.json", **kwargs):
    """(parity: mx.profiler.set_config / MXSetProcessProfilerConfig)"""
    _state["filename"] = filename


profiler_set_config = set_config


def set_state(state="stop", profile_process="worker"):
    """(parity: mx.profiler.set_state — 'run' starts tracing, 'stop' dumps)"""
    if state == "run":
        if not _state["running"]:
            out_dir = os.path.splitext(_state["filename"])[0] + "_trace"
            os.makedirs(out_dir, exist_ok=True)
            jax.profiler.start_trace(out_dir)
            # stamp the host-span window: the merged dump keeps only
            # spans recorded while the device trace ran
            from . import telemetry
            telemetry.mark_trace_start()
            _state["dir"] = out_dir
            _state["running"] = True
    elif state == "stop":
        if _state["running"]:
            jax.profiler.stop_trace()
            _state["running"] = False
            _link_chrome_trace()
    else:
        raise MXNetError("state must be 'run' or 'stop'")


profiler_set_state = set_state


def _host_events():
    """Telemetry host spans as chrome events — including the causal
    FLOW events (``ph: s/t/f``) linking one serving request's or one
    fit step's spans across threads; the alignment shift below applies
    to those too (they carry ``ts`` like every slice)."""
    from . import telemetry
    return telemetry.chrome_events()


# any epoch-microsecond stamp after ~1973 exceeds this; a trace-relative
# stamp would need a ~3-year-long trace to reach it
_EPOCH_TS_FLOOR_US = 1e14


def _aligned_host_events(device_events, host):
    """Host span events on the device trace's timebase. Telemetry stamps
    spans in epoch microseconds; XLA's trace converter may emit epoch-
    based OR trace-relative timestamps depending on version. The two
    cases are separated by MAGNITUDE (epoch stamps are ~1.7e15 us;
    trace-relative ones start near zero — a first-device-op gap, e.g. a
    minutes-long in-window compile, cannot cross that line): epoch-based
    device stamps need no adjustment; trace-relative ones get the host
    events shifted so the trace-start instant maps onto the earliest
    device timestamp."""
    from . import telemetry
    t0_us = telemetry.trace_start_epoch_us()
    dts = [e["ts"] for e in device_events
           if e.get("ph") in ("X", "B") and "ts" in e]
    if not dts or t0_us is None:
        return host
    dmin = min(dts)
    if dmin > _EPOCH_TS_FLOOR_US:    # device stamps already epoch-based
        return host
    shift = dmin - t0_us
    for e in host:
        if "ts" in e:
            e["ts"] = round(e["ts"] + shift, 3)
    return host


def _link_chrome_trace():
    """Surface the chrome trace at the configured filename as plain JSON
    — the reference emits an uncompressed chrome://tracing file
    (profiler.cc:161) — with the telemetry HOST spans merged into the
    device event list (one perfetto view, host track above the device
    tracks). When the backend produced no ``.trace.json.gz`` (some
    platforms/versions skip the converter), a host-span-only trace is
    still written so the configured filename always materialises."""
    out_dir = _state["dir"]
    if not out_dir:
        return
    matches = glob.glob(os.path.join(out_dir, "**", "*.trace.json.gz"),
                        recursive=True)
    host = _host_events()
    if matches and not any(e.get("ph") == "X" for e in host):
        # nothing to merge (telemetry disabled / empty span window):
        # stream the device dump through verbatim instead of paying a
        # full parse+re-serialize of a potentially huge trace
        import gzip
        import shutil
        with gzip.open(sorted(matches)[-1], "rb") as src, \
                open(_state["filename"], "wb") as dst:
            shutil.copyfileobj(src, dst)
        return
    trace = None
    if matches:
        import gzip
        with gzip.open(sorted(matches)[-1], "rb") as src:
            raw = src.read()
        try:
            trace = json.loads(raw.decode("utf-8", "replace"))
        except ValueError:
            # unparseable device dump: keep the reference behavior
            # (surface it verbatim) rather than lose it to the merge
            with open(_state["filename"], "wb") as dst:
                dst.write(raw)
            return
    if not isinstance(trace, dict) or \
            not isinstance(trace.get("traceEvents"), list):
        events = trace if isinstance(trace, list) else []
        trace = {"traceEvents": events, "displayTimeUnit": "ms"}
    trace["traceEvents"].extend(
        _aligned_host_events(trace["traceEvents"], host))
    # program cards ride in the trace file's otherData (a chrome-trace
    # field perfetto preserves): the cost/memory/compile figures of
    # every program whose spans appear on the host track, so one file
    # carries timeline AND cost model
    from . import telemetry
    cards = telemetry.programs()
    if cards:
        other = trace.setdefault("otherData", {})
        if isinstance(other, dict):
            other["mxnet_tpu_programs"] = cards
    # the flight recorder's recent time-series window rides too (when
    # the sampler ran): the trace then carries timeline, cost model AND
    # the metrics trajectory around the captured window
    from . import flight
    samples = flight.series(240)
    if samples:
        other = trace.setdefault("otherData", {})
        if isinstance(other, dict):
            other["mxnet_tpu_series"] = samples
    with open(_state["filename"], "w") as dst:
        json.dump(trace, dst)


def dump(finished=True, profile_process="worker"):
    """(parity: mx.profiler.dump)"""
    if _state["running"]:
        set_state("stop")


def pause(profile_process="worker"):
    pass


def resume(profile_process="worker"):
    pass


class Scope:
    """Annotate a region so it shows up in the device trace
    (jax.profiler.TraceAnnotation under the hood) AND as a telemetry
    host span (so the region also lands in the merged chrome dump and
    the snapshot percentiles)."""

    def __init__(self, name):
        self._ann = jax.profiler.TraceAnnotation(name)
        from . import telemetry
        self._span = telemetry.span(name)

    def __enter__(self):
        self._span.__enter__()
        try:
            self._ann.__enter__()
        except BaseException:
            # the device annotation failing to arm (profiler state,
            # backend teardown) must not leave the host span entered
            # forever — every entered span exits (mxlife)
            self._span.__exit__(None, None, None)
            raise
        return self

    def __exit__(self, *exc):
        self._ann.__exit__(*exc)
        self._span.__exit__(*exc)


def dump_profile():
    """Deprecated alias of dump() (parity: profiler.dump_profile)."""
    dump(True)
