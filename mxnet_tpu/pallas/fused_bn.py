"""Fused BatchNorm-apply + residual-add + ReLU as a Pallas TPU kernel.

The perf lever PERF.md's xprof analysis calls for: at a ResNet block
tail the compiler's fusion boundary sits at the convolution output, so
the BN normalize, the residual add and the ReLU can land in a separate
elementwise pass over the (N,H,W,C) activation — one extra HBM
round-trip of the largest tensors in the model. This kernel performs

    out = max(x * scale + bias + residual, 0)

in ONE pass: per-channel ``scale``/``bias`` are the folded BN apply
coefficients (scale = gamma * rsqrt(var + eps), bias = beta -
mean * scale — the same folding ops/nn.py:batch_norm does), so the whole
block tail reads x and residual once and writes out once.

Layout: channels-LAST (the framework's MXU-native layout,
mxnet_tpu/layout.py) — the channel dim maps to the 128-wide lane
dimension, rows of the flattened (N*H*W, C) view map to sublanes.

``interpret=True`` off-TPU so the unit suite runs on the CPU mesh.

Backward is a custom VJP in plain XLA (one fused elementwise pass as
well): with ``m = out > 0``, dx = g*m*scale, dresidual = g*m,
dscale = sum_rows(g*m*x), dbias = sum_rows(g*m).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

__all__ = ["scale_bias_add_relu"]

BLOCK_ROWS = 256
BLOCK_COLS = 512


def _use_interpret():
    return jax.default_backend() != "tpu"


def _kernel(x_ref, s_ref, b_ref, r_ref, o_ref):
    x = x_ref[...]
    y = x * s_ref[...] + b_ref[...]
    if r_ref is not None:
        y = y + r_ref[...]
    o_ref[...] = jnp.maximum(y, jnp.zeros((), y.dtype))


def _kernel_nores(x_ref, s_ref, b_ref, o_ref):
    _kernel(x_ref, s_ref, b_ref, None, o_ref)


@functools.partial(jax.jit, static_argnums=(4,))
def _fused_fwd(x2, s, b, r2, interpret):
    m, c = x2.shape
    bm = min(BLOCK_ROWS, m)
    bc = min(BLOCK_COLS, c)
    grid = (pl.cdiv(m, bm), pl.cdiv(c, bc))
    x_spec = pl.BlockSpec((bm, bc), lambda i, j: (i, j))
    v_spec = pl.BlockSpec((1, bc), lambda i, j: (0, j))
    if r2 is not None:
        return pl.pallas_call(
            _kernel,
            grid=grid,
            in_specs=[x_spec, v_spec, v_spec, x_spec],
            out_specs=x_spec,
            out_shape=jax.ShapeDtypeStruct((m, c), x2.dtype),
            interpret=interpret,
        )(x2, s[None, :], b[None, :], r2)
    return pl.pallas_call(
        _kernel_nores,
        grid=grid,
        in_specs=[x_spec, v_spec, v_spec],
        out_specs=x_spec,
        out_shape=jax.ShapeDtypeStruct((m, c), x2.dtype),
        interpret=interpret,
    )(x2, s[None, :], b[None, :])


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _fused(x, scale, bias, residual, interpret):
    c = x.shape[-1]
    x2 = x.reshape(-1, c)
    r2 = residual.reshape(-1, c) if residual is not None else None
    out = _fused_fwd(x2, scale.astype(x.dtype), bias.astype(x.dtype), r2,
                     interpret)
    return out.reshape(x.shape)


def _fused_vjp_fwd(x, scale, bias, residual, interpret):
    out = _fused(x, scale, bias, residual, interpret)
    # bias rides along for its dtype (cotangents must match primal
    # dtypes); residual presence is static via the None subtree
    return out, (x, scale, bias, out, residual)


def _fused_vjp_bwd(interpret, res, g):
    x, scale, bias, out, residual = res
    m = (out > 0).astype(g.dtype)
    gm = g * m
    red = tuple(range(x.ndim - 1))
    dx = (gm * scale.astype(g.dtype)).astype(x.dtype)
    dscale = jnp.sum(gm.astype(jnp.float32) * x.astype(jnp.float32),
                     axis=red).astype(scale.dtype)
    dbias = jnp.sum(gm.astype(jnp.float32), axis=red).astype(bias.dtype)
    dres = gm.astype(residual.dtype) if residual is not None else None
    return dx, dscale, dbias, dres


_fused.defvjp(_fused_vjp_fwd, _fused_vjp_bwd)


def scale_bias_add_relu(x, scale, bias, residual=None, interpret=None):
    """``max(x * scale + bias [+ residual], 0)`` in one device pass.

    x: (..., C) channels-last activation; scale/bias: (C,) folded BN
    apply coefficients; residual: same shape as x or None.
    Differentiable w.r.t. x, scale, bias, residual.
    """
    if interpret is None:
        interpret = _use_interpret()
    if residual is not None:
        if residual.shape != x.shape:
            raise ValueError("residual shape %s != x shape %s"
                             % (residual.shape, x.shape))
        # one compute dtype inside the kernel: the store dtype is pinned
        # to x.dtype, and mixed inputs would promote the block (the
        # composed fallback would silently promote instead — keep the
        # two paths numerically identical)
        residual = residual.astype(x.dtype)
    return _fused(x, scale, bias, residual, bool(interpret))
