"""Hand-written Pallas TPU kernels for the hot ops.

The compute path of this framework is XLA; these kernels cover the spots
where XLA's automatic fusion is not enough (blockwise attention with an
online-softmax accumulator, quantised communication payloads). Every
kernel has an ``interpret`` fallback so the suite runs on the virtual CPU
mesh (tests/conftest.py) and compiles natively on TPU.
"""
from .flash_attention import flash_attention, flash_attention_carry

__all__ = ["flash_attention", "flash_attention_carry"]
