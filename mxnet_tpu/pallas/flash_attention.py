"""Blockwise (flash) attention as a Pallas TPU kernel.

New-framework extension beyond the 2017 reference (which predates
attention, SURVEY.md §5.7); this is the single-chip building block that
``parallel.ring_attention`` composes over the 'sp' mesh axis.

Design (TPU-first):
- grid over (batch*heads, q-blocks); each program owns a ``block_q``-row
  Q tile in VMEM and the device's whole local K/V block (VMEM-resident —
  ring attention keeps per-device K/V small, so one MXU matmul per tile
  beats a DMA'd kv-chunk loop).
- online softmax: running max ``m`` and denominator ``l`` per Q row, so
  the kernel can be chained across ring steps: ``flash_attention_carry``
  takes and returns the (o, m, l) accumulator, exactly the carry that
  rotates with ``ppermute``.
- causal masking by *global* positions (``q_offset``/``kv_offset``): the
  same kernel serves both the single-chip and the sequence-sharded case.
- ``interpret=True`` off-TPU so the unit suite runs on the CPU mesh.

Backward for the plain entry is a custom VJP: recompute probabilities
from the saved log-sum-exp one Q block at a time (lax.map), so peak
memory stays O(block_q * S) instead of O(S^2) — the flash backward
formulation, expressed in XLA.
"""
from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention", "flash_attention_carry"]

DEFAULT_BLOCK_Q = 128
# candidate Q-block sizes offered to the operator tuner (default first)
TUNE_BLOCKS_Q = (128, 256, 512)
NEG_INF = -1e30


def _use_interpret():
    return jax.default_backend() != "tpu"


def _resolve_block_q(q, k, causal, interpret):
    """``block_q=None`` -> measured choice per (shape, dtype, causal)
    signature via the operator tuner (mxnet_tpu.tuner ≙ reference
    operator_tune.h:37-202). Interpret mode (off-TPU) skips measurement —
    timings there say nothing about the MXU."""
    if interpret:
        return DEFAULT_BLOCK_Q
    b, h, s_q, d = q.shape
    s_kv = k.shape[2]
    effective = []
    for blk in TUNE_BLOCKS_Q:
        e = min(blk, max(s_q, 1))
        if e not in effective:
            effective.append(e)
    if len(effective) == 1:
        return effective[0]
    from ..tuner import tuned_choice

    def mk(blk):
        def thunk():
            qz = jnp.zeros((b, h, s_q, d), q.dtype)
            kz = jnp.zeros((b, h, s_kv, d), k.dtype)
            return _forward(qz, kz, kz, causal, 1.0 / math.sqrt(d), blk,
                            interpret)[0]
        return thunk

    key = "bh%d_sq%d_skv%d_d%d_%s_c%d" % (b * h, s_q, s_kv, d,
                                          jnp.dtype(q.dtype).name,
                                          int(causal))
    label = tuned_choice("flash_attention.block_q", key,
                         [(str(e), mk(e)) for e in effective], args=(q, k))
    return int(label)


def _attn_kernel(scalars_ref, q_ref, k_ref, v_ref, o_in_ref, m_in_ref,
                 l_in_ref, o_ref, m_ref, l_ref, *, causal, scale, block_q):
    """One (bh, q-block) program: merge this K/V block into the online
    accumulator. scalars = [q_offset, kv_offset, kv_len]."""
    q_off = scalars_ref[0]
    kv_off = scalars_ref[1]
    kv_len = scalars_ref[2]

    q = q_ref[0]                       # (block_q, D)
    k = k_ref[0]                       # (S_kv, D)
    v = v_ref[0]
    s_kv = k.shape[0]

    scores = jax.lax.dot_general(
        q, k, dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * scale     # (block_q, S_kv)

    qi = pl.program_id(1)
    q_pos = q_off + qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, s_kv), 0)
    k_pos = kv_off + jax.lax.broadcasted_iota(jnp.int32, (block_q, s_kv), 1)
    mask = k_pos < kv_len
    if causal:
        mask = jnp.logical_and(mask, q_pos >= k_pos)
    scores = jnp.where(mask, scores, NEG_INF)

    m_in = m_in_ref[0]                 # (block_q, 1)
    l_in = l_in_ref[0]
    o_in = o_in_ref[0]                 # (block_q, D)

    blk_max = jnp.max(scores, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_in, blk_max)
    corr = jnp.exp(m_in - m_new)
    p = jnp.exp(scores - m_new)
    p = jnp.where(mask, p, 0.0)
    l_new = l_in * corr + jnp.sum(p, axis=-1, keepdims=True)
    pv = jax.lax.dot_general(
        p.astype(v.dtype), v, dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    o_new = o_in * corr + pv

    o_ref[0] = o_new
    m_ref[0] = m_new
    l_ref[0] = l_new


def _carry_call(q, k, v, o, m, l, q_offset, kv_offset, kv_len, causal,
                scale, block_q, interpret):
    """Raw pallas_call on padded (BH, S, D) tensors. Accumulators are
    float32 (BH, Sq[, D])."""
    bh, s_q, d = q.shape
    n_q = s_q // block_q
    # accumulator stats ride as (BH, Sq, 1): unit lane dim keeps the
    # block shapes legal for Mosaic tiling
    m3 = m[..., None]
    l3 = l[..., None]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(bh, n_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, *_: (b, i, 0)),
            pl.BlockSpec((1, k.shape[1], d), lambda b, i, *_: (b, 0, 0)),
            pl.BlockSpec((1, k.shape[1], d), lambda b, i, *_: (b, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, *_: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, *_: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, *_: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, *_: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, *_: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, *_: (b, i, 0)),
        ],
    )
    scalars = jnp.asarray([q_offset, kv_offset, kv_len], jnp.int32)
    kernel = functools.partial(_attn_kernel, causal=causal, scale=scale,
                               block_q=block_q)
    s_kv = k.shape[1]
    flops = 4 * bh * s_q * s_kv * d
    o2, m2, l2 = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((bh, s_q, d), jnp.float32),
            jax.ShapeDtypeStruct((bh, s_q, 1), jnp.float32),
            jax.ShapeDtypeStruct((bh, s_q, 1), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=flops,
            bytes_accessed=4 * (q.size + k.size + v.size + o.size),
            transcendentals=bh * s_q * s_kv),
        interpret=interpret,
    )(scalars, q, k, v, o, m3, l3)
    return o2, m2[..., 0], l2[..., 0]


def _pad_q(x, block_q):
    s = x.shape[1]
    pad = (-s) % block_q
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
    return x, s


def flash_attention_carry(q, k, v, o, m, l, q_offset=0, kv_offset=0,
                          causal=False, scale=None,
                          block_q=DEFAULT_BLOCK_Q, interpret=None):
    """Merge one K/V block into an online-softmax accumulator.

    q: (BH, Sq, D); k/v: (BH, Skv, D); o: (BH, Sq, D) f32 numerator;
    m/l: (BH, Sq) f32 running max / denominator. Returns updated
    (o, m, l) — the caller normalises ``o / l`` after the last block.
    """
    if interpret is None:
        interpret = _use_interpret()
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    block_q = min(block_q, max(q.shape[1], 1))
    qp, s_q = _pad_q(q, block_q)
    pad = qp.shape[1] - s_q
    if pad:
        o = jnp.pad(o, ((0, 0), (0, pad), (0, 0)))
        m = jnp.pad(m, ((0, 0), (0, pad)), constant_values=NEG_INF)
        l = jnp.pad(l, ((0, 0), (0, pad)))
    o2, m2, l2 = _carry_call(qp, k, v, o, m, l, q_offset, kv_offset,
                             kv_offset + k.shape[1], causal, scale,
                             block_q, interpret)
    if pad:
        o2, m2, l2 = o2[:, :s_q], m2[:, :s_q], l2[:, :s_q]
    return o2, m2, l2


def _forward(q, k, v, causal, scale, block_q, interpret):
    """(B, H, S, D) -> (out, lse). Single chip, whole sequence."""
    b, h, s_q, d = q.shape
    s_kv = k.shape[2]
    qf = q.reshape(b * h, s_q, d)
    kf = k.reshape(b * h, s_kv, d)
    vf = v.reshape(b * h, s_kv, d)
    o0 = jnp.zeros((b * h, s_q, d), jnp.float32)
    m0 = jnp.full((b * h, s_q), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b * h, s_q), jnp.float32)
    o, m, l = flash_attention_carry(qf, kf, vf, o0, m0, l0, 0, 0, causal,
                                    scale, block_q, interpret)
    out = o / jnp.maximum(l, 1e-30)[..., None]
    lse = m + jnp.log(jnp.maximum(l, 1e-30))
    return out.astype(q.dtype).reshape(b, h, s_q, d), lse.reshape(b, h, s_q)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal=False, scale=None,
                    block_q=None, interpret=None):
    """Exact attention, (B, H, S, D) layout, O(block_q * S) memory.

    Differentiable; the forward runs as a Pallas kernel on TPU (interpret
    mode elsewhere), the backward recomputes probabilities blockwise from
    the saved log-sum-exp. ``block_q=None`` (default) lets the operator
    tuner measure-and-cache the Q-block size per signature.
    """
    if interpret is None:
        interpret = _use_interpret()
    if block_q is None:
        block_q = _resolve_block_q(q, k, causal, interpret)
    out, _ = _forward(q, k, v, causal, scale if scale is not None
                      else 1.0 / math.sqrt(q.shape[-1]), block_q, interpret)
    return out


def _fwd(q, k, v, causal, scale, block_q, interpret):
    if interpret is None:
        interpret = _use_interpret()
    if block_q is None:
        block_q = _resolve_block_q(q, k, causal, interpret)
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    out, lse = _forward(q, k, v, causal, scale, block_q, interpret)
    return out, (q, k, v, out, lse)


def _bwd_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, delta_ref,
                dq_ref, dk_ref, dv_ref, *, causal, scale, block_q):
    """One (bh, q-block) program of the flash backward: recompute p from
    the saved lse, then dv += p^T dO, ds = p*(dp - delta), dq = ds k,
    dk += ds^T q. dk/dv accumulate across the (sequential) q-block grid
    axis into constant-index output blocks — the TPU Pallas revisiting
    pattern."""
    i = pl.program_id(1)
    f32 = jnp.float32
    q = q_ref[0].astype(f32)           # (bq, D)
    k = k_ref[0].astype(f32)           # (S, D)
    v = v_ref[0].astype(f32)
    g = g_ref[0].astype(f32)           # (bq, D)
    lse = lse_ref[0]                   # (bq, 1) f32
    delta = delta_ref[0]               # (bq, 1) f32

    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=f32) * scale
    if causal:
        s_kv = k.shape[0]
        q_pos = i * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, s_kv), 0)
        k_pos = jax.lax.broadcasted_iota(jnp.int32, (block_q, s_kv), 1)
        s = jnp.where(q_pos >= k_pos, s, NEG_INF)
    p = jnp.exp(s - lse)                                   # (bq, S)

    dv_c = jax.lax.dot_general(p, g, (((0,), (0,)), ((), ())),
                               preferred_element_type=f32)  # (S, D)
    dp = jax.lax.dot_general(g, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=f32)    # (bq, S)
    ds = p * (dp - delta) * scale
    dq = jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                             preferred_element_type=f32)    # (bq, D)
    dk_c = jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                               preferred_element_type=f32)  # (S, D)

    dq_ref[0] = dq

    @pl.when(i == 0)
    def _init():
        dk_ref[0] = jnp.zeros_like(dk_ref[0])
        dv_ref[0] = jnp.zeros_like(dv_ref[0])

    dk_ref[0] += dk_c
    dv_ref[0] += dv_c


def _bwd(causal, scale, block_q, interpret, res, g):
    if interpret is None:
        interpret = _use_interpret()
    use_xla = os.environ.get("MXTPU_FLASH_BWD", "") == "xla"
    if not use_xla:
        return _bwd_flash(causal, scale, block_q, interpret, res, g)
    return _bwd_xla(causal, scale, block_q, interpret, res, g)


def _bwd_flash(causal, scale, block_q, interpret, res, g):
    q, k, v, out, lse = res
    if block_q is None:
        # same tuner decision as the forward: the cache is keyed by the
        # identical signature, so the cached winner (or default) applies
        block_q = _resolve_block_q(q, k, causal, interpret)
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    b, h, s_q, d = q.shape
    s_kv = k.shape[2]
    f32 = jnp.float32
    bh = b * h
    qf = q.reshape(bh, s_q, d)
    kf = k.reshape(bh, s_kv, d)
    vf = v.reshape(bh, s_kv, d)
    gf = g.reshape(bh, s_q, d)
    of = out.reshape(bh, s_q, d)
    lf = lse.reshape(bh, s_q)

    block = min(block_q, max(s_q, 1))
    pad = (-s_q) % block
    qp, _ = _pad_q(qf, block)
    gp, _ = _pad_q(gf, block)
    op, _ = _pad_q(of, block)
    lsep = jnp.pad(lf, ((0, 0), (0, pad)), constant_values=-NEG_INF)
    delta = jnp.sum(gp.astype(f32) * op.astype(f32), -1)   # (BH, Sq')
    n_q = qp.shape[1] // block

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=0,
        grid=(bh, n_q),
        in_specs=[
            pl.BlockSpec((1, block, d), lambda b, i: (b, i, 0)),      # q
            pl.BlockSpec((1, s_kv, d), lambda b, i: (b, 0, 0)),       # k
            pl.BlockSpec((1, s_kv, d), lambda b, i: (b, 0, 0)),       # v
            pl.BlockSpec((1, block, d), lambda b, i: (b, i, 0)),      # g
            pl.BlockSpec((1, block, 1), lambda b, i: (b, i, 0)),      # lse
            pl.BlockSpec((1, block, 1), lambda b, i: (b, i, 0)),      # delta
        ],
        out_specs=[
            pl.BlockSpec((1, block, d), lambda b, i: (b, i, 0)),      # dq
            pl.BlockSpec((1, s_kv, d), lambda b, i: (b, 0, 0)),       # dk
            pl.BlockSpec((1, s_kv, d), lambda b, i: (b, 0, 0)),       # dv
        ],
    )
    kernel = functools.partial(_bwd_kernel, causal=causal, scale=scale,
                               block_q=block)
    dq, dk, dv = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((bh, qp.shape[1], d), f32),
            jax.ShapeDtypeStruct((bh, s_kv, d), f32),
            jax.ShapeDtypeStruct((bh, s_kv, d), f32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=5 * bh * qp.shape[1] * s_kv * d,
            bytes_accessed=4 * (qp.size + kf.size + vf.size + gp.size),
            transcendentals=bh * qp.shape[1] * s_kv),
        interpret=interpret,
    )(qp, kf, vf, gp, lsep[..., None], delta[..., None])
    dq = dq[:, :s_q].reshape(b, h, s_q, d)
    return (dq.astype(q.dtype), dk.reshape(b, h, s_kv, d).astype(k.dtype),
            dv.reshape(b, h, s_kv, d).astype(v.dtype))


def _bwd_xla(causal, scale, block_q, interpret, res, g):
    q, k, v, out, lse = res
    if block_q is None:
        block_q = _resolve_block_q(q, k, causal, interpret)
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    b, h, s_q, d = q.shape
    s_kv = k.shape[2]
    block = min(block_q, s_q)
    pad = (-s_q) % block
    qp = jnp.pad(q, ((0, 0), (0, 0), (0, pad), (0, 0)))
    gp = jnp.pad(g, ((0, 0), (0, 0), (0, pad), (0, 0)))
    op = jnp.pad(out, ((0, 0), (0, 0), (0, pad), (0, 0)))
    # padded q rows get a large POSITIVE lse so p = exp(s - lse) -> 0
    # (NEG_INF here would give exp(+inf) -> NaN folded into dk/dv)
    lsep = jnp.pad(lse, ((0, 0), (0, 0), (0, pad)), constant_values=-NEG_INF)
    n_blk = qp.shape[2] // block

    # delta_i = rowsum(dO * O)
    delta = jnp.sum(gp.astype(jnp.float32) * op.astype(jnp.float32), -1)

    k_pos = jnp.arange(s_kv)

    def blk(i):
        def sl(x, ax=2):
            return lax.dynamic_slice_in_dim(x, i * block, block, axis=ax)
        qb, gb = sl(qp), sl(gp)
        lb = sl(lsep)
        db = sl(delta)
        s = jnp.einsum("bhqd,bhkd->bhqk", qb, k,
                       preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = i * block + jnp.arange(block)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask, s, NEG_INF)
        p = jnp.exp(s - lb[..., None])                  # (b,h,block,S)
        dp = jnp.einsum("bhqd,bhkd->bhqk", gb, v,
                        preferred_element_type=jnp.float32)
        ds = p * (dp - db[..., None]) * scale
        dq = jnp.einsum("bhqk,bhkd->bhqd", ds, k)
        dk = jnp.einsum("bhqk,bhqd->bhkd", ds, qb)
        dv = jnp.einsum("bhqk,bhqd->bhkd", p, gb)
        return dq, dk, dv

    dqs, dks, dvs = lax.map(blk, jnp.arange(n_blk))
    dq = jnp.moveaxis(dqs, 0, 2).reshape(b, h, n_blk * block, d)[:, :, :s_q]
    dk = jnp.sum(dks, axis=0)
    dv = jnp.sum(dvs, axis=0)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


flash_attention.defvjp(_fwd, _bwd)
