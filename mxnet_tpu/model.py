"""Model helpers: checkpointing + the BatchEndParam plumbing.

Parity: reference ``python/mxnet/model.py`` (save_checkpoint:366,
load_checkpoint:396, BatchEndParam, _create_kvstore). The legacy
FeedForward API is represented by Module (module/), which the reference
itself recommends.

Checkpoint format (parity: SURVEY.md §5.4's three artifacts):
  prefix-symbol.json   — graph JSON (reference-compatible node list)
  prefix-NNNN.params   — arg:/aux:-prefixed arrays (nd.save container)
"""
from __future__ import annotations

from collections import namedtuple

from . import symbol as sym
from .ndarray import save as _nd_save, load as _nd_load

BatchEndParam = namedtuple("BatchEndParams",
                           ["epoch", "nbatch", "eval_metric", "locals"])


def _create_kvstore(kvstore, num_device, arg_params):
    """(parity: model._create_kvstore:58)"""
    from . import kvstore as kvs
    update_on_kvstore = True
    if kvstore is None:
        kv = None
    elif isinstance(kvstore, kvs.KVStore):
        kv = kvstore
    elif isinstance(kvstore, str):
        if num_device == 1 and "dist" not in kvstore:
            kv = None
        else:
            kv = kvs.create(kvstore)
            if kvstore == "local":
                max_size = max(int(__import__("numpy").prod(p.shape))
                               for p in arg_params.values()) if arg_params else 0
                if max_size > 1024 * 1024 * 16:
                    update_on_kvstore = False
    else:
        raise TypeError("kvstore must be KVStore, str, or None")
    if kv is None:
        update_on_kvstore = False
    return kv, update_on_kvstore


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params):
    """(parity: model.save_checkpoint:366) — ATOMIC, unlike the
    reference: every artifact lands via temp+fsync+rename
    (mxnet_tpu/checkpoint.py), so a preemption mid-save never leaves a
    truncated ``.params`` file poisoning the next start, and a
    concurrent reader sees either the previous complete checkpoint or
    the new one."""
    from .checkpoint import atomic_write, atomic_save_ndarrays
    from .filesystem import scheme_of
    if symbol is not None:
        if scheme_of(prefix):      # remote URIs cannot rename
            symbol.save("%s-symbol.json" % prefix)
        else:
            atomic_write("%s-symbol.json" % prefix, symbol.tojson())
    save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
    save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
    param_name = "%s-%04d.params" % (prefix, epoch)
    atomic_save_ndarrays(param_name, save_dict)


def load_checkpoint(prefix, epoch):
    """(parity: model.load_checkpoint:396) -> (symbol, arg_params, aux_params)"""
    symbol = sym.load("%s-symbol.json" % prefix)
    save_dict = _nd_load("%s-%04d.params" % (prefix, epoch))
    arg_params, aux_params = {}, {}
    for k, v in save_dict.items():
        tp, name = k.split(":", 1)
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
    return symbol, arg_params, aux_params


class FeedForward:
    """Legacy training API (parity: model.FeedForward:? — deprecated in
    the reference in favour of Module, kept for old user code; this is a
    faithful wrapper over mx.mod.Module)."""

    def __init__(self, symbol, ctx=None, num_epoch=None, epoch_size=None,
                 optimizer="sgd", initializer=None, numpy_batch_size=128,
                 arg_params=None, aux_params=None, allow_extra_params=False,
                 begin_epoch=0, **kwargs):
        from . import initializer as init_mod
        self.symbol = symbol
        self.ctx = ctx
        self.num_epoch = num_epoch
        self.epoch_size = epoch_size
        self.optimizer = optimizer
        self.initializer = initializer or init_mod.Uniform(0.01)
        self.numpy_batch_size = numpy_batch_size
        self.arg_params = arg_params
        self.aux_params = aux_params
        self.allow_extra_params = allow_extra_params
        self.begin_epoch = begin_epoch
        self.kwargs = dict(kwargs)
        self._module = None

    # -- data normalisation -------------------------------------------------
    def _as_iter(self, X, y=None, shuffle=False):
        from .io import DataIter, NDArrayIter
        if isinstance(X, DataIter):
            return X
        return NDArrayIter(X, y, batch_size=self.numpy_batch_size,
                           shuffle=shuffle)

    def _ensure_module(self, data_iter):
        from .module import Module
        if self._module is None:
            label_names = [d.name for d in (data_iter.provide_label or [])] \
                or None
            self._module = Module(
                self.symbol, data_names=[d.name for d in
                                         data_iter.provide_data],
                label_names=label_names, context=self.ctx)
        return self._module

    # -- training / inference ----------------------------------------------
    def fit(self, X, y=None, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            logger=None, work_load_list=None, monitor=None,
            eval_end_callback=None, eval_batch_end_callback=None):
        train = self._as_iter(X, y, shuffle=True)
        mod = self._ensure_module(train)
        opt_kwargs = dict(self.kwargs)
        # allow_extra_params means "ignore surplus keys in arg_params"
        # (reference FeedForward semantics) — NOT Module's allow_missing
        arg_params = self.arg_params
        if arg_params and self.allow_extra_params:
            valid = set(self.symbol.list_arguments())
            arg_params = {k: v for k, v in arg_params.items() if k in valid}
        mod.fit(train, eval_data=eval_data, eval_metric=eval_metric,
                epoch_end_callback=epoch_end_callback,
                batch_end_callback=batch_end_callback, kvstore=kvstore,
                optimizer=self.optimizer, optimizer_params=opt_kwargs,
                initializer=self.initializer,
                arg_params=arg_params, aux_params=self.aux_params,
                # reference FeedForward initialises any param absent from
                # arg_params with self.initializer (_init_params), so a
                # partial dict is always permitted here
                allow_missing=arg_params is not None,
                begin_epoch=self.begin_epoch,
                num_epoch=self.num_epoch or 1, monitor=monitor)
        self.arg_params, self.aux_params = mod.get_params()
        return self

    def predict(self, X, num_batch=None, return_data=False, reset=True):
        import numpy as np
        data = self._as_iter(X)
        mod = self._ensure_module(data)
        if not mod.binded:
            mod.bind(data_shapes=data.provide_data,
                     label_shapes=data.provide_label, for_training=False)
            mod.set_params(self.arg_params or {}, self.aux_params or {},
                           allow_missing=False)
        if reset:
            data.reset()
        outs = mod.predict(data, num_batch=num_batch)
        outs = outs if isinstance(outs, list) else [outs]
        res = [o.asnumpy() for o in outs]
        return res[0] if len(res) == 1 else res

    def score(self, X, eval_metric="acc", num_batch=None,
              batch_end_callback=None, reset=True):
        from . import metric as metric_mod
        data = self._as_iter(X)
        mod = self._ensure_module(data)
        if not mod.binded:
            mod.bind(data_shapes=data.provide_data,
                     label_shapes=data.provide_label, for_training=False)
            mod.set_params(self.arg_params or {}, self.aux_params or {})
        if reset:
            data.reset()
        res = mod.score(data, eval_metric, num_batch=num_batch,
                        batch_end_callback=batch_end_callback)
        return res[0][1]

    # -- persistence ---------------------------------------------------------
    def save(self, prefix, epoch=None):
        if epoch is None:
            epoch = self.num_epoch or 0
        save_checkpoint(prefix, epoch, self.symbol,
                        self.arg_params or {}, self.aux_params or {})

    @staticmethod
    def load(prefix, epoch, ctx=None, **kwargs):
        symbol, arg_params, aux_params = load_checkpoint(prefix, epoch)
        return FeedForward(symbol, ctx=ctx, arg_params=arg_params,
                           aux_params=aux_params, begin_epoch=epoch,
                           **kwargs)

    @staticmethod
    def create(symbol, X, y=None, ctx=None, num_epoch=None, epoch_size=None,
               optimizer="sgd", initializer=None, eval_data=None,
               eval_metric="acc", epoch_end_callback=None,
               batch_end_callback=None, kvstore="local", logger=None,
               work_load_list=None, eval_end_callback=None,
               eval_batch_end_callback=None, **kwargs):
        model = FeedForward(symbol, ctx=ctx, num_epoch=num_epoch,
                            epoch_size=epoch_size, optimizer=optimizer,
                            initializer=initializer, **kwargs)
        model.fit(X, y, eval_data=eval_data, eval_metric=eval_metric,
                  epoch_end_callback=epoch_end_callback,
                  batch_end_callback=batch_end_callback, kvstore=kvstore,
                  logger=logger, work_load_list=work_load_list,
                  eval_end_callback=eval_end_callback,
                  eval_batch_end_callback=eval_batch_end_callback)
        return model
