"""Model helpers: checkpointing + the BatchEndParam plumbing.

Parity: reference ``python/mxnet/model.py`` (save_checkpoint:366,
load_checkpoint:396, BatchEndParam, _create_kvstore). The legacy
FeedForward API is represented by Module (module/), which the reference
itself recommends.

Checkpoint format (parity: SURVEY.md §5.4's three artifacts):
  prefix-symbol.json   — graph JSON (reference-compatible node list)
  prefix-NNNN.params   — arg:/aux:-prefixed arrays (nd.save container)
"""
from __future__ import annotations

from collections import namedtuple

from . import symbol as sym
from .ndarray import save as _nd_save, load as _nd_load

BatchEndParam = namedtuple("BatchEndParams",
                           ["epoch", "nbatch", "eval_metric", "locals"])


def _create_kvstore(kvstore, num_device, arg_params):
    """(parity: model._create_kvstore:58)"""
    from . import kvstore as kvs
    update_on_kvstore = True
    if kvstore is None:
        kv = None
    elif isinstance(kvstore, kvs.KVStore):
        kv = kvstore
    elif isinstance(kvstore, str):
        if num_device == 1 and "dist" not in kvstore:
            kv = None
        else:
            kv = kvs.create(kvstore)
            if kvstore == "local":
                max_size = max(int(__import__("numpy").prod(p.shape))
                               for p in arg_params.values()) if arg_params else 0
                if max_size > 1024 * 1024 * 16:
                    update_on_kvstore = False
    else:
        raise TypeError("kvstore must be KVStore, str, or None")
    if kv is None:
        update_on_kvstore = False
    return kv, update_on_kvstore


def save_checkpoint(prefix, epoch, symbol, arg_params, aux_params):
    """(parity: model.save_checkpoint:366)"""
    if symbol is not None:
        symbol.save("%s-symbol.json" % prefix)
    save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
    save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
    param_name = "%s-%04d.params" % (prefix, epoch)
    _nd_save(param_name, save_dict)


def load_checkpoint(prefix, epoch):
    """(parity: model.load_checkpoint:396) -> (symbol, arg_params, aux_params)"""
    symbol = sym.load("%s-symbol.json" % prefix)
    save_dict = _nd_load("%s-%04d.params" % (prefix, epoch))
    arg_params, aux_params = {}, {}
    for k, v in save_dict.items():
        tp, name = k.split(":", 1)
        if tp == "arg":
            arg_params[name] = v
        elif tp == "aux":
            aux_params[name] = v
    return symbol, arg_params, aux_params
