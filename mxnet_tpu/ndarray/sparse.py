"""Sparse NDArrays: row_sparse and csr storage types.

Parity: reference ``python/mxnet/ndarray/sparse.py`` (RowSparseNDArray,
CSRNDArray) and ``include/mxnet/ndarray.h:59-63`` storage types.

TPU-native design: TPUs have no native CSR kernels; sparse arrays keep
their compressed representation on host/device as (data, indices[, indptr])
jax arrays, and compute paths use gather/scatter + segment-sum (XLA lowers
these well) or densify when the op has no sparse path — mirroring the
reference's "storage fallback" (``src/common/utils.h``). The row_sparse
gradient path for embeddings is the important one for parity
(SURVEY.md §2.3 "sparse/large-embedding parallelism").
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..base import MXNetError
from ..context import current_context
from .ndarray import NDArray, _wrap, array as _dense_array

__all__ = ["RowSparseNDArray", "CSRNDArray", "row_sparse_array", "csr_matrix",
           "zeros", "cast_storage", "dot", "add_n", "elemwise_add"]


class BaseSparseNDArray(NDArray):
    """Common base. The dense view is built LAZILY: ``_data`` is a
    property that materialises (and caches) on first dense access, so
    sparse-native paths (kvstore row_sparse push/pull, add_n, retain)
    never allocate the full weight-shape tensor — the point of the
    reference's kRowSparsePushPull path (kvstore_dist.h:430-496)."""

    __slots__ = ("_dense_cache", "_sp_shape")

    @property
    def _data(self):
        if self._dense_cache is None:
            self._dense_cache = self._make_dense()
        return self._dense_cache

    @_data.setter
    def _data(self, v):
        self._dense_cache = v

    @property
    def shape(self):
        return self._sp_shape

    @property
    def ndim(self):
        return len(self._sp_shape)

    @property
    def size(self):
        n = 1
        for s in self._sp_shape:
            n *= s
        return n


class RowSparseNDArray(BaseSparseNDArray):
    """Row-sparse array: (indices -> rows) pair, rest implicitly zero."""

    __slots__ = ("_rsp_data", "_rsp_indices")

    def __init__(self, data, indices, shape, ctx=None):
        super().__init__(None, ctx or current_context())
        self._sp_shape = tuple(shape)
        self._rsp_data = data
        self._rsp_indices = indices.astype(jnp.int64)
        self._stype = "row_sparse"

    def _make_dense(self):
        return jnp.zeros(self._sp_shape, self._rsp_data.dtype) \
            .at[self._rsp_indices.astype(jnp.int32)].set(self._rsp_data)

    @property
    def dtype(self):
        return np.dtype(self._rsp_data.dtype) \
            if self._rsp_data.dtype != jnp.bfloat16 else self._rsp_data.dtype

    @property
    def data(self):
        return _wrap(self._rsp_data, self._ctx)

    @property
    def indices(self):
        return _wrap(self._rsp_indices, self._ctx)

    def tostype(self, stype):
        if stype == "row_sparse":
            return self
        if stype == "default":
            return _wrap(self._data, self._ctx)
        if stype == "csr":
            return cast_storage(_wrap(self._data, self._ctx), "csr")
        raise MXNetError("unknown stype %r" % stype)

    def copy(self):
        return RowSparseNDArray(self._rsp_data, self._rsp_indices, self.shape,
                                self._ctx)

    def retain(self, row_ids):
        """Keep only listed rows (parity: mx.nd.sparse.retain)."""
        rows = row_ids.asnumpy().astype(np.int64) if isinstance(row_ids, NDArray) \
            else np.asarray(row_ids, np.int64)
        mask = np.isin(np.asarray(self._rsp_indices), rows)
        idx = np.asarray(self._rsp_indices)[mask]
        data = np.asarray(self._rsp_data)[mask]
        return RowSparseNDArray(jnp.asarray(data), jnp.asarray(idx), self.shape,
                                self._ctx)


class CSRNDArray(BaseSparseNDArray):
    """Compressed sparse row matrix."""

    __slots__ = ("_csr_data", "_csr_indices", "_csr_indptr")

    def __init__(self, data, indices, indptr, shape, ctx=None):
        super().__init__(None, ctx or current_context())
        self._sp_shape = tuple(shape)
        self._csr_data = jnp.asarray(np.asarray(data))
        self._csr_indices = jnp.asarray(np.asarray(indices, np.int64))
        self._csr_indptr = jnp.asarray(np.asarray(indptr, np.int64))
        self._stype = "csr"

    def _make_dense(self):
        data_np = np.asarray(self._csr_data)
        ind_np = np.asarray(self._csr_indices)
        ptr_np = np.asarray(self._csr_indptr)
        dense = np.zeros(self._sp_shape, data_np.dtype)
        for r in range(self._sp_shape[0]):
            lo, hi = ptr_np[r], ptr_np[r + 1]
            dense[r, ind_np[lo:hi]] = data_np[lo:hi]
        return jnp.asarray(dense)

    @property
    def dtype(self):
        return np.dtype(self._csr_data.dtype) \
            if self._csr_data.dtype != jnp.bfloat16 else self._csr_data.dtype

    @property
    def data(self):
        return _wrap(self._csr_data, self._ctx)

    @property
    def indices(self):
        return _wrap(self._csr_indices, self._ctx)

    @property
    def indptr(self):
        return _wrap(self._csr_indptr, self._ctx)

    def tostype(self, stype):
        if stype == "csr":
            return self
        if stype == "default":
            return _wrap(self._data, self._ctx)
        if stype == "row_sparse":
            return cast_storage(_wrap(self._data, self._ctx), "row_sparse")
        raise MXNetError("unknown stype %r" % stype)


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    """Create a RowSparseNDArray (parity: mx.nd.sparse.row_sparse_array)."""
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        data = _dense_array(data, dtype=dtype)._data
        indices = np.asarray(indices, np.int64)
        if shape is None:
            raise MXNetError("row_sparse_array: shape required")
        return RowSparseNDArray(data, jnp.asarray(indices), tuple(shape), ctx)
    dense = _dense_array(arg1, dtype=dtype)
    return cast_storage(dense, "row_sparse")


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    """Create a CSRNDArray (parity: mx.nd.sparse.csr_matrix)."""
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        data = _dense_array(data, dtype=dtype)._data
        if shape is None:
            raise MXNetError("csr_matrix: shape required")
        return CSRNDArray(data, indices, indptr, tuple(shape), ctx)
    dense = _dense_array(arg1, dtype=dtype)
    return cast_storage(dense, "csr")


def zeros(stype, shape, ctx=None, dtype=None):
    dt = np.dtype(dtype or np.float32)
    if stype == "row_sparse":
        return RowSparseNDArray(jnp.zeros((0,) + tuple(shape[1:]), dt),
                                jnp.zeros((0,), jnp.int64), tuple(shape), ctx)
    if stype == "csr":
        return CSRNDArray(jnp.zeros((0,), dt), np.zeros((0,), np.int64),
                          np.zeros((shape[0] + 1,), np.int64), tuple(shape), ctx)
    from .ndarray import zeros as _dz
    return _dz(shape, ctx=ctx, dtype=dtype)


def cast_storage(arr, stype):
    """Convert between storage types (parity: mx.nd.cast_storage,
    reference src/operator/tensor/cast_storage.cc)."""
    if arr.stype == stype:
        return arr
    dense = np.asarray(arr.asnumpy())
    if stype == "default":
        return _wrap(jnp.asarray(dense), arr.context)
    if stype == "row_sparse":
        nz_rows = np.where(np.any(dense != 0, axis=tuple(range(1, dense.ndim))))[0]
        return RowSparseNDArray(jnp.asarray(dense[nz_rows]),
                                jnp.asarray(nz_rows.astype(np.int64)),
                                dense.shape, arr.context)
    if stype == "csr":
        if dense.ndim != 2:
            raise MXNetError("csr requires 2-D")
        indptr = [0]
        indices = []
        data = []
        for r in range(dense.shape[0]):
            nz = np.nonzero(dense[r])[0]
            indices.extend(nz.tolist())
            data.extend(dense[r, nz].tolist())
            indptr.append(len(indices))
        return CSRNDArray(np.asarray(data, dense.dtype),
                          np.asarray(indices, np.int64),
                          np.asarray(indptr, np.int64), dense.shape, arr.context)
    raise MXNetError("unknown stype %r" % stype)


def add_n(arrays):
    """Sum row_sparse arrays without densifying (parity: reference
    ElementwiseSum's row_sparse path, ndarray.cc:575): index-space union
    on host (indices are tiny), one XLA segment-sum over the stacked
    values — the aggregation kvstore uses for sparse gradient pushes."""
    import jax
    if not arrays:
        raise MXNetError("add_n: empty list")
    if not all(isinstance(a, RowSparseNDArray) for a in arrays):
        raise MXNetError("add_n: all inputs must be row_sparse")
    shape = arrays[0].shape
    idx_list = [np.asarray(a._rsp_indices, np.int64) for a in arrays]
    uniq, inv = np.unique(np.concatenate(idx_list), return_inverse=True)
    data = jnp.concatenate([a._rsp_data for a in arrays], axis=0)
    summed = jax.ops.segment_sum(data, jnp.asarray(inv),
                                 num_segments=len(uniq))
    return RowSparseNDArray(summed, jnp.asarray(uniq), shape,
                            arrays[0].context)


def elemwise_add(lhs, rhs):
    """row_sparse + row_sparse -> row_sparse (reference
    elemwise_binary_op_basic.cc sparse path)."""
    return add_n([lhs, rhs])


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Sparse-aware dot: on TPU sparse operands compute via their dense view
    (XLA) — the API-level contract (csr·dense, csr^T·dense used by the
    sparse linear-classification example) is preserved."""
    from . import dot as _dense_dot
    return _dense_dot(_wrap(lhs._data, lhs.context) if isinstance(lhs, BaseSparseNDArray) else lhs,
                      _wrap(rhs._data, rhs.context) if isinstance(rhs, BaseSparseNDArray) else rhs,
                      transpose_a=transpose_a, transpose_b=transpose_b)
