"""Sparse NDArrays: row_sparse and csr storage types.

Parity: reference ``python/mxnet/ndarray/sparse.py`` (RowSparseNDArray,
CSRNDArray) and ``include/mxnet/ndarray.h:59-63`` storage types.

TPU-native design: TPUs have no native CSR kernels; sparse arrays keep
their compressed representation on host/device as (data, indices[, indptr])
jax arrays, and compute paths use gather/scatter + segment-sum (XLA lowers
these well) or densify when the op has no sparse path — mirroring the
reference's "storage fallback" (``src/common/utils.h``). The row_sparse
gradient path for embeddings is the important one for parity
(SURVEY.md §2.3 "sparse/large-embedding parallelism").
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..base import MXNetError
from ..context import current_context
from .ndarray import NDArray, _wrap, array as _dense_array

__all__ = ["RowSparseNDArray", "CSRNDArray", "row_sparse_array", "csr_matrix",
           "empty", "array",
           "zeros", "cast_storage", "dot", "add_n", "elemwise_add",
           "elemwise_sub", "elemwise_mul", "square", "square_sum", "sum"]


class BaseSparseNDArray(NDArray):
    """Common base. The dense view is built LAZILY: ``_data`` is a
    property that materialises (and caches) on first dense access, so
    sparse-native paths (kvstore row_sparse push/pull, add_n, retain)
    never allocate the full weight-shape tensor — the point of the
    reference's kRowSparsePushPull path (kvstore_dist.h:430-496)."""

    __slots__ = ("_dense_cache", "_sp_shape")

    @property
    def _data(self):
        if self._dense_cache is None:
            self._dense_cache = self._make_dense()
        return self._dense_cache

    @_data.setter
    def _data(self, v):
        self._dense_cache = v

    @property
    def shape(self):
        return self._sp_shape

    @property
    def ndim(self):
        return len(self._sp_shape)

    @property
    def size(self):
        n = 1
        for s in self._sp_shape:
            n *= s
        return n


class RowSparseNDArray(BaseSparseNDArray):
    """Row-sparse array: (indices -> rows) pair, rest implicitly zero."""

    __slots__ = ("_rsp_data", "_rsp_indices")

    def __init__(self, data, indices, shape, ctx=None):
        super().__init__(None, ctx or current_context())
        self._sp_shape = tuple(shape)
        self._rsp_data = data
        self._rsp_indices = indices.astype(jnp.int32)
        self._stype = "row_sparse"

    def _make_dense(self):
        # duplicate row ids (ill-formed but constructible input): XLA's
        # scatter-set order is UNSPECIFIED, so pin last-stored-wins
        # deterministically — cast_storage's dedup uses the same rule,
        # keeping the two representations equal on every backend
        idx = np.asarray(self._rsp_indices)
        data = self._rsp_data
        if idx.size and np.unique(idx).size != idx.size:
            order = np.argsort(idx, kind="stable")
            sorted_ids = idx[order]
            keep = order[np.concatenate(
                [sorted_ids[1:] != sorted_ids[:-1], [True]])]
            idx = idx[keep]
            data = data[jnp.asarray(keep.astype(np.int32))]
        return jnp.zeros(self._sp_shape, self._rsp_data.dtype) \
            .at[jnp.asarray(idx.astype(np.int32))].set(data)

    @property
    def dtype(self):
        return np.dtype(self._rsp_data.dtype) \
            if self._rsp_data.dtype != jnp.bfloat16 else self._rsp_data.dtype

    @property
    def data(self):
        return _wrap(self._rsp_data, self._ctx)

    @property
    def indices(self):
        return _wrap(self._rsp_indices, self._ctx)

    def tostype(self, stype):
        if stype == "row_sparse":
            return self
        if stype == "default":
            return _wrap(self._data, self._ctx)
        if stype == "csr":
            return cast_storage(self, "csr")  # O(stored-rows), no densify
        raise MXNetError("unknown stype %r" % stype)

    def copy(self):
        return RowSparseNDArray(self._rsp_data, self._rsp_indices, self.shape,
                                self._ctx)

    def retain(self, row_ids):
        """Keep only listed rows (parity: mx.nd.sparse.retain). Membership
        test and compaction run device-side (jnp.isin + boolean gather);
        only the result sizes reach the host."""
        rows = row_ids._data if isinstance(row_ids, NDArray) \
            else jnp.asarray(np.asarray(row_ids))
        mask = jnp.isin(self._rsp_indices, rows.astype(self._rsp_indices.dtype))
        return RowSparseNDArray(self._rsp_data[mask],
                                self._rsp_indices[mask], self.shape,
                                self._ctx)


class CSRNDArray(BaseSparseNDArray):
    """Compressed sparse row matrix."""

    __slots__ = ("_csr_data", "_csr_indices", "_csr_indptr")

    def __init__(self, data, indices, indptr, shape, ctx=None):
        super().__init__(None, ctx or current_context())
        self._sp_shape = tuple(shape)
        # device arrays pass through untouched — the sparse-native
        # conversion paths must not bounce O(nnz) payloads via the host
        self._csr_data = data if isinstance(data, jax.Array) \
            else jnp.asarray(np.asarray(data))
        self._csr_indices = indices if isinstance(indices, jax.Array) \
            else jnp.asarray(np.asarray(indices, np.int64))
        self._csr_indptr = indptr if isinstance(indptr, jax.Array) \
            else jnp.asarray(np.asarray(indptr, np.int64))
        self._stype = "csr"

    def _row_ids(self):
        """Expand indptr to one row id per stored value (the segment-id
        form every CSR kernel here consumes; one device op)."""
        counts = jnp.diff(self._csr_indptr)
        return jnp.repeat(jnp.arange(self._sp_shape[0]), counts,
                          total_repeat_length=int(self._csr_data.shape[0]))

    def _make_dense(self):
        """One scatter: dense[row_ids, col_indices] = data (CSR has unique
        coordinates, so .set is exact). No host loop — the round trip
        stays on device."""
        rows = self._row_ids()
        return jnp.zeros(self._sp_shape, self._csr_data.dtype) \
            .at[rows, self._csr_indices.astype(jnp.int32)] \
            .set(self._csr_data)

    @property
    def dtype(self):
        return np.dtype(self._csr_data.dtype) \
            if self._csr_data.dtype != jnp.bfloat16 else self._csr_data.dtype

    @property
    def data(self):
        return _wrap(self._csr_data, self._ctx)

    @property
    def indices(self):
        return _wrap(self._csr_indices, self._ctx)

    @property
    def indptr(self):
        return _wrap(self._csr_indptr, self._ctx)

    def tostype(self, stype):
        if stype == "csr":
            return self
        if stype == "default":
            return _wrap(self._data, self._ctx)
        if stype == "row_sparse":
            return cast_storage(self, "row_sparse")  # O(nnz), no densify
        raise MXNetError("unknown stype %r" % stype)


def _csr_asscipy(self):
    """scipy.sparse.csr_matrix view (parity: sparse.CSRNDArray.asscipy)."""
    try:
        from scipy import sparse as sps
    except ImportError:
        raise ImportError("scipy is not installed")
    return sps.csr_matrix((self.data.asnumpy(), self.indices.asnumpy(),
                           self.indptr.asnumpy()), shape=self.shape)


CSRNDArray.asscipy = _csr_asscipy


def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    """Create a RowSparseNDArray (parity: mx.nd.sparse.row_sparse_array)."""
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        data = _dense_array(data, dtype=dtype)._data
        indices = np.asarray(indices, np.int64)
        if shape is None:
            raise MXNetError("row_sparse_array: shape required")
        return RowSparseNDArray(data, jnp.asarray(indices), tuple(shape), ctx)
    dense = _dense_array(arg1, dtype=dtype)
    return cast_storage(dense, "row_sparse")


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    """Create a CSRNDArray (parity: mx.nd.sparse.csr_matrix)."""
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        data = _dense_array(data, dtype=dtype)._data
        if shape is None:
            raise MXNetError("csr_matrix: shape required")
        return CSRNDArray(data, indices, indptr, tuple(shape), ctx)
    dense = _dense_array(arg1, dtype=dtype)
    return cast_storage(dense, "csr")


def zeros(stype, shape, ctx=None, dtype=None):
    dt = np.dtype(dtype or np.float32)
    if stype == "row_sparse":
        return RowSparseNDArray(jnp.zeros((0,) + tuple(shape[1:]), dt),
                                jnp.zeros((0,), jnp.int32), tuple(shape), ctx)
    if stype == "csr":
        return CSRNDArray(jnp.zeros((0,), dt), np.zeros((0,), np.int64),
                          np.zeros((shape[0] + 1,), np.int64), tuple(shape), ctx)
    from .ndarray import zeros as _dz
    return _dz(shape, ctx=ctx, dtype=dtype)


def cast_storage(arr, stype):
    """Convert between storage types (parity: mx.nd.cast_storage,
    reference src/operator/tensor/cast_storage.cc, cast_storage-inl.h).
    Compression runs device-side: reductions + one eager nonzero
    (row-major order, which IS the CSR order) + gathers — no Python row
    loop. Sparse<->sparse conversions work on the COMPRESSED
    representation — O(stored_rows * ncols + nnz + nrows), never the
    full dense shape (the 1M-row embedding case, SURVEY §2.3)."""
    if arr.stype == stype:
        return arr
    if stype == "default":
        return _wrap(arr._data, arr.context)
    if isinstance(arr, RowSparseNDArray) and stype == "csr":
        if len(arr.shape) != 2:
            raise MXNetError("csr requires 2-D")
        # compress only the stored block; sort by row id first (user-
        # created rsp indices may be unsorted, CSR requires row order).
        # Duplicate row ids: keep the LAST stored occurrence — the same
        # scatter-set semantics the dense view (_make_dense) has, so the
        # two representations agree. Index work is host-side numpy (the
        # index vector is O(stored rows), tiny next to the value block).
        idx_np = np.asarray(arr._rsp_indices)
        order = np.argsort(idx_np, kind="stable")
        if order.size:
            sorted_ids = idx_np[order]
            last_of_group = np.concatenate(
                [sorted_ids[1:] != sorted_ids[:-1], [True]])
            order = order[last_of_group]
        ridx = jnp.asarray(idx_np[order].astype(np.int32))
        block = arr._rsp_data[jnp.asarray(order.astype(np.int32))]
        mask = block != 0
        counts = jnp.sum(mask, axis=1, dtype=jnp.int32)
        row_counts = jnp.zeros((arr.shape[0],), jnp.int32) \
            .at[ridx].set(counts)
        indptr = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                                  jnp.cumsum(row_counts)])
        r, c = jnp.nonzero(mask)  # eager; row-major == CSR order
        return CSRNDArray(block[r, c], c.astype(jnp.int32), indptr,
                          arr.shape, arr.context)
    if isinstance(arr, CSRNDArray) and stype == "row_sparse":
        counts = jnp.diff(arr._csr_indptr)
        nz_rows = jnp.nonzero(counts > 0)[0]  # eager, already sorted
        rows = arr._row_ids()
        pos = jnp.searchsorted(nz_rows, rows)  # block slot per nnz
        block = jnp.zeros((int(nz_rows.shape[0]),) + tuple(arr.shape[1:]),
                          arr._csr_data.dtype) \
            .at[pos, arr._csr_indices.astype(jnp.int32)] \
            .set(arr._csr_data)
        return RowSparseNDArray(block, nz_rows.astype(jnp.int32),
                                arr.shape, arr.context)
    dense = arr._data
    if stype == "row_sparse":
        nz = jnp.any(dense != 0, axis=tuple(range(1, dense.ndim)))
        nz_rows = jnp.nonzero(nz)[0]
        return RowSparseNDArray(dense[nz_rows],
                                nz_rows.astype(jnp.int32),
                                dense.shape, arr.context)
    if stype == "csr":
        if dense.ndim != 2:
            raise MXNetError("csr requires 2-D")
        mask = dense != 0
        counts = jnp.sum(mask, axis=1)
        indptr = jnp.concatenate([jnp.zeros((1,), counts.dtype),
                                  jnp.cumsum(counts)])
        rows, cols = jnp.nonzero(mask)
        return CSRNDArray(dense[rows, cols], cols.astype(jnp.int32),
                          indptr.astype(jnp.int32), dense.shape,
                          arr.context)
    raise MXNetError("unknown stype %r" % stype)


def add_n(arrays):
    """Sum row_sparse arrays without densifying (parity: reference
    ElementwiseSum's row_sparse path, ndarray.cc:575): index-space union
    on host (indices are tiny), one XLA segment-sum over the stacked
    values — the aggregation kvstore uses for sparse gradient pushes."""
    import jax
    if not arrays:
        raise MXNetError("add_n: empty list")
    if not all(isinstance(a, RowSparseNDArray) for a in arrays):
        raise MXNetError("add_n: all inputs must be row_sparse")
    shape = arrays[0].shape
    idx_list = [np.asarray(a._rsp_indices, np.int64) for a in arrays]
    uniq, inv = np.unique(np.concatenate(idx_list), return_inverse=True)
    data = jnp.concatenate([a._rsp_data for a in arrays], axis=0)
    summed = jax.ops.segment_sum(data, jnp.asarray(inv),
                                 num_segments=len(uniq))
    return RowSparseNDArray(summed, jnp.asarray(uniq), shape,
                            arrays[0].context)


def _csr_merge(lhs, rhs, mode):
    """COO merge of two same-shape CSR matrices on the compressed
    representations: concat -> host lexsort of the (row, col) keys
    (O(nnz) ints; the value merge stays on device) -> segment
    combine -> rebuild indptr. O(nnz) memory, never the dense shape —
    the reference's elemwise FComputeEx kernel role
    (elemwise_binary_op-inl.h csr/csr paths).

    mode "add"/"sub": structural UNION of coordinates (a sum that
    cancels to exact zero stays stored — reference sparse-kernel
    semantics). mode "mul": structural INTERSECTION (a coordinate
    stored on only one side contributes 0 * x and is dropped, which is
    exactly what the reference's csr*csr kernel produces)."""
    if lhs.shape != rhs.shape:
        raise MXNetError("elemwise %s: shape mismatch %s vs %s"
                         % (mode, lhs.shape, rhs.shape))
    r = np.concatenate([np.asarray(lhs._row_ids()),
                        np.asarray(rhs._row_ids())])
    c = np.concatenate([np.asarray(lhs._csr_indices),
                        np.asarray(rhs._csr_indices)])
    rhs_vals = rhs._csr_data if mode != "sub" else -rhs._csr_data
    vals = jnp.concatenate([lhs._csr_data, rhs_vals])
    order = np.lexsort((c, r))
    r, c = r[order], c[order]
    # unique (row, col) keys in CSR order + segment map for the combine
    key_changed = np.empty(len(r), bool)
    key_changed[:1] = True
    if len(r) > 1:
        key_changed[1:] = (r[1:] != r[:-1]) | (c[1:] != c[:-1])
    seg = np.cumsum(key_changed) - 1
    n_seg = int(seg[-1]) + 1 if len(seg) else 0
    vals = vals[jnp.asarray(order)]
    uniq_r, uniq_c = r[key_changed], c[key_changed]
    if mode == "mul":
        # CSR coordinates are unique per matrix, so a segment holds 1 or
        # 2 values; products survive only where BOTH sides stored one
        combined = jax.ops.segment_prod(vals, jnp.asarray(seg),
                                        num_segments=n_seg)
        both = np.bincount(seg, minlength=n_seg) == 2
        combined = combined[jnp.asarray(np.nonzero(both)[0])]
        uniq_r, uniq_c = uniq_r[both], uniq_c[both]
    else:
        combined = jax.ops.segment_sum(vals, jnp.asarray(seg),
                                       num_segments=n_seg)
    row_counts = np.bincount(uniq_r, minlength=lhs.shape[0])
    indptr = np.concatenate([[0], np.cumsum(row_counts)])
    return CSRNDArray(combined, jnp.asarray(uniq_c.astype(np.int32)),
                      jnp.asarray(indptr.astype(np.int32)),
                      lhs.shape, lhs.context)


def _rsp_pair(lhs, rhs, mode):
    """Native (row_sparse, row_sparse) elemwise combine on the stored
    blocks. add/sub: row-id UNION via one segment-sum (reference
    ElemwiseBinaryOp rsp/rsp path); mul: row-id INTERSECTION — rows
    stored on one side only multiply implicit zeros and vanish."""
    if lhs.shape != rhs.shape:
        raise MXNetError("elemwise %s: shape mismatch %s vs %s"
                         % (mode, lhs.shape, rhs.shape))
    if mode == "mul":
        li = np.asarray(lhs._rsp_indices, np.int64)
        ri = np.asarray(rhs._rsp_indices, np.int64)
        common, lpos, rpos = np.intersect1d(li, ri, return_indices=True)
        data = lhs._rsp_data[jnp.asarray(lpos.astype(np.int32))] \
            * rhs._rsp_data[jnp.asarray(rpos.astype(np.int32))]
        return RowSparseNDArray(data, jnp.asarray(common.astype(np.int32)),
                                lhs.shape, lhs.context)
    neg = rhs if mode == "add" else RowSparseNDArray(
        -rhs._rsp_data, rhs._rsp_indices, rhs.shape, rhs._ctx)
    return add_n([lhs, neg])


def _binary_sparse(lhs, rhs, mode, opname):
    """Shared storage-dispatch for elemwise add/sub/mul (reference
    elemwise_binary_op_basic.cc storage tables: csr/csr -> csr,
    rsp/rsp -> rsp, anything else -> dense through the logged storage
    fallback)."""
    if isinstance(lhs, CSRNDArray) and isinstance(rhs, CSRNDArray):
        return _csr_merge(lhs, rhs, mode)
    if isinstance(lhs, RowSparseNDArray) and isinstance(rhs, RowSparseNDArray):
        return _rsp_pair(lhs, rhs, mode)
    from ..config import storage_fallback_log
    storage_fallback_log("%s(%s, %s)" % (opname, lhs.stype, rhs.stype))
    ld = lhs.tostype("default") if isinstance(lhs, BaseSparseNDArray) else lhs
    rd = rhs.tostype("default") if isinstance(rhs, BaseSparseNDArray) else rhs
    # mixed storage combinations produce DEFAULT storage — the
    # reference's documented table ("otherwise ... default storage"),
    # and identical to what the registered-op dispatch route yields
    return {"add": ld.__add__, "sub": ld.__sub__, "mul": ld.__mul__}[mode](rd)


def elemwise_add(lhs, rhs):
    """Sparse elemwise add (reference elemwise_binary_op_basic.cc)."""
    return _binary_sparse(lhs, rhs, "add", "elemwise_add")


def elemwise_sub(lhs, rhs):
    """Sparse elemwise subtract: csr-csr -> csr, rsp-rsp -> rsp, native
    on the compressed representations (reference
    elemwise_binary_op_basic.cc elemwise_sub storage table)."""
    return _binary_sparse(lhs, rhs, "sub", "elemwise_sub")


def elemwise_mul(lhs, rhs):
    """Sparse elemwise multiply: csr*csr -> csr, rsp*rsp -> rsp
    (structural intersection), native on the compressed representations
    (reference elemwise_binary_op_basic.cc elemwise_mul storage table)."""
    return _binary_sparse(lhs, rhs, "mul", "elemwise_mul")


def square(arr):
    """Stype-preserving elementwise square: square(rsp)=rsp,
    square(csr)=csr, operating on the stored values only — f(0)=0, so
    the structure is unchanged (reference elemwise_unary_op_basic.cc
    MXNET_OPERATOR_REGISTER_UNARY_WITH_RSP_CSR(square))."""
    return _map_values(arr, lambda v: v * v)


def _map_values(arr, fn):
    """Apply an f(0)=0 elementwise fn to the stored values, keeping the
    sparse structure (the reference's UnaryOp::ComputeEx / scalar
    ComputeEx shape: `only operates on the data array of the input`)."""
    if isinstance(arr, RowSparseNDArray):
        return RowSparseNDArray(fn(arr._rsp_data), arr._rsp_indices,
                                arr.shape, arr._ctx)
    if isinstance(arr, CSRNDArray):
        return CSRNDArray(fn(arr._csr_data), arr._csr_indices,
                          arr._csr_indptr, arr.shape, arr._ctx)
    return _wrap(fn(arr._data), arr.context)


def square_sum(arr, axis=None, keepdims=False):
    """Sum of squares over a row_sparse array WITHOUT densifying
    (reference _square_sum, src/operator/tensor/square_sum-inl.h — the
    reduction behind lazy-update optimizer norms). Storage table, per
    SquareSumForwardInferStorageType:
      axis=1, keepdims=True  -> row_sparse (per stored row)
      axis=1, keepdims=False -> dense vector (nrows,)
      axis=0                 -> dense vector over columns
    Anything else is unsupported there too."""
    if not isinstance(arr, RowSparseNDArray):
        raise MXNetError("_square_sum: row_sparse input required "
                         "(reference square_sum-inl.h)")
    if isinstance(axis, (tuple, list)):
        if len(axis) != 1:
            raise MXNetError("_square_sum: single-axis reductions only "
                             "(got axis=%r; reference square_sum-inl.h "
                             "supports axis 0 or 1)" % (axis,))
        ax = axis[0]
    else:
        ax = axis
    sq = arr._rsp_data * arr._rsp_data
    nrows = arr.shape[0]
    if ax == 1 and keepdims:
        per_row = jnp.sum(sq, axis=tuple(range(1, sq.ndim)), keepdims=False)
        return RowSparseNDArray(per_row[:, None], arr._rsp_indices,
                                (nrows, 1), arr._ctx)
    if ax == 1:
        per_row = jnp.sum(sq, axis=tuple(range(1, sq.ndim)))
        dense = jnp.zeros((nrows,), per_row.dtype) \
            .at[arr._rsp_indices].set(per_row)
        return _wrap(dense, arr.context)
    if ax == 0:
        out = jnp.sum(sq, axis=0)
        if keepdims:
            out = out[None, ...]
        return _wrap(out, arr.context)
    raise MXNetError("_square_sum: axis must be 0 or 1 (got %r)" % (axis,))


def sum(arr, axis=None, keepdims=False, exclude=False):
    """Reduce a CSR matrix over one axis natively — O(nnz) segment-sum /
    scatter-add, dense output (reference sum(csr, axis) FComputeEx,
    broadcast_reduce_op_value.cc SumOpForwardEx). Other inputs take the
    logged dense fallback."""
    ax = axis[0] if isinstance(axis, (tuple, list)) and len(axis) == 1 \
        else axis
    if isinstance(arr, CSRNDArray) and not exclude and ax in (0, 1):
        nrows, ncols = arr.shape
        if ax == 1:
            out = jax.ops.segment_sum(arr._csr_data, arr._row_ids(),
                                      num_segments=nrows)
            if keepdims:
                out = out[:, None]
        else:
            out = jnp.zeros((ncols,), arr._csr_data.dtype) \
                .at[arr._csr_indices.astype(jnp.int32)].add(arr._csr_data)
            if keepdims:
                out = out[None, :]
        return _wrap(out, arr.context)
    from ..config import storage_fallback_log
    storage_fallback_log("sum(%s, axis=%r)"
                         % (getattr(arr, "stype", "default"), axis))
    from . import sum as _dense_sum
    dense = _wrap(arr._data, arr.context) \
        if isinstance(arr, BaseSparseNDArray) else arr
    return _dense_sum(dense, axis=axis, keepdims=keepdims, exclude=exclude)


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Sparse-aware dot (parity: reference dot-inl.h sparse kernels).

    csr · dense and csr^T · dense (the sparse linear-classification hot
    ops) run NATIVELY on the compressed representation: O(nnz * N)
    gather + segment-sum / scatter-add, never materialising the dense
    lhs. Other sparse combinations fall back to the dense view, the
    reference's storage-fallback behaviour (src/common/utils.h).
    """
    from . import dot as _dense_dot
    if isinstance(lhs, CSRNDArray) and not isinstance(rhs, BaseSparseNDArray) \
            and not transpose_b and rhs._data.ndim == 2:
        rows = lhs._row_ids()
        cols = lhs._csr_indices.astype(jnp.int32)
        vals = lhs._csr_data
        r = rhs._data
        # explicit inner-dim check: JAX clamps out-of-bounds gathers
        # instead of raising, which would return plausible garbage
        inner = lhs.shape[0] if transpose_a else lhs.shape[1]
        if r.shape[0] != inner:
            raise MXNetError("dot: shape mismatch %s x %s (transpose_a=%s)"
                             % (lhs.shape, tuple(r.shape), transpose_a))
        nrows, ncols = lhs.shape

        def _pure(rr):
            if not transpose_a:
                # out[i] = sum_k csr[i, k] * rhs[k] -> segment-sum on rows
                prod = vals[:, None] * rr[cols]
                return (jax.ops.segment_sum(prod, rows,
                                            num_segments=nrows),)
            # out[k] += csr[i, k] * rhs[i] -> scatter-add over columns
            prod = vals[:, None] * rr[rows]
            return (jnp.zeros((ncols, rr.shape[1]), prod.dtype)
                    .at[cols].add(prod),)

        # grad w.r.t. the DENSE rhs stays O(nnz * N): jax.vjp of the
        # gather/segment-sum formulation is the transposed scatter —
        # the reference's dot backward pair (dot-inl.h csr.T kernels).
        # Grad w.r.t. the csr lhs is not produced (reference parity).
        from .. import imperative as _imp
        if (_imp.is_recording()
                and getattr(rhs, "_tape", None) is not None):
            (out,), vjp_fn = jax.vjp(_pure, r)
            node = _imp.TapeNode(
                [rhs._tape], vjp_fn,
                [jax.ShapeDtypeStruct(out.shape, out.dtype)], "sparse_dot")
            node.pure_fn = _pure
            node.raw_inputs = [None]
            res = _wrap(out, lhs.context)
            res._tape = (node, 0)
            return res
        return _wrap(_pure(r)[0], lhs.context)
    if isinstance(lhs, BaseSparseNDArray) or isinstance(rhs, BaseSparseNDArray):
        from ..config import storage_fallback_log
        storage_fallback_log("dot(%s, %s)" % (getattr(lhs, "stype", "default"),
                                              getattr(rhs, "stype", "default")))
    return _dense_dot(_wrap(lhs._data, lhs.context) if isinstance(lhs, BaseSparseNDArray) else lhs,
                      _wrap(rhs._data, rhs.context) if isinstance(rhs, BaseSparseNDArray) else rhs,
                      transpose_a=transpose_a, transpose_b=transpose_b)


#: op name -> union/intersection mode for the binary FComputeEx table.
#: broadcast_* entries serve the NDArray dunders, whose same-shape
#: sparse case IS the elemwise op (reference FInferStorageType routes
#: identically).
_BINARY_EX = {"elemwise_add": "add", "broadcast_add": "add",
              "_grad_add": "add",
              "elemwise_sub": "sub", "broadcast_sub": "sub",
              "elemwise_mul": "mul", "broadcast_mul": "mul"}


def dispatch_ex(op_name, inputs, params):
    """Storage-aware kernel dispatch — the reference's FInferStorageType
    + FComputeEx pair (operator registry attrs, e.g.
    elemwise_binary_op_basic.cc) collapsed into one table lookup.
    ``imperative.invoke`` consults this before touching any input's
    dense view; NotImplemented means "no native kernel for this storage
    combination" and the caller takes the logged dense fallback, exactly
    the reference's dispatch-mode machinery (src/common/utils.h)."""
    mode = _BINARY_EX.get(op_name)
    if mode is not None and len(inputs) == 2:
        l, r = inputs
        if (isinstance(l, CSRNDArray) and isinstance(r, CSRNDArray)
                and l.shape == r.shape):
            return _csr_merge(l, r, mode)
        if (isinstance(l, RowSparseNDArray)
                and isinstance(r, RowSparseNDArray) and l.shape == r.shape):
            return _rsp_pair(l, r, mode)
        return NotImplemented
    if len(inputs) != 1 or not isinstance(inputs[0], BaseSparseNDArray):
        return NotImplemented
    arr = inputs[0]
    if op_name == "square":
        return square(arr)
    if op_name == "negative":
        return _map_values(arr, lambda v: -v)
    if op_name == "_mul_scalar":
        s = params.get("scalar", 1.0)
        return _map_values(arr, lambda v: v * s)
    if op_name == "_div_scalar":
        s = params.get("scalar", 1.0)
        return _map_values(arr, lambda v: v / s)
    if op_name == "sum" and isinstance(arr, CSRNDArray):
        ax = params.get("axis")
        axn = ax[0] if isinstance(ax, (tuple, list)) and len(ax) == 1 else ax
        if not params.get("exclude", False) and axn in (0, 1):
            return sum(arr, axis=ax, keepdims=params.get("keepdims", False))
        return NotImplemented
    if op_name == "_square_sum" and isinstance(arr, RowSparseNDArray):
        ax = params.get("axis")
        axn = ax[0] if isinstance(ax, (tuple, list)) and len(ax) == 1 else ax
        if axn in (0, 1):
            return square_sum(arr, axis=ax,
                              keepdims=params.get("keepdims", False))
    return NotImplemented


def empty(stype, shape, ctx=None, dtype=None):
    """Sparse-aware empty (parity: sparse.empty — zeros-backed like the
    dense path; XLA has no uninitialised buffers)."""
    return zeros(stype, shape, ctx=ctx, dtype=dtype)


def array(source_array, ctx=None, dtype=None):
    """Build a sparse NDArray from sparse input — a sparse NDArray or a
    scipy.sparse matrix (parity: sparse.array, which accepts exactly
    these and rejects dense input)."""
    import numpy as _np
    if isinstance(source_array, BaseSparseNDArray):
        out = cast_storage(source_array.tostype("default"),
                           source_array.stype)
        if dtype is not None:
            out = out.astype(dtype)
        return out
    try:
        import scipy.sparse as _sps
        is_scipy = _sps.issparse(source_array)
    except ImportError:
        is_scipy = False
    if is_scipy:
        csr = source_array.tocsr()
        data = _np.asarray(csr.data, dtype or csr.dtype)
        return csr_matrix((data, _np.asarray(csr.indices),
                           _np.asarray(csr.indptr)), shape=csr.shape,
                          ctx=ctx)
    raise MXNetError(
        "sparse.array expects a sparse NDArray or scipy.sparse matrix; "
        "use mx.nd.array / csr_matrix / row_sparse_array for dense input "
        "(the reference rejects dense input here too)")
