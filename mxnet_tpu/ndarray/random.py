"""``mx.nd.random`` namespace (parity: python/mxnet/ndarray/random.py)."""
from __future__ import annotations

from .. import imperative as _imp
from ..ops import registry as _registry


def _call(name, kwargs):
    kwargs = {k: v for k, v in kwargs.items() if v is not None}
    out = kwargs.pop("out", None)
    return _imp.invoke(_registry.get_op(name), [], kwargs, out=out)


def uniform(low=0, high=1, shape=(), dtype="float32", ctx=None, out=None, **kw):
    return _call("_random_uniform", dict(low=low, high=high, shape=shape,
                                         dtype=dtype, out=out))


def normal(loc=0, scale=1, shape=(), dtype="float32", ctx=None, out=None, **kw):
    return _call("_random_normal", dict(loc=loc, scale=scale, shape=shape,
                                        dtype=dtype, out=out))


def gamma(alpha=1, beta=1, shape=(), dtype="float32", ctx=None, out=None, **kw):
    return _call("_random_gamma", dict(alpha=alpha, beta=beta, shape=shape,
                                       dtype=dtype, out=out))


def exponential(lam=1, shape=(), dtype="float32", ctx=None, out=None, **kw):
    return _call("_random_exponential", dict(lam=lam, shape=shape, dtype=dtype,
                                             out=out))


def poisson(lam=1, shape=(), dtype="float32", ctx=None, out=None, **kw):
    return _call("_random_poisson", dict(lam=lam, shape=shape, dtype=dtype,
                                         out=out))


def negative_binomial(k=1, p=1, shape=(), dtype="float32", ctx=None, out=None,
                      **kw):
    return _call("_random_negative_binomial",
                 dict(k=k, p=p, shape=shape, dtype=dtype, out=out))


def generalized_negative_binomial(mu=1, alpha=1, shape=(), dtype="float32",
                                  ctx=None, out=None, **kw):
    return _call("_random_generalized_negative_binomial",
                 dict(mu=mu, alpha=alpha, shape=shape, dtype=dtype, out=out))


def multinomial(data, shape=(), get_prob=False, out=None, dtype="int32", **kw):
    from .. import imperative as imp
    return imp.invoke(_registry.get_op("_sample_multinomial"), [data],
                      dict(shape=shape, get_prob=get_prob, dtype=dtype), out=out)


def shuffle(data, out=None, **kw):
    return _imp.invoke(_registry.get_op("_shuffle"), [data], {}, out=out)
