"""NDArray serialization: save/load.

Parity: reference ``python/mxnet/ndarray/utils.py:149-185`` and the C
``MXNDArraySave/Load`` (``c_api.h:358-371``). Format: NPZ container
(name->array), a TPU-native replacement for the dmlc::Stream binary blob —
same semantics (dict or list of arrays round-trips), portable, and
mmap-friendly for host-side loading before device_put.
"""
from __future__ import annotations

import os
import zipfile

import numpy as np

from ..base import MXNetError
from .ndarray import NDArray, array

_LIST_PREFIX = "__mx_list__:"


def save(fname, data):
    """Save a list or dict of NDArrays (parity: mx.nd.save)."""
    if isinstance(data, NDArray):
        data = [data]
    arrays = {}
    if isinstance(data, dict):
        for k, v in data.items():
            if not isinstance(v, NDArray):
                raise MXNetError("save: values must be NDArrays")
            arrays[k] = v.asnumpy()
    elif isinstance(data, (list, tuple)):
        for i, v in enumerate(data):
            if not isinstance(v, NDArray):
                raise MXNetError("save: values must be NDArrays")
            arrays[_LIST_PREFIX + str(i)] = v.asnumpy()
    else:
        raise MXNetError("save: data must be NDArray, list, or dict")
    np.savez(fname if fname.endswith(".npz") else fname, **arrays)
    # np.savez appends .npz; rename back for exact-path semantics
    if not fname.endswith(".npz") and os.path.exists(fname + ".npz"):
        os.replace(fname + ".npz", fname)


def load(fname):
    """Load NDArrays saved by :func:`save` (parity: mx.nd.load)."""
    if not os.path.exists(fname):
        raise MXNetError("load: no such file %r" % fname)
    with np.load(fname, allow_pickle=False) as npz:
        keys = list(npz.keys())
        if keys and all(k.startswith(_LIST_PREFIX) for k in keys):
            items = sorted(keys, key=lambda k: int(k[len(_LIST_PREFIX):]))
            return [array(npz[k]) for k in items]
        return {k: array(npz[k]) for k in keys}
