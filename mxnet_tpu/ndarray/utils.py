"""NDArray serialization: save/load.

Parity: reference ``python/mxnet/ndarray/utils.py:149-185`` and the C
``MXNDArraySave/Load`` (``c_api.h:358-371``). Format: NPZ container
(name->array), a TPU-native replacement for the dmlc::Stream binary blob —
same semantics (dict or list of arrays round-trips), portable, and
mmap-friendly for host-side loading before device_put.
"""
from __future__ import annotations

import os
import zipfile

import numpy as np

from ..base import MXNetError
from ..filesystem import open_uri, scheme_of
from .ndarray import NDArray, array

_LIST_PREFIX = "__mx_list__:"


def save(fname, data):
    """Save a list or dict of NDArrays (parity: mx.nd.save)."""
    if isinstance(data, NDArray):
        data = [data]
    arrays = {}
    if isinstance(data, dict):
        for k, v in data.items():
            if not isinstance(v, NDArray):
                raise MXNetError("save: values must be NDArrays")
            arrays[k] = v.asnumpy()
    elif isinstance(data, (list, tuple)):
        for i, v in enumerate(data):
            if not isinstance(v, NDArray):
                raise MXNetError("save: values must be NDArrays")
            arrays[_LIST_PREFIX + str(i)] = v.asnumpy()
    else:
        raise MXNetError("save: data must be NDArray, list, or dict")
    # URI-aware stream (parity: dmlc Stream::Create — the reference
    # saves through S3/HDFS-capable streams, ndarray/utils.py:149-185)
    with open_uri(fname, "wb") as f:
        np.savez(f, **arrays)


def load(fname):
    """Load NDArrays saved by :func:`save` (parity: mx.nd.load)."""
    import io as _io
    try:
        f = open_uri(fname, "rb")
    except FileNotFoundError:
        raise MXNetError("load: no such file %r" % fname)
    with f:
        # seekable handles (local files) stream straight into np.load;
        # only non-seekable registered-scheme streams get buffered
        src = f if f.seekable() else _io.BytesIO(f.read())
        return _load_npz(src)


def load_frombuffer(buf):
    """Load NDArrays from an in-memory save blob (parity:
    mx.nd.load_frombuffer / C MXNDArrayLoadFromBuffer — the path the
    predict ABI's MXNDListCreate uses)."""
    import io as _io
    return _load_npz(_io.BytesIO(bytes(buf)))


def _load_npz(src):
    with np.load(src, allow_pickle=False) as npz:
        keys = list(npz.keys())
        if keys and all(k.startswith(_LIST_PREFIX) for k in keys):
            items = sorted(keys,
                           key=lambda k: int(k[len(_LIST_PREFIX):]))
            return [array(npz[k]) for k in items]
        return {k: array(npz[k]) for k in keys}
