"""``mx.nd.linalg`` namespace (parity: python/mxnet/ndarray/linalg.py)."""
from __future__ import annotations

from .. import imperative as _imp
from ..ops import registry as _registry


def _make(name, opname):
    def fn(*args, **kwargs):
        out = kwargs.pop("out", None)
        return _imp.invoke(_registry.get_op(opname), list(args), kwargs, out=out)
    fn.__name__ = name
    return fn


gemm = _make("gemm", "_linalg_gemm")
gemm2 = _make("gemm2", "_linalg_gemm2")
potrf = _make("potrf", "_linalg_potrf")
potri = _make("potri", "_linalg_potri")
trmm = _make("trmm", "_linalg_trmm")
trsm = _make("trsm", "_linalg_trsm")
sumlogdiag = _make("sumlogdiag", "_linalg_sumlogdiag")
syrk = _make("syrk", "_linalg_syrk")
