"""Code-generation of the ``nd.*`` operator namespace from the registry.

Parity: reference ``python/mxnet/ndarray/register.py:142-168`` which
generates a Python function per C-registered op at import time. Here the
registry is Python (ops/registry.py) so generation is direct; signatures
accept tensor args positionally or by their reference kwarg names
(``data=``, ``weight=`` …), plus ``out=`` like the reference.
"""
from __future__ import annotations

from .. import imperative as _imp
from ..ops import registry as _registry


def make_op_func(op):
    arg_names = op.arg_names

    param_order = list(op.defaults)

    def generic_op(*args, **kwargs):
        out = kwargs.pop("out", None)
        from .ndarray import NDArray
        # leading NDArray positionals are tensor inputs; trailing positional
        # values map onto the op's params in declaration order (matching the
        # reference's generated signatures, e.g. nd.clip(x, 0.0, 1.0)).
        inputs = []
        i = 0
        while i < len(args) and isinstance(args[i], NDArray):
            inputs.append(args[i])
            i += 1
        for j, val in enumerate(args[i:]):
            if j < len(param_order):
                kwargs.setdefault(param_order[j], val)
        if op.nin == -1:
            kwargs.pop("num_args", None)
        else:
            # named tensor args may come via kwargs
            if len(inputs) < len(arg_names):
                for name in arg_names[len(inputs):]:
                    if name in kwargs and isinstance(kwargs[name], NDArray):
                        inputs.append(kwargs.pop(name))
                    else:
                        break
        return _imp.invoke(op, inputs, kwargs, out=out)

    generic_op.__name__ = op.name
    generic_op.__doc__ = op.doc or ("%s operator (see reference MXNet %s)" %
                                    (op.name, op.name))
    return generic_op


def populate(namespace, include_internal=True):
    """Install one function per registered op into ``namespace``."""
    for name in _registry.list_ops():
        op = _registry.get_op(name)
        if not include_internal and name.startswith("_"):
            continue
        namespace[name] = make_op_func(op)
    return namespace
