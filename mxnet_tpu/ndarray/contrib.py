"""``mx.nd.contrib`` namespace (parity: python/mxnet/ndarray/contrib.py):
exposes ops registered with the ``_contrib_`` prefix under short names."""
from __future__ import annotations

from ..ops import registry as _registry
from . import register as _register

for _name in _registry.list_ops():
    if _name.startswith("_contrib_"):
        _op = _registry.get_op(_name)
        globals()[_name[len("_contrib_"):]] = _register.make_op_func(_op)
        globals()[_name] = _register.make_op_func(_op)
