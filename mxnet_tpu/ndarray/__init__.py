"""The ``mx.nd`` namespace: NDArray + generated operator functions.

Parity: reference ``python/mxnet/ndarray/__init__.py``.
"""
from .ndarray import (NDArray, array, zeros, ones, full, empty, arange,
                      concatenate, moveaxis, waitall, onehot_encode)
from .utils import save, load, load_frombuffer
from . import register as _register

# code-gen every registered op into this module (mx.nd.dot, mx.nd.Convolution…)
_register.populate(globals())

from . import random   # noqa: E402,F401
from . import linalg   # noqa: E402,F401
from . import sparse   # noqa: E402,F401
from .sparse import RowSparseNDArray, CSRNDArray  # noqa: E402,F401

from . import contrib  # noqa: E402,F401
