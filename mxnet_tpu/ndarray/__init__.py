"""The ``mx.nd`` namespace: NDArray + generated operator functions.

Parity: reference ``python/mxnet/ndarray/__init__.py``.
"""
from .ndarray import (NDArray, array, zeros, ones, full, empty, arange,
                      concatenate, moveaxis, waitall, onehot_encode)
from .utils import save, load, load_frombuffer
from . import register as _register

# code-gen every registered op into this module (mx.nd.dot, mx.nd.Convolution…)
_register.populate(globals())

from . import random   # noqa: E402,F401
from . import linalg   # noqa: E402,F401
from . import sparse   # noqa: E402,F401
from .sparse import RowSparseNDArray, CSRNDArray  # noqa: E402,F401
# top-level aliases the reference exposes as nnvm ops (mx.nd.cast_storage)
from .sparse import cast_storage  # noqa: E402,F401

from . import contrib  # noqa: E402,F401


# module-level arithmetic helpers (parity: ndarray.py:2743-3103 — the
# reference exposes operator-overload semantics as named functions that
# also accept scalar/scalar and scalar/array operands)
def _binary(name, arr_fn, np_fn):
    import numpy as _np

    def fn(lhs, rhs):
        lhs_nd = isinstance(lhs, NDArray)
        rhs_nd = isinstance(rhs, NDArray)
        if lhs_nd and rhs_nd:
            return arr_fn(lhs, rhs)
        if lhs_nd:
            return arr_fn(lhs, rhs)
        if rhs_nd:
            return arr_fn(array(_np.full(rhs.shape, lhs, _np.float32)), rhs)
        return np_fn(lhs, rhs)
    fn.__name__ = name
    fn.__doc__ = "(parity: mx.nd.%s)" % name
    return fn


import numpy as _np                                   # noqa: E402
add = _binary("add", lambda a, b: a + b, _np.add)
subtract = _binary("subtract", lambda a, b: a - b, _np.subtract)
multiply = _binary("multiply", lambda a, b: a * b, _np.multiply)
divide = _binary("divide", lambda a, b: a / b, _np.divide)
true_divide = divide
modulo = _binary("modulo", lambda a, b: a % b, _np.mod)
power = _binary("power", lambda a, b: a ** b, _np.power)
maximum = _binary("maximum", lambda a, b: broadcast_maximum(a, b)
                  if isinstance(b, NDArray) else _maximum_scalar(a, scalar=b),
                  _np.maximum)
minimum = _binary("minimum", lambda a, b: broadcast_minimum(a, b)
                  if isinstance(b, NDArray) else _minimum_scalar(a, scalar=b),
                  _np.minimum)
equal = _binary("equal", lambda a, b: a == b, lambda a, b: float(a == b))
not_equal = _binary("not_equal", lambda a, b: a != b,
                    lambda a, b: float(a != b))
greater = _binary("greater", lambda a, b: a > b, lambda a, b: float(a > b))
greater_equal = _binary("greater_equal", lambda a, b: a >= b,
                        lambda a, b: float(a >= b))
lesser = _binary("lesser", lambda a, b: a < b, lambda a, b: float(a < b))
lesser_equal = _binary("lesser_equal", lambda a, b: a <= b,
                       lambda a, b: float(a <= b))


def imdecode(str_img, clip_rect=(0, 0, 0, 0), out=None, index=0, channels=3,
             mean=None):
    """Decode an image bytestring (parity: mx.nd.imdecode)."""
    from ..image import image as _img
    return _img.imdecode(str_img, flag=1 if channels == 3 else 0)


# fluent methods: x.relu() == mx.nd.relu(x) — generated from the op
# namespace exactly like the reference attaches op wrappers to NDArray
# (ndarray.py fluent-method block)
_FLUENT_METHODS = [
    "reshape_like", "zeros_like", "ones_like", "broadcast_axes", "repeat",
    "pad", "split", "slice", "take", "one_hot", "pick", "sort", "topk",
    "argsort", "argmax_channel", "flip", "nansum", "nanprod", "rint",
    "fix", "floor", "ceil", "trunc", "sin", "cos", "tan", "arcsin",
    "arccos", "arctan", "degrees", "radians", "sinh", "cosh", "tanh",
    "arcsinh", "arccosh", "arctanh", "expm1", "log10", "log2", "log1p",
    "rsqrt", "cbrt", "rcbrt", "reciprocal", "relu", "sigmoid", "softmax",
    "log_softmax", "swapaxes", "argmax", "argmin", "clip", "abs", "sign",
    "expand_dims", "broadcast_to", "tile", "prod", "max", "min", "norm",
    "round", "exp", "log", "sqrt", "square", "flatten",
]


def _attach_fluent(cls, ns, names):
    def make(op_name, fn):
        def method(self, *args, **kwargs):
            return fn(self, *args, **kwargs)
        method.__name__ = op_name
        method.__doc__ = "Fluent form of %s(self, ...)" % op_name
        return method
    for op_name in names:
        if op_name in ns and not hasattr(cls, op_name):
            setattr(cls, op_name, make(op_name, ns[op_name]))


_attach_fluent(NDArray, globals(), _FLUENT_METHODS)
