"""NDArray — the imperative tensor.

Parity: reference ``include/mxnet/ndarray.h`` + ``src/ndarray/ndarray.cc``
and ``python/mxnet/ndarray/ndarray.py``. TPU-native design: an NDArray
wraps a ``jax.Array`` living in HBM (or host memory for cpu contexts).
The reference's engine-variable/versioning machinery is unnecessary —
PJRT dispatch is already async and ordered, so:

* every op call returns immediately with a future-backed buffer
  (reference: Engine::PushAsync);
* ``wait_to_read`` / ``asnumpy`` are ``block_until_ready`` sync points
  (reference: WaitToRead, ndarray.h:340-348);
* in-place mutation (``+=``, sliced assignment, optimizer updates)
  rebinds the wrapped buffer — functionally pure underneath, mutable at
  the API, which keeps the reference's aliasing semantics without its
  RAW/WAR tracking.
"""
from __future__ import annotations

import numbers

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError
from ..context import Context, current_context, cpu
from ..ops import registry as _registry
from ..ops.common import mx_dtype
from .. import imperative as _imp
from .. import telemetry

__all__ = ["NDArray", "array", "zeros", "ones", "full", "empty", "arange",
           "concatenate", "moveaxis", "waitall", "imresize", "onehot_encode"]


class NDArray:
    """Multi-dimensional, asynchronously-evaluated array on a device."""

    # _fresh_grad backs MXNDArray{Set,Get}GradState on the C ABI
    __slots__ = ("_data", "_ctx", "_grad", "_tape", "_stype", "_fresh_grad",
                 "__weakref__")

    __array_priority__ = 100.0  # beat numpy in mixed expressions

    def __init__(self, data, ctx=None):
        if ctx is None:
            ctx = current_context()
        self._ctx = ctx if isinstance(ctx, Context) else Context(ctx)
        self._data = data
        self._grad = None
        self._tape = None
        self._stype = "default"
        # live device-buffer ledger: charge this wrapper's bytes to its
        # context until the NDArray is collected (telemetry holds only
        # a weakref.finalize — no reference cycle). Views (_SliceView)
        # skip __init__ and alias the parent, so they are not charged;
        # wrappers sharing one buffer (detach) each count — the ledger
        # is the FRAMEWORK's upper-bound view, reconciled against PJRT
        # by Storage.ledger_report(). Traced (abstract) payloads are
        # SKIPPED: wrappers built under a jax trace (the gluon
        # run_block path) would otherwise charge one phantom buffer
        # per COMPILE — sized from the tracer's aval — and pin a
        # finalizer on the tracer (found by mxlint trace-purity).
        # mxlint: disable=trace-purity -- tracer-guarded: traced payloads take the early exit, nothing below runs under the tracer
        if telemetry.enabled() and not isinstance(data, jax.core.Tracer):
            try:
                nbytes = int(data.size) * data.dtype.itemsize
                shape, dtype = data.shape, data.dtype
            except AttributeError:
                nbytes, shape, dtype = 0, None, None
            # mxlint: disable=trace-purity -- tracer-guarded above; also cuts the trace cone out of the ledger internals
            telemetry.ledger_track(self, str(self._ctx), nbytes,
                                   shape=shape, dtype=dtype)

    # -- internal ----------------------------------------------------------
    def _set_data(self, raw):
        self._data = raw

    @property
    def data_(self):
        return self._data

    # -- basic properties --------------------------------------------------
    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return np.dtype(self._data.dtype) if self._data.dtype != jnp.bfloat16 \
            else self._data.dtype

    @property
    def size(self):
        return int(np.prod(self.shape)) if self.shape else 1

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def context(self):
        return self._ctx

    ctx = context

    @property
    def stype(self):
        return self._stype

    @property
    def grad(self):
        return self._grad

    @property
    def T(self):
        return transpose(self)

    # -- sync points -------------------------------------------------------
    def wait_to_read(self):
        """Block until the value is computed (parity: NDArray::WaitToRead)."""
        telemetry.record_host_sync("wait_to_read")
        jax.block_until_ready(self._data)

    wait_to_write = wait_to_read

    def asnumpy(self):
        """Copy to a numpy array; synchronises (parity: ndarray.py asnumpy)."""
        telemetry.record_host_sync("asnumpy")
        telemetry.record_transfer(self._data.size * self._data.dtype.itemsize,
                                  direction="d2h")
        out = np.asarray(jax.device_get(self._data))
        return out

    def asscalar(self):
        if self.size != 1:
            raise MXNetError("the array is not scalar-sized")
        return self.asnumpy().reshape(())[()]

    def item(self):
        return self.asscalar()

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __bool__(self):
        if self.size != 1:
            raise MXNetError("truth value of multi-element NDArray is ambiguous")
        return bool(self.asscalar())

    def __len__(self):
        if not self.shape:
            raise MXNetError("len() of 0-d array")
        return self.shape[0]

    def __array__(self, dtype=None):
        out = self.asnumpy()
        return out.astype(dtype) if dtype is not None else out

    # -- conversion / movement --------------------------------------------
    def astype(self, dtype, copy=True):
        dt = mx_dtype(dtype)
        if not copy and np.dtype(self._data.dtype) == np.dtype(dt):
            return self
        return _wrap(self._data.astype(dt), self._ctx)

    def copy(self):
        return _wrap(jnp.copy(self._data), self._ctx)

    def copyto(self, other):
        """Copy into another NDArray or to a Context (parity: CopyFromTo,
        reference ndarray.cc:514-571 — PJRT issues the D2D/H2D transfer
        asynchronously)."""
        if isinstance(other, Context):
            dev = other.jax_device()
            return _wrap(jax.device_put(self._data, dev), other)
        if isinstance(other, NDArray):
            # a destination committed to a multi-device sharding (mesh-DP
            # Module state) keeps that sharding — the reference's CopyFromTo
            # also copies into the destination's existing placement
            target = _multi_device_sharding(other._data) \
                or other._ctx.jax_device()
            other._set_data(jax.device_put(self._data, target)
                            .astype(other._data.dtype))
            return other
        raise TypeError("copyto: expected NDArray or Context")

    def as_in_context(self, ctx):
        if ctx == self._ctx:
            return self
        return self.copyto(ctx)

    def detach(self):
        out = _wrap(self._data, self._ctx)
        return out

    # -- autograd ----------------------------------------------------------
    def attach_grad(self, grad_req="write", stype=None):
        """Allocate a gradient buffer (parity: gluon Parameter/autograd)."""
        grad = _wrap(jnp.zeros(self.shape, self._data.dtype), self._ctx)
        _imp.mark_variables([self], [grad], [grad_req])

    def backward(self, out_grad=None, retain_graph=False, train_mode=True):
        _imp.backward([self], [out_grad], retain_graph=retain_graph,
                      train_mode=train_mode)

    # -- shape ops (delegate to registered operators) ----------------------
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        shape = kwargs.get("shape", shape)
        return _invoke("Reshape", [self], {"shape": shape,
                                           "reverse": kwargs.get("reverse", False)})

    def flatten(self):
        return _invoke("Flatten", [self], {})

    def expand_dims(self, axis):
        return _invoke("expand_dims", [self], {"axis": axis})

    def squeeze(self, axis=None):
        return _invoke("squeeze", [self], {"axis": axis})

    def transpose(self, *axes):
        if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = tuple(axes[0])
        return _invoke("transpose", [self], {"axes": axes})

    def swapaxes(self, dim1, dim2):
        return _invoke("SwapAxis", [self], {"dim1": dim1, "dim2": dim2})

    def broadcast_to(self, shape):
        return _invoke("broadcast_to", [self], {"shape": shape})

    def tile(self, reps):
        return _invoke("tile", [self], {"reps": reps})

    def slice_axis(self, axis, begin, end):
        return _invoke("slice_axis", [self], {"axis": axis, "begin": begin,
                                              "end": end})

    # reductions
    def sum(self, axis=None, keepdims=False, **kw):
        return _invoke("sum", [self], {"axis": axis, "keepdims": keepdims})

    def mean(self, axis=None, keepdims=False, **kw):
        return _invoke("mean", [self], {"axis": axis, "keepdims": keepdims})

    def max(self, axis=None, keepdims=False, **kw):
        return _invoke("max", [self], {"axis": axis, "keepdims": keepdims})

    def min(self, axis=None, keepdims=False, **kw):
        return _invoke("min", [self], {"axis": axis, "keepdims": keepdims})

    def prod(self, axis=None, keepdims=False, **kw):
        return _invoke("prod", [self], {"axis": axis, "keepdims": keepdims})

    def argmax(self, axis=None, keepdims=False):
        return _invoke("argmax", [self], {"axis": axis, "keepdims": keepdims})

    def argmin(self, axis=None, keepdims=False):
        return _invoke("argmin", [self], {"axis": axis, "keepdims": keepdims})

    def norm(self):
        return _invoke("norm", [self], {})

    def abs(self):
        return _invoke("abs", [self], {})

    def sqrt(self):
        return _invoke("sqrt", [self], {})

    def square(self):
        return _invoke("square", [self], {})

    def sign(self):
        return _invoke("sign", [self], {})

    def clip(self, a_min, a_max):
        return _invoke("clip", [self], {"a_min": a_min, "a_max": a_max})

    def round(self):
        return _invoke("round", [self], {})

    def log(self):
        return _invoke("log", [self], {})

    def exp(self):
        return _invoke("exp", [self], {})

    def tostype(self, stype):
        if stype == "default":
            return self
        from . import sparse as _sp
        return _sp.cast_storage(self, stype)

    # -- arithmetic --------------------------------------------------------
    def _binary(self, other, op, scalar_op, reverse=False):
        if isinstance(other, NDArray):
            args = [other, self] if reverse else [self, other]
            return _invoke(op, args, {})
        if isinstance(other, numbers.Number):
            name = scalar_op if not reverse else scalar_op.replace("_", "_r", 1) \
                if not scalar_op.startswith("_r") else scalar_op
            return _invoke(name, [self], {"scalar": other})
        return NotImplemented

    def __add__(self, other):
        return self._binary(other, "broadcast_add", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, other):
        return self._binary(other, "broadcast_sub", "_minus_scalar")

    def __rsub__(self, other):
        if isinstance(other, numbers.Number):
            return _invoke("_rminus_scalar", [self], {"scalar": other})
        return NotImplemented

    def __mul__(self, other):
        return self._binary(other, "broadcast_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self._binary(other, "broadcast_div", "_div_scalar")

    def __rtruediv__(self, other):
        if isinstance(other, numbers.Number):
            return _invoke("_rdiv_scalar", [self], {"scalar": other})
        return NotImplemented

    __div__ = __truediv__
    __rdiv__ = __rtruediv__

    def __mod__(self, other):
        return self._binary(other, "broadcast_mod", "_mod_scalar")

    def __rmod__(self, other):
        if isinstance(other, numbers.Number):
            return _invoke("_rmod_scalar", [self], {"scalar": other})
        return NotImplemented

    def __pow__(self, other):
        return self._binary(other, "broadcast_power", "_power_scalar")

    def __rpow__(self, other):
        if isinstance(other, numbers.Number):
            return _invoke("_rpower_scalar", [self], {"scalar": other})
        return NotImplemented

    def __neg__(self):
        return _invoke("negative", [self], {})

    def __abs__(self):
        return _invoke("abs", [self], {})

    def __eq__(self, other):
        if isinstance(other, NDArray):
            return _invoke("broadcast_equal", [self, other], {})
        if isinstance(other, numbers.Number):
            return _invoke("_equal_scalar", [self], {"scalar": other})
        return NotImplemented

    def __ne__(self, other):
        if isinstance(other, NDArray):
            return _invoke("broadcast_not_equal", [self, other], {})
        if isinstance(other, numbers.Number):
            return _invoke("_not_equal_scalar", [self], {"scalar": other})
        return NotImplemented

    def __gt__(self, other):
        if isinstance(other, NDArray):
            return _invoke("broadcast_greater", [self, other], {})
        return _invoke("_greater_scalar", [self], {"scalar": other})

    def __ge__(self, other):
        if isinstance(other, NDArray):
            return _invoke("broadcast_greater_equal", [self, other], {})
        return _invoke("_greater_equal_scalar", [self], {"scalar": other})

    def __lt__(self, other):
        if isinstance(other, NDArray):
            return _invoke("broadcast_lesser", [self, other], {})
        return _invoke("_lesser_scalar", [self], {"scalar": other})

    def __le__(self, other):
        if isinstance(other, NDArray):
            return _invoke("broadcast_lesser_equal", [self, other], {})
        return _invoke("_lesser_equal_scalar", [self], {"scalar": other})

    def __hash__(self):
        return id(self)

    # in-place: rebind the buffer (functional underneath)
    def __iadd__(self, other):
        res = self.__add__(other)
        self._set_data(res._data)
        self._tape = res._tape
        return self

    def __isub__(self, other):
        res = self.__sub__(other)
        self._set_data(res._data)
        self._tape = res._tape
        return self

    def __imul__(self, other):
        res = self.__mul__(other)
        self._set_data(res._data)
        self._tape = res._tape
        return self

    def __itruediv__(self, other):
        res = self.__truediv__(other)
        self._set_data(res._data)
        self._tape = res._tape
        return self

    __idiv__ = __itruediv__

    # -- indexing ----------------------------------------------------------
    def __getitem__(self, key):
        # basic axis-0 indexing returns a WRITE-THROUGH VIEW of the
        # parent (reference NDArray.__getitem__ aliases via
        # MXNDArraySlice/_at; `a[1:3][:] = x` must mutate `a`).
        # Advanced/tuple indexing copies, like the reference. bool is
        # mask indexing, NOT row 0/1, so it must not match the int path.
        if isinstance(key, (int, np.integer)) \
                and not isinstance(key, (bool, np.bool_)):
            idx = int(key)
            n = self.shape[0] if self.ndim else 0
            if idx < -n or idx >= n:
                # eager bounds check: .at[oob].set silently drops writes
                # and sequence-protocol iteration relies on IndexError
                raise IndexError("index %d is out of bounds for axis 0 "
                                 "with size %d" % (idx, n))
            return _SliceView(self, idx % n if n else idx)
        if isinstance(key, slice) and key.step in (None, 1):
            return _SliceView(self, key)
        # advanced indexing under autograd must stay on the tape: route
        # through the registered gather ops (reference: a[i, j] and
        # fancy indexing are differentiable gathers)
        from ..imperative import is_recording
        if is_recording():
            if isinstance(key, NDArray):
                # mode="wrap" preserves eager negative-index semantics
                return _invoke("take", [self, key],
                               {"axis": 0, "mode": "wrap"})
            if isinstance(key, tuple) and key and \
                    all(isinstance(k, (int, np.integer, NDArray))
                        and not isinstance(k, (bool, np.bool_))
                        for k in key):
                from . import array as _array
                if all(isinstance(k, (int, np.integer)) for k in key):
                    # one gather, one constant index matrix
                    indices = _array(np.array([[int(k)] for k in key],
                                              np.int32))
                    out = _invoke("gather_nd", [self, indices], {})
                    return out.reshape(tuple(self.shape[len(key):]))
                # mixed int/array keys broadcast like eager numpy fancy
                # indexing; each key becomes one row of the gather_nd
                # index tensor at the broadcast shape
                bshape = np.broadcast_shapes(
                    *[k.shape for k in key if isinstance(k, NDArray)])
                rows = []
                for k in key:
                    if isinstance(k, NDArray):
                        if tuple(k.shape) != tuple(bshape):
                            k = _invoke("broadcast_to", [k],
                                        {"shape": bshape})
                    else:
                        k = _array(np.broadcast_to(
                            np.int32(int(k)), bshape).copy())
                    rows.append(k.reshape((1,) + tuple(bshape)))
                indices = _invoke("Concat", rows,
                                  {"dim": 0, "num_args": len(rows)})
                return _invoke("gather_nd", [self, indices], {})
        if isinstance(key, NDArray):
            key = key._data.astype(jnp.int32)
        elif isinstance(key, tuple):
            key = tuple(k._data.astype(jnp.int32) if isinstance(k, NDArray)
                        else k for k in key)
        return _wrap(self._data[key], self._ctx)

    def __setitem__(self, key, value):
        if isinstance(key, NDArray):
            key = key._data.astype(jnp.int32)
        elif isinstance(key, tuple):
            key = tuple(k._data.astype(jnp.int32) if isinstance(k, NDArray)
                        else k for k in key)
        if isinstance(value, NDArray):
            value = value._data
        elif isinstance(value, np.ndarray):
            value = jnp.asarray(value, self._data.dtype)
        if key is Ellipsis or (isinstance(key, slice) and key == slice(None)):
            new = jnp.broadcast_to(jnp.asarray(value, self._data.dtype),
                                   self.shape).astype(self._data.dtype)
            sh = _multi_device_sharding(self._data)
            self._set_data(jax.device_put(new, sh) if sh is not None
                           else _to_device(new, self._ctx))
        else:
            self._set_data(self._data.at[key].set(value))

    def __repr__(self):
        return "\n%s\n<NDArray %s @%s>" % (
            self.asnumpy(), "x".join(str(s) for s in self.shape), self._ctx)

    # pickle / deepcopy support
    def __getstate__(self):
        return {"data": self.asnumpy(), "ctx_type": self._ctx.device_type,
                "ctx_id": self._ctx.device_id}

    def __setstate__(self, st):
        ctx = Context(st["ctx_type"], st["ctx_id"])
        self._ctx = ctx
        self._data = _to_device(jnp.asarray(st["data"]), ctx)
        self._grad = None
        self._tape = None
        self._stype = "default"
        if telemetry.enabled():   # unpickled arrays enter the ledger too
            d = self._data
            telemetry.ledger_track(self, str(ctx),
                                   int(d.size) * d.dtype.itemsize,
                                   shape=d.shape, dtype=d.dtype)


# ---------------------------------------------------------------------------
# helpers and creation functions
# ---------------------------------------------------------------------------

def _to_device(raw, ctx):
    try:
        return jax.device_put(raw, ctx.jax_device())
    except Exception:
        return jnp.asarray(raw)


def _multi_device_sharding(raw):
    """The committed sharding of ``raw`` if it spans >1 device (mesh-
    sharded/replicated state under the DP Module), else None."""
    sh = getattr(raw, "sharding", None)
    if sh is not None and len(getattr(sh, "device_set", ())) > 1:
        return sh
    return None


class _SliceView(NDArray):
    """Write-through view of a basic axis-0 slice (parity: the
    reference's aliasing NDArray views). ``_data`` reads through to the
    parent; ``_set_data`` writes back into the parent's buffer, so
    in-place ops and ``view[:] = x`` mutate the parent like shared
    storage would."""

    __slots__ = ("_parent", "_vkey")

    def __init__(self, parent, key):
        self._parent = parent
        self._vkey = key
        self._ctx = parent._ctx
        self._grad = None
        self._tape = None
        self._stype = "default"

    @property
    def _data(self):
        return self._parent._data[self._vkey]

    def _set_data(self, raw):
        parent = self._parent
        parent._set_data(parent._data.at[self._vkey].set(
            jnp.asarray(raw, parent._data.dtype)))

    # shape/dtype are derivable from the parent + key without issuing a
    # device slice per attribute access (the _data property dispatches a
    # gather each read)
    @property
    def shape(self):
        pshape = self._parent.shape
        if isinstance(self._vkey, slice):
            start, stop, _ = self._vkey.indices(pshape[0])
            return (max(0, stop - start),) + pshape[1:]
        return pshape[1:]

    @property
    def dtype(self):
        return self._parent.dtype

    @property
    def ndim(self):
        return len(self.shape)

    @property
    def size(self):
        out = 1
        for d in self.shape:
            out *= d
        return out

    def __reduce__(self):
        # views pickle/deepcopy as detached base arrays (the inherited
        # __setstate__ assigns _data, which a getter-only property on
        # this class would reject)
        return (_rebuild_detached, (self.asnumpy(),
                                    self._ctx.device_type,
                                    self._ctx.device_id))


def _rebuild_detached(arr, ctx_type, ctx_id):
    return array(arr, ctx=Context(ctx_type, ctx_id))


def _wrap(raw, ctx=None):
    return NDArray(raw, ctx if ctx is not None else current_context())


def _invoke(op_name, inputs, kwargs, out=None):
    return _imp.invoke(_registry.get_op(op_name), inputs, kwargs, out=out)


def array(source_array, ctx=None, dtype=None):
    """Create an NDArray from any array-like (parity: mx.nd.array)."""
    if isinstance(source_array, NDArray):
        src = source_array._data
    elif isinstance(source_array, (np.ndarray, jax.Array)):
        src = source_array
    else:
        src = np.asarray(source_array)
        # python lists of floats default to float32 (MXNet convention)
        if src.dtype == np.float64:
            src = src.astype(np.float32)
    dt = mx_dtype(dtype)
    if dt is None:
        # keep source dtype; JAX x64-off coerces float64->float32 like the
        # reference's real_t default
        dt = np.float32 if np.dtype(getattr(src, "dtype", np.float32)) == np.float64 \
            else src.dtype
    ctx = ctx or current_context()
    if isinstance(src, np.ndarray):
        # MUST copy: the CPU backend zero-copies 64-byte-aligned host
        # buffers, and the reference's NDArray construction semantics are
        # always-copy — without this, callers reusing a staging buffer
        # (pooled ImageIter batches) would mutate live arrays
        converted = jnp.array(src, dt)
    else:
        converted = jnp.asarray(src, dt)
    return _wrap(_to_device(converted, ctx), ctx)


def zeros(shape, ctx=None, dtype=None, stype=None, **kwargs):
    ctx = ctx or current_context()
    dt = mx_dtype(dtype) or np.float32
    if isinstance(shape, numbers.Number):
        shape = (int(shape),)
    return _wrap(_to_device(jnp.zeros(shape, dt), ctx), ctx)


def ones(shape, ctx=None, dtype=None, **kwargs):
    ctx = ctx or current_context()
    dt = mx_dtype(dtype) or np.float32
    if isinstance(shape, numbers.Number):
        shape = (int(shape),)
    return _wrap(_to_device(jnp.ones(shape, dt), ctx), ctx)


def full(shape, val, ctx=None, dtype=None, **kwargs):
    ctx = ctx or current_context()
    dt = mx_dtype(dtype) or np.float32
    if isinstance(shape, numbers.Number):
        shape = (int(shape),)
    return _wrap(_to_device(jnp.full(shape, val, dt), ctx), ctx)


def empty(shape, ctx=None, dtype=None):
    return zeros(shape, ctx=ctx, dtype=dtype)


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype=None):
    return _invoke("_arange", [], {"start": start, "stop": stop, "step": step,
                                   "repeat": repeat,
                                   "dtype": dtype or "float32"})


def concatenate(arrays, axis=0, always_copy=True):
    return _invoke("Concat", list(arrays), {"dim": axis})


def moveaxis(tensor, source, destination):
    return _wrap(jnp.moveaxis(tensor._data, source, destination), tensor._ctx)


def transpose(data, axes=()):
    return _invoke("transpose", [data], {"axes": axes})


def waitall():
    """Block until all async computation completes (parity: mx.nd.waitall)."""
    jax.effects_barrier()


def onehot_encode(indices, out):
    depth = out.shape[1]
    res = _invoke("one_hot", [indices], {"depth": depth})
    out._set_data(res._data)
    return out


def imresize(*args, **kwargs):  # pragma: no cover
    raise MXNetError("imresize requires the image pipeline (mxnet_tpu.image)")
