"""Legacy symbolic RNN cells.

Parity: reference ``python/mxnet/rnn/rnn_cell.py`` — Symbol-graph cells
used by the bucketing LSTM example. Each cell emits Symbol ops;
``FusedRNNCell`` maps to the fused RNN op (≙ cuDNN path).
"""
from __future__ import annotations

from ..base import MXNetError, NameManager
from .. import symbol as sym_mod
from ..symbol import Symbol, Variable

__all__ = ["BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell", "FusedRNNCell",
           "SequentialRNNCell", "BidirectionalCell", "DropoutCell",
           "ZoneoutCell", "ResidualCell", "RNNParams", "ModifierCell",
           "BaseConvRNNCell", "ConvRNNCell", "ConvLSTMCell", "ConvGRUCell",
           "lstm_decode_step"]


class RNNParams:
    """(parity: rnn_cell.RNNParams) — shared weight container."""

    def __init__(self, prefix=""):
        self._prefix = prefix
        self._params = {}

    def get(self, name, **kwargs):
        name = self._prefix + name
        if name not in self._params:
            self._params[name] = Variable(name, **kwargs)
        return self._params[name]


class BaseRNNCell:
    """(parity: rnn_cell.BaseRNNCell)"""

    def __init__(self, prefix="", params=None):
        if params is None:
            params = RNNParams(prefix)
            self._own_params = True
        else:
            self._own_params = False
        self._prefix = prefix
        self._params = params
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1

    @property
    def params(self):
        self._own_params = False
        return self._params

    @property
    def state_info(self):
        raise NotImplementedError

    @property
    def state_shape(self):
        return [ele["shape"] for ele in self.state_info]

    @property
    def _gate_names(self):
        return ()

    def begin_state(self, func=None, **kwargs):
        if func is None:
            func = sym_mod.zeros
        states = []
        for info in self.state_info:
            self._init_counter += 1
            if info is not None:
                info = dict(info)
                shape = info.pop("shape", None)
                state = func(name="%sbegin_state_%d" % (self._prefix,
                                                        self._init_counter),
                             shape=shape, **kwargs)
            else:
                state = func(name="%sbegin_state_%d" % (self._prefix,
                                                        self._init_counter),
                             **kwargs)
            states.append(state)
        return states

    def __call__(self, inputs, states):
        raise NotImplementedError

    def _symbolic_begin_state(self, ref, reduce_axes):
        """Default zero states whose batch dim comes from ``ref``.

        The reference writes ``sym.zeros(shape=(0, H))`` and relies on
        MXNet's 0=unknown bidirectional shape inference; forward-only XLA
        inference can't see through that, so the unknown dim is instead
        taken from the input symbol: a zeroed batch vector (ref summed
        over ``reduce_axes``) broadcast against a zeros literal. XLA
        constant-folds the whole expression to a plain zeros buffer."""
        zero_vec = sym_mod.sum(ref * 0, axis=reduce_axes)  # shape (N,)

        def _zeros_from_ref(name=None, shape=None, **kwargs):
            if not shape or 0 not in shape:
                return sym_mod.zeros(name=name, shape=shape, **kwargs)
            shape = tuple(shape)
            i = shape.index(0)
            col = sym_mod.reshape(
                zero_vec, shape=(1,) * i + (-1,) + (1,) * (len(shape) - i - 1))
            base = sym_mod.zeros(shape=tuple(1 if d == 0 else d
                                             for d in shape))
            return sym_mod.broadcast_add(base, col)

        return self.begin_state(func=_zeros_from_ref)

    def _default_begin_state(self, inputs, layout):
        """begin_state for unroll when the caller gave none: symbolic
        inputs get batch-inferred zeros, arrays get plain zeros."""
        if isinstance(inputs, Symbol):
            n_axis = layout.find("N")
            return self._symbolic_begin_state(
                inputs, tuple(i for i in range(3) if i != n_axis))
        if isinstance(inputs, (list, tuple)) and inputs \
                and isinstance(inputs[0], Symbol):
            return self._symbolic_begin_state(inputs[0], (1,))
        return self.begin_state()

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        """(parity: BaseRNNCell.unroll)"""
        self.reset()
        axis = layout.find("T")
        if begin_state is None:
            begin_state = self._default_begin_state(inputs, layout)
        if isinstance(inputs, Symbol):
            steps = sym_mod.SliceChannel(inputs, num_outputs=length,
                                         axis=axis, squeeze_axis=True)
            inputs = [steps[i] for i in range(length)]
        states = begin_state
        outputs = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
        if merge_outputs is None or merge_outputs:
            outputs = [sym_mod.expand_dims(o, axis=axis) for o in outputs]
            outputs = sym_mod.Concat(*outputs, dim=axis)
        return outputs, states

    def _get_activation(self, inputs, activation, **kwargs):
        if isinstance(activation, str):
            return sym_mod.Activation(inputs, act_type=activation, **kwargs)
        return activation(inputs, **kwargs)


class RNNCell(BaseRNNCell):
    """(parity: rnn_cell.RNNCell)"""

    def __init__(self, num_hidden, activation="tanh", prefix="rnn_",
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._activation = activation
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = sym_mod.FullyConnected(inputs, weight=self._iW, bias=self._iB,
                                     num_hidden=self._num_hidden,
                                     name="%si2h" % name)
        h2h = sym_mod.FullyConnected(states[0], weight=self._hW,
                                     bias=self._hB,
                                     num_hidden=self._num_hidden,
                                     name="%sh2h" % name)
        output = self._get_activation(i2h + h2h, self._activation,
                                      name="%sout" % name)
        return output, [output]


class LSTMCell(BaseRNNCell):
    """(parity: rnn_cell.LSTMCell; gates i,f,c,o)"""

    def __init__(self, num_hidden, prefix="lstm_", params=None,
                 forget_bias=1.0):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        from ..initializer import LSTMBias
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"},
                {"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ["_i", "_f", "_c", "_o"]

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h = sym_mod.FullyConnected(inputs, weight=self._iW, bias=self._iB,
                                     num_hidden=self._num_hidden * 4,
                                     name="%si2h" % name)
        h2h = sym_mod.FullyConnected(states[0], weight=self._hW,
                                     bias=self._hB,
                                     num_hidden=self._num_hidden * 4,
                                     name="%sh2h" % name)
        gates = i2h + h2h
        slice_gates = sym_mod.SliceChannel(gates, num_outputs=4, axis=1,
                                           name="%sslice" % name)
        in_gate = sym_mod.Activation(slice_gates[0], act_type="sigmoid")
        forget_gate = sym_mod.Activation(slice_gates[1], act_type="sigmoid")
        in_transform = sym_mod.Activation(slice_gates[2], act_type="tanh")
        out_gate = sym_mod.Activation(slice_gates[3], act_type="sigmoid")
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * sym_mod.Activation(next_c, act_type="tanh")
        return next_h, [next_h, next_c]


class GRUCell(BaseRNNCell):
    """(parity: rnn_cell.GRUCell)"""

    def __init__(self, num_hidden, prefix="gru_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        prev_h = states[0]
        i2h = sym_mod.FullyConnected(inputs, weight=self._iW, bias=self._iB,
                                     num_hidden=self._num_hidden * 3,
                                     name="%si2h" % name)
        h2h = sym_mod.FullyConnected(prev_h, weight=self._hW, bias=self._hB,
                                     num_hidden=self._num_hidden * 3,
                                     name="%sh2h" % name)
        i2h_s = sym_mod.SliceChannel(i2h, num_outputs=3, axis=1)
        h2h_s = sym_mod.SliceChannel(h2h, num_outputs=3, axis=1)
        reset_gate = sym_mod.Activation(i2h_s[0] + h2h_s[0],
                                        act_type="sigmoid")
        update_gate = sym_mod.Activation(i2h_s[1] + h2h_s[1],
                                         act_type="sigmoid")
        next_h_tmp = sym_mod.Activation(i2h_s[2] + reset_gate * h2h_s[2],
                                        act_type="tanh")
        next_h = (1.0 - update_gate) * next_h_tmp + update_gate * prev_h
        return next_h, [next_h]


class FusedRNNCell(BaseRNNCell):
    """Fused multi-layer cell over the RNN op (parity: rnn_cell.FusedRNNCell
    ≙ the cuDNN path; see ops/rnn.py for the TPU lax.scan design)."""

    def __init__(self, num_hidden, num_layers=1, mode="lstm",
                 bidirectional=False, dropout=0.0, get_next_state=False,
                 forget_bias=1.0, prefix=None, params=None):
        if prefix is None:
            prefix = "%s_" % mode
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._dropout = dropout
        self._get_next_state = get_next_state
        self._parameter = self.params.get("parameters")
        self._directions = 2 if bidirectional else 1

    @property
    def state_info(self):
        b = self._directions * self._num_layers
        n = 2 if self._mode == "lstm" else 1
        return [{"shape": (b, 0, self._num_hidden), "__layout__": "LNC"}
                for _ in range(n)]

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        if begin_state is None:
            begin_state = self._default_begin_state(inputs, layout)
        if layout == "NTC":
            inputs = sym_mod.swapaxes(inputs, dim1=0, dim2=1)
        states = begin_state
        rnn_args = dict(state_size=self._num_hidden,
                        num_layers=self._num_layers, mode=self._mode,
                        bidirectional=self._bidirectional, p=self._dropout,
                        state_outputs=self._get_next_state)
        if self._mode == "lstm":
            rnn = sym_mod.RNN(inputs, self._parameter, states[0], states[1],
                              name=self._prefix + "rnn", **rnn_args)
        else:
            rnn = sym_mod.RNN(inputs, self._parameter, states[0],
                              name=self._prefix + "rnn", **rnn_args)
        if not self._get_next_state:
            outputs, states = rnn, []
        elif self._mode == "lstm":
            outputs, states = rnn[0], [rnn[1], rnn[2]]
        else:
            outputs, states = rnn[0], [rnn[1]]
        if layout == "NTC":
            outputs = sym_mod.swapaxes(outputs, dim1=0, dim2=1)
        return outputs, states

    # -- packed-blob <-> named-parameter views ------------------------------
    @property
    def _gate_names(self):
        return {"rnn_relu": [""], "rnn_tanh": [""],
                "lstm": ["_i", "_f", "_c", "_o"],
                "gru": ["_r", "_z", "_o"]}[self._mode]

    def _blob_layout(self, input_size):
        """[(name, shape, offset)] of the packed blob (ops/rnn.py layout:
        per layer, per direction: W_ih, W_hh, b_ih, b_hh; per-gate rows)."""
        from ..ops.rnn import _GATES
        g = _GATES[self._mode]
        H = self._num_hidden
        gates = self._gate_names
        out = []
        off = 0
        for layer in range(self._num_layers):
            in_sz = input_size if layer == 0 else H * self._directions
            for d in range(self._directions):
                pre = "%s%s%d_" % (self._prefix, "lr"[d], layer)
                for gi in range(g):
                    out.append(("%si2h%s_weight" % (pre, gates[gi]),
                                (H, in_sz), off + gi * H * in_sz))
                off += g * H * in_sz
                for gi in range(g):
                    out.append(("%sh2h%s_weight" % (pre, gates[gi]),
                                (H, H), off + gi * H * H))
                off += g * H * H
                for gi in range(g):
                    out.append(("%si2h%s_bias" % (pre, gates[gi]),
                                (H,), off + gi * H))
                off += g * H
                for gi in range(g):
                    out.append(("%sh2h%s_bias" % (pre, gates[gi]),
                                (H,), off + gi * H))
                off += g * H
        return out, off

    def _infer_input_size(self, blob_size):
        """Solve the packed size equation for input_size (rnn_param_size
        is linear in it)."""
        from ..ops.rnn import rnn_param_size
        base = rnn_param_size(0, self._num_hidden, self._num_layers,
                              self._mode, self._bidirectional)
        from ..ops.rnn import _GATES
        per_in = _GATES[self._mode] * self._num_hidden * self._directions
        in_sz, rem = divmod(blob_size - base, per_in)
        if rem or in_sz <= 0:
            raise MXNetError("parameter blob size %d does not match this "
                             "cell's configuration" % blob_size)
        return in_sz

    def unpack_weights(self, args):
        """Packed ``parameters`` blob -> per-layer/gate named arrays
        (parity: rnn_cell.FusedRNNCell.unpack_weights)."""
        import numpy as np
        from ..ndarray import array as nd_array
        args = args.copy()
        blob = args.pop("%sparameters" % self._prefix)
        flat = blob.asnumpy().ravel()
        layout, total = self._blob_layout(self._infer_input_size(flat.size))
        if total != flat.size:
            raise MXNetError("blob size mismatch")
        for name, shape, off in layout:
            n = int(np.prod(shape))
            args[name] = nd_array(flat[off:off + n].reshape(shape))
        return args

    def pack_weights(self, args):
        """Inverse of :meth:`unpack_weights`."""
        import numpy as np
        from ..ndarray import array as nd_array
        args = args.copy()
        g = self._gate_names
        probe = "%s%s0_i2h%s_weight" % (self._prefix, "l", g[0])
        in_sz = args[probe].shape[1]
        layout, total = self._blob_layout(in_sz)
        flat = np.zeros(total, np.float32)
        for name, shape, off in layout:
            n = int(np.prod(shape))
            flat[off:off + n] = args.pop(name).asnumpy().ravel()
        args["%sparameters" % self._prefix] = nd_array(flat)
        return args


class SequentialRNNCell(BaseRNNCell):
    """(parity: rnn_cell.SequentialRNNCell)"""

    def __init__(self, params=None):
        super().__init__(prefix="", params=params)
        self._cells = []
        self._override_cell_params = params is not None

    def add(self, cell):
        self._cells.append(cell)

    @property
    def state_info(self):
        info = []
        for cell in self._cells:
            info.extend(cell.state_info)
        return info

    def begin_state(self, **kwargs):
        states = []
        for cell in self._cells:
            states.extend(cell.begin_state(**kwargs))
        return states

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._cells:
            n = len(cell.state_info)
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.extend(state)
        return inputs, next_states


class DropoutCell(BaseRNNCell):
    """(parity: rnn_cell.DropoutCell)"""

    def __init__(self, dropout, prefix="dropout_", params=None):
        super().__init__(prefix=prefix, params=params)
        self.dropout = dropout

    @property
    def state_info(self):
        return []

    def __call__(self, inputs, states):
        if self.dropout > 0:
            inputs = sym_mod.Dropout(inputs, p=self.dropout)
        return inputs, states


class ModifierCell(BaseRNNCell):
    def __init__(self, base_cell):
        super().__init__(prefix="", params=None)
        base_cell._modified = True
        self.base_cell = base_cell

    @property
    def state_info(self):
        return self.base_cell.state_info

    def begin_state(self, func=None, **kwargs):
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func=func, **kwargs)
        self.base_cell._modified = True
        return begin


class ZoneoutCell(ModifierCell):
    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self.prev_output = None

    def __call__(self, inputs, states):
        output, new_states = self.base_cell(inputs, states)
        if self.zoneout_outputs > 0:
            mask = sym_mod.Dropout(sym_mod.ones_like(output),
                                   p=self.zoneout_outputs)
            prev = self.prev_output if self.prev_output is not None \
                else sym_mod.zeros_like(output)
            output = sym_mod.where(mask, output, prev)
        if self.zoneout_states > 0:
            new_states = [sym_mod.where(
                sym_mod.Dropout(sym_mod.ones_like(ns), p=self.zoneout_states),
                ns, s) for ns, s in zip(new_states, states)]
        self.prev_output = output
        return output, new_states


class ResidualCell(ModifierCell):
    def __call__(self, inputs, states):
        output, states = self.base_cell(inputs, states)
        return output + inputs, states


class BidirectionalCell(BaseRNNCell):
    """(parity: rnn_cell.BidirectionalCell)"""

    def __init__(self, l_cell, r_cell, params=None, output_prefix="bi_"):
        super().__init__(prefix="", params=params)
        self._output_prefix = output_prefix
        self._cells = [l_cell, r_cell]

    @property
    def state_info(self):
        return self._cells[0].state_info + self._cells[1].state_info

    def begin_state(self, **kwargs):
        return (self._cells[0].begin_state(**kwargs)
                + self._cells[1].begin_state(**kwargs))

    def __call__(self, inputs, states):
        raise MXNetError("BidirectionalCell supports only unroll()")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        axis = layout.find("T")
        if begin_state is None:
            begin_state = self._default_begin_state(inputs, layout)
        l_cell, r_cell = self._cells
        n_l = len(l_cell.state_info)
        l_out, l_states = l_cell.unroll(length, inputs, begin_state[:n_l],
                                        layout, merge_outputs=True)
        rev = sym_mod.reverse(inputs, axis=axis)
        r_out, r_states = r_cell.unroll(length, rev, begin_state[n_l:],
                                        layout, merge_outputs=True)
        r_out = sym_mod.reverse(r_out, axis=axis)
        outputs = sym_mod.Concat(l_out, r_out, dim=2,
                                 name="%sout" % self._output_prefix)
        return outputs, l_states + r_states


# ---------------------------------------------------------------------------
# Convolutional RNN cells (parity: rnn_cell.py:1094-1455)
# ---------------------------------------------------------------------------

class BaseConvRNNCell(BaseRNNCell):
    """Abstract convolutional RNN cell (parity: rnn_cell.BaseConvRNNCell):
    gate pre-activations are convolutions over the input and the spatial
    hidden state instead of dense projections."""

    def __init__(self, input_shape, num_hidden, h2h_kernel=(3, 3),
                 h2h_dilate=(1, 1), i2h_kernel=(3, 3), i2h_stride=(1, 1),
                 i2h_pad=(1, 1), i2h_dilate=(1, 1),
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 activation="tanh", prefix="", params=None,
                 conv_layout="NCHW"):
        super().__init__(prefix=prefix, params=params)
        if h2h_kernel[0] % 2 == 0 or h2h_kernel[1] % 2 == 0:
            raise MXNetError("h2h_kernel must be odd (got %s)"
                             % (h2h_kernel,))
        self._h2h_kernel = tuple(h2h_kernel)
        self._h2h_dilate = tuple(h2h_dilate)
        self._h2h_pad = (h2h_dilate[0] * (h2h_kernel[0] - 1) // 2,
                         h2h_dilate[1] * (h2h_kernel[1] - 1) // 2)
        self._i2h_kernel = tuple(i2h_kernel)
        self._i2h_stride = tuple(i2h_stride)
        self._i2h_pad = tuple(i2h_pad)
        self._i2h_dilate = tuple(i2h_dilate)
        self._num_hidden = num_hidden
        self._input_shape = tuple(input_shape)
        self._conv_layout = conv_layout
        self._activation = activation
        # state spatial shape falls out of the i2h convolution
        probe = sym_mod.Convolution(
            Variable("data"), num_filter=num_hidden,
            kernel=self._i2h_kernel, stride=self._i2h_stride,
            pad=self._i2h_pad, dilate=self._i2h_dilate)
        shape = probe.infer_shape(data=self._input_shape)[1][0]
        self._state_shape = (0,) + tuple(shape[1:])
        self._iW = self.params.get("i2h_weight",
                                   init=i2h_weight_initializer)
        self._hW = self.params.get("h2h_weight",
                                   init=h2h_weight_initializer)
        self._iB = self.params.get("i2h_bias", init=i2h_bias_initializer)
        self._hB = self.params.get("h2h_bias", init=h2h_bias_initializer)

    @property
    def _num_gates(self):
        return len(self._gate_names)

    @property
    def state_info(self):
        return [{"shape": self._state_shape,
                 "__layout__": self._conv_layout},
                {"shape": self._state_shape,
                 "__layout__": self._conv_layout}]

    def _conv_forward(self, inputs, states, name):
        i2h = sym_mod.Convolution(
            inputs, weight=self._iW, bias=self._iB,
            num_filter=self._num_hidden * self._num_gates,
            kernel=self._i2h_kernel, stride=self._i2h_stride,
            pad=self._i2h_pad, dilate=self._i2h_dilate,
            name="%si2h" % name)
        h2h = sym_mod.Convolution(
            states[0], weight=self._hW, bias=self._hB,
            num_filter=self._num_hidden * self._num_gates,
            kernel=self._h2h_kernel, pad=self._h2h_pad,
            dilate=self._h2h_dilate, name="%sh2h" % name)
        return i2h, h2h


class ConvRNNCell(BaseConvRNNCell):
    """(parity: rnn_cell.ConvRNNCell)"""

    @property
    def _gate_names(self):
        return ("",)

    @property
    def state_info(self):
        return [{"shape": self._state_shape,
                 "__layout__": self._conv_layout}]

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h, h2h = self._conv_forward(inputs, states, name)
        output = self._get_activation(i2h + h2h, self._activation,
                                      name="%sout" % name)
        return output, [output]


class ConvLSTMCell(BaseConvRNNCell):
    """(parity: rnn_cell.ConvLSTMCell — Shi et al. convolutional LSTM)"""

    @property
    def _gate_names(self):
        return ("_i", "_f", "_c", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h, h2h = self._conv_forward(inputs, states, name)
        gates = i2h + h2h
        sliced = sym_mod.SliceChannel(gates, num_outputs=4, axis=1,
                                      name="%sslice" % name)
        in_gate = sym_mod.Activation(sliced[0], act_type="sigmoid")
        forget_gate = sym_mod.Activation(sliced[1], act_type="sigmoid")
        in_transform = self._get_activation(sliced[2], self._activation)
        out_gate = sym_mod.Activation(sliced[3], act_type="sigmoid")
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * self._get_activation(next_c, self._activation)
        return next_h, [next_h, next_c]


class ConvGRUCell(BaseConvRNNCell):
    """(parity: rnn_cell.ConvGRUCell)"""

    @property
    def _gate_names(self):
        return ("_r", "_z", "_o")

    @property
    def state_info(self):
        return [{"shape": self._state_shape,
                 "__layout__": self._conv_layout}]

    def __call__(self, inputs, states):
        self._counter += 1
        name = "%st%d_" % (self._prefix, self._counter)
        i2h, h2h = self._conv_forward(inputs, states, name)
        i2h_s = sym_mod.SliceChannel(i2h, num_outputs=3, axis=1)
        h2h_s = sym_mod.SliceChannel(h2h, num_outputs=3, axis=1)
        reset_gate = sym_mod.Activation(i2h_s[0] + h2h_s[0],
                                        act_type="sigmoid")
        update_gate = sym_mod.Activation(i2h_s[1] + h2h_s[1],
                                         act_type="sigmoid")
        next_h_tmp = self._get_activation(i2h_s[2] + reset_gate * h2h_s[2],
                                          self._activation)
        next_h = (1.0 - update_gate) * next_h_tmp + update_gate * states[0]
        return next_h, [next_h]


def lstm_decode_step(x, h, c, wx, wh, b):
    """Pure-jax single-step LSTM: the decode-path counterpart of
    ``LSTMCell`` for the slot-based decode engine (mxnet_tpu/decode.py),
    which vmaps it over active slots. Same gate packing as ``LSTMCell``:
    the fused (..., 4H) projection slices to in/forget/transform/out.

    ``x`` (..., E) input, ``h``/``c`` (..., H) carried state,
    ``wx`` (E, 4H), ``wh`` (H, 4H), ``b`` (4H,).
    Returns ``(next_h, next_c)``.
    """
    # local import: the Symbol-graph cells above must stay importable
    # without touching the jax numeric stack
    import jax
    import jax.numpy as jnp
    gates = x @ wx + h @ wh + b
    in_g, forget_g, transform, out_g = jnp.split(gates, 4, axis=-1)
    in_g = jax.nn.sigmoid(in_g)
    forget_g = jax.nn.sigmoid(forget_g)
    out_g = jax.nn.sigmoid(out_g)
    next_c = forget_g * c + in_g * jnp.tanh(transform)
    next_h = out_g * jnp.tanh(next_c)
    return next_h, next_c
