"""RNN checkpoint helpers (parity: python/mxnet/rnn/rnn.py)."""
from __future__ import annotations

from ..model import save_checkpoint, load_checkpoint

__all__ = ["save_rnn_checkpoint", "load_rnn_checkpoint", "do_rnn_checkpoint"]


def _unpack_cells(cells):
    if not isinstance(cells, (list, tuple)):
        cells = [cells]
    return cells


def save_rnn_checkpoint(cells, prefix, epoch, symbol, arg_params, aux_params):
    """(parity: rnn.save_rnn_checkpoint — fused/unfused param layouts are
    identical here so no repacking is needed)"""
    save_checkpoint(prefix, epoch, symbol, arg_params, aux_params)


def load_rnn_checkpoint(cells, prefix, epoch):
    return load_checkpoint(prefix, epoch)


def do_rnn_checkpoint(cells, prefix, period=1):
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            save_rnn_checkpoint(cells, prefix, iter_no + 1, sym, arg, aux)
    return _callback
