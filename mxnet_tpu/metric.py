"""Evaluation metrics.

Parity: reference ``python/mxnet/metric.py`` — EvalMetric base + registry
and the 16 built-ins (SURVEY.md §5.5).
"""
from __future__ import annotations

import math

import numpy as _numpy

from .base import MXNetError, registry_create
from .ndarray.ndarray import NDArray
from . import telemetry

__all__ = ["EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy",
           "F1", "Perplexity", "MAE", "MSE", "RMSE", "CrossEntropy",
           "NegativeLogLikelihood", "PearsonCorrelation", "Loss", "Torch",
           "Caffe", "CustomMetric", "np", "create", "register"]

register, _alias, _create, _get = registry_create("metric")


def create(metric, *args, **kwargs):
    """(parity: metric.create) Accepts name, callable, instance, or list."""
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, (list, tuple)):
        composite = CompositeEvalMetric()
        for child in metric:
            composite.add(create(child, *args, **kwargs))
        return composite
    return _create(str(metric), *args, **kwargs)


def _as_numpy(x):
    return x.asnumpy() if isinstance(x, NDArray) else _numpy.asarray(x)


def _acc_chain(p, l, a, axis):
    """Pure accuracy accumulate: (optional argmax) + compare + sum +
    running-sum add. The ONE definition both the phase-split jitted
    program (``_acc_fused``) and the whole-step fused metric kernel
    (``Accuracy.device_kernel``) trace — bit-identical paths by
    construction, not by hand-synchronised copies. ``axis`` is None
    when predictions are already class ids."""
    import jax.numpy as jnp
    if axis is not None:
        p = jnp.argmax(p, axis=axis)
    p = p.astype(jnp.int32).reshape(-1)
    l = l.astype(jnp.int32).reshape(-1)
    return a + jnp.sum(p == l).astype(jnp.float32)


def _acc_fused(pred, label, acc, argmax_axis):
    """Accuracy accumulate as one compiled program (jitted
    ``_acc_chain``; ``argmax_axis`` is static)."""
    import jax
    global _ACC_FUSED_JIT
    if _ACC_FUSED_JIT is None:
        _ACC_FUSED_JIT = jax.jit(_acc_chain, static_argnames="axis")
    from .executor import record_dispatch
    record_dispatch("metric")
    return _ACC_FUSED_JIT(pred, label, acc, axis=argmax_axis)


_ACC_FUSED_JIT = None


def _colocate(ref, x):
    """Reshard ``x`` to ``ref``'s placement (mesh-DP outputs are sharded
    over the device mesh while labels arrive single-device). A ``ref``
    whose rank differs from ``x``'s (an mp-sharded prediction spec like
    ``P('dp','mp')`` against a rank-1 label) cannot be applied verbatim
    — ``x`` then shards over the leading dims the two share and
    replicates the rest, landing on the SAME mesh so the jitted
    accumulate accepts the pair."""
    import jax
    sh = getattr(ref, "sharding", None)
    if sh is None:
        return x
    try:
        if getattr(x, "sharding", None) == sh:
            return x
    except ValueError:
        pass
    try:
        return jax.device_put(x, sh)
    except (TypeError, ValueError):
        pass
    mesh = getattr(sh, "mesh", None)
    if mesh is None:
        return x
    from jax.sharding import NamedSharding, PartitionSpec
    entries = tuple(sh.spec)[:x.ndim]
    try:
        return jax.device_put(x, NamedSharding(mesh,
                                               PartitionSpec(*entries)))
    except (TypeError, ValueError):
        return jax.device_put(x, NamedSharding(mesh, PartitionSpec()))


def check_label_shapes(labels, preds, shape=False):
    if (not shape and len(labels) != len(preds)) or \
            (shape and labels.shape != preds.shape):
        raise MXNetError("Shape of labels %s does not match shape of "
                         "predictions %s" % (len(labels), len(preds)))


class EvalMetric:
    """Base metric (parity: metric.EvalMetric)."""

    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def __str__(self):
        return "EvalMetric: {}".format(dict(self.get_name_value()))

    def get_config(self):
        config = dict(self._kwargs)
        config.update({"metric": self.__class__.__name__, "name": self.name,
                       "output_names": self.output_names,
                       "label_names": self.label_names})
        return config

    def update_dict(self, label, pred):
        if self.output_names is not None:
            pred = [pred[name] for name in self.output_names]
        else:
            pred = list(pred.values())
        if self.label_names is not None:
            label = [label[name] for name in self.label_names]
        else:
            label = list(label.values())
        self.update(label, pred)

    def update(self, labels, preds):
        raise NotImplementedError

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0
        self._dev_sum = None

    # -- async device accumulation ----------------------------------------
    # Hot metrics reduce ON DEVICE and enqueue the scalar without a host
    # sync; get() is the only synchronisation point. On a remoted PJRT
    # backend a per-batch logits pull would otherwise serialise the
    # training pipeline (no reference counterpart — the reference's
    # metrics run in-process where the copy is cheap, metric.py:39).
    def _accum_device(self, scalar, n):
        prev = getattr(self, "_dev_sum", None)
        self._dev_sum = scalar if prev is None else prev + scalar
        self.num_inst += n

    def _flush_device(self):
        if getattr(self, "_dev_sum", None) is not None:
            # THE metric synchronisation point: the only blocking fetch
            # the async accumulate paths ever issue
            telemetry.record_host_sync("metric_fetch")
            with telemetry.span("metric_fetch"):
                self.sum_metric += float(self._dev_sum)
            self._dev_sum = None

    # -- whole-train-step fusion hooks -------------------------------------
    def device_kernel(self):
        """Pure accumulate function for the Module whole-step fused
        training program: ``(labels, preds, acc) -> new_acc`` over traced
        arrays, or None when this metric can only accumulate eagerly
        (Module.fit then falls back to the phase-split ``update`` path
        for the metric — see module/module.py ``_fused_batch_step``).

        Under the dp-mesh SPMD step the kernel traces over BATCH-SHARDED
        labels/preds and a replicated accumulator: the reduction to the
        scalar makes GSPMD insert the cross-replica psum inside the step
        program, so the accumulator handed back to ``_install_fused`` is
        already the GLOBAL sum — fetching it costs no extra program."""
        return None

    def _install_fused(self, dev_sum, n):
        """Adopt the accumulator returned by a fused train step (the
        device value is fetched lazily at ``get()``, like the eager
        ``_accum_device`` path). ``dev_sum`` is the global (mesh-psummed)
        running sum and ``n`` the GLOBAL instance count."""
        self._dev_sum = dev_sum
        self.num_inst += n

    def get(self):
        self._flush_device()
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))


class CompositeEvalMetric(EvalMetric):
    """(parity: metric.CompositeEvalMetric)"""

    def __init__(self, metrics=None, name="composite", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        return self.metrics[index]

    def update_dict(self, labels, preds):
        for metric in self.metrics:
            metric.update_dict(labels, preds)

    def update(self, labels, preds):
        for metric in self.metrics:
            metric.update(labels, preds)

    def reset(self):
        for metric in getattr(self, "metrics", []):
            metric.reset()

    def get(self):
        names, values = [], []
        for metric in self.metrics:
            name, value = metric.get()
            names.append(name) if not isinstance(name, list) else names.extend(name)
            values.append(value) if not isinstance(value, list) else values.extend(value)
        return (names, values)


@register
@register(name="acc")
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, axis=axis)
        self.axis = axis

    def device_kernel(self):
        """Fused-step accumulate: traces the SAME ``_acc_chain`` the
        phase-split ``_acc_fused`` program jits, so the two paths are
        bit-identical."""
        axis = self.axis

        def kernel(labels, preds, acc):
            for l, p in zip(labels, preds):
                ax = axis % p.ndim if p.ndim > l.ndim else None
                acc = _acc_chain(p, l, acc, ax)
            return acc

        return kernel

    def update(self, labels, preds):
        import jax.numpy as jnp
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            if isinstance(label, NDArray) and isinstance(pred, NDArray):
                p, l = pred._data, label._data
                # shape agreement checked host-side so the whole
                # argmax+compare+sum+accumulate chain runs as ONE
                # dispatched program — eager op-by-op execution cost
                # several relay round-trips per batch on remoted PJRT
                n = int(_numpy.prod(l.shape))
                if p.ndim > l.ndim:
                    ax = self.axis % p.ndim
                    p_n = int(_numpy.prod(p.shape[:ax]
                                          + p.shape[ax + 1:]))
                else:
                    p_n = int(_numpy.prod(p.shape))
                if p_n != n:
                    raise MXNetError(
                        "Shape of labels %s does not match shape of "
                        "predictions %s" % (l.shape, p.shape))
                l = _colocate(p, l)
                prev = getattr(self, "_dev_sum", None)
                if prev is None:
                    prev = jnp.zeros((), jnp.float32)
                self._dev_sum = _acc_fused(p, l, prev,
                                           self.axis % p.ndim
                                           if p.ndim > l.ndim else None)
                self.num_inst += n
                continue
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            if pred.ndim > label.ndim:
                pred = _numpy.argmax(pred, axis=self.axis)
            pred = pred.astype(_numpy.int32).flatten()
            label = label.astype(_numpy.int32).flatten()
            check_label_shapes(label, pred, shape=True)
            self.sum_metric += float((pred == label).sum())
            self.num_inst += len(label)


@register
@register(name="top_k_accuracy")
@register(name="top_k_acc")
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, top_k=top_k)
        self.top_k = top_k
        self.name += "_%d" % top_k

    def update(self, labels, preds):
        import jax
        import jax.numpy as jnp
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            if isinstance(label, NDArray) and isinstance(pred, NDArray):
                p, l = pred._data, label._data
                assert p.ndim == 2
                _, topk = jax.lax.top_k(p, self.top_k)
                l = _colocate(topk, l.astype(jnp.int32).reshape(-1, 1))
                hits = jnp.sum(topk == l)
                self._accum_device(hits.astype(jnp.float32),
                                   int(l.shape[0]))
                continue
            pred = _as_numpy(pred)
            label = _as_numpy(label).astype(_numpy.int32)
            assert pred.ndim == 2
            topk = _numpy.argsort(pred, axis=1)[:, -self.top_k:]
            for j in range(self.top_k):
                self.sum_metric += float((topk[:, j] == label.flatten()).sum())
            self.num_inst += len(label)


@register
class F1(EvalMetric):
    def __init__(self, name="f1", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            pred = _as_numpy(pred)
            label = _as_numpy(label).astype(_numpy.int32)
            pred_label = _numpy.argmax(pred, axis=1)
            if len(_numpy.unique(label)) > 2:
                raise MXNetError("F1 currently only supports binary labels")
            tp = float(((pred_label == 1) & (label == 1)).sum())
            fp = float(((pred_label == 1) & (label == 0)).sum())
            fn = float(((pred_label == 0) & (label == 1)).sum())
            precision = tp / (tp + fp) if tp + fp > 0 else 0.0
            recall = tp / (tp + fn) if tp + fn > 0 else 0.0
            f1 = 2 * precision * recall / (precision + recall) \
                if precision + recall > 0 else 0.0
            self.sum_metric += f1
            self.num_inst += 1


@register
class Perplexity(EvalMetric):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity",
                 output_names=None, label_names=None):
        super().__init__(name, output_names, label_names,
                         ignore_label=ignore_label)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        loss = 0.0
        num = 0
        for label, pred in zip(labels, preds):
            label = _as_numpy(label).astype(_numpy.int32).flatten()
            pred = _as_numpy(pred)
            pred = pred.reshape(-1, pred.shape[-1])
            probs = pred[_numpy.arange(label.size), label]
            if self.ignore_label is not None:
                ignore = (label == self.ignore_label)
                probs = _numpy.where(ignore, 1.0, probs)
                num -= int(ignore.sum())
            loss += -_numpy.log(_numpy.maximum(1e-10, probs)).sum()
            num += label.size
        self.sum_metric += float(math.exp(loss / max(num, 1))) * num
        self.num_inst += num

    def get(self):
        if self.num_inst == 0:
            return (self.name, float("nan"))
        return (self.name, self.sum_metric / self.num_inst)


@register
class MAE(EvalMetric):
    def __init__(self, name="mae", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            if label.ndim == 1:
                label = label.reshape(label.shape[0], 1)
            if pred.ndim == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self.sum_metric += float(_numpy.abs(label - pred).mean())
            self.num_inst += 1


@register
class MSE(EvalMetric):
    def __init__(self, name="mse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            if label.ndim == 1:
                label = label.reshape(label.shape[0], 1)
            if pred.ndim == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self.sum_metric += float(((label - pred) ** 2).mean())
            self.num_inst += 1


@register
class RMSE(EvalMetric):
    def __init__(self, name="rmse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            if label.ndim == 1:
                label = label.reshape(label.shape[0], 1)
            if pred.ndim == 1:
                pred = pred.reshape(pred.shape[0], 1)
            self.sum_metric += float(_numpy.sqrt(((label - pred) ** 2).mean()))
            self.num_inst += 1


@register
@register(name="ce")
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, eps=eps)
        self.eps = eps

    def update(self, labels, preds):
        import jax.numpy as jnp
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            if isinstance(label, NDArray) and isinstance(pred, NDArray):
                p, l = pred._data, label._data.reshape(-1).astype(jnp.int32)
                assert l.shape[0] == p.shape[0]
                l = _colocate(p, l)
                prob = jnp.take_along_axis(
                    p.astype(jnp.float32), l[:, None], axis=1)[:, 0]
                self._accum_device(-jnp.sum(jnp.log(prob + self.eps)),
                                   int(l.shape[0]))
                continue
            label = _as_numpy(label).ravel().astype(_numpy.int32)
            pred = _as_numpy(pred)
            assert label.shape[0] == pred.shape[0]
            prob = pred[_numpy.arange(label.shape[0]), label]
            self.sum_metric += float((-_numpy.log(prob + self.eps)).sum())
            self.num_inst += label.shape[0]


@register
@register(name="nll_loss")
class NegativeLogLikelihood(EvalMetric):
    def __init__(self, eps=1e-12, name="nll-loss", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, eps=eps)
        self.eps = eps

    update = CrossEntropy.update


@register
@register(name="pearsonr")
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, labels, preds):
        check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            label = _as_numpy(label).ravel()
            pred = _as_numpy(pred).ravel()
            self.sum_metric += float(_numpy.corrcoef(pred, label)[0, 1])
            self.num_inst += 1


@register
class Loss(EvalMetric):
    """Mean of the raw outputs (parity: metric.Loss)."""

    def __init__(self, name="loss", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, _, preds):
        for pred in preds:
            pred = _as_numpy(pred)
            self.sum_metric += float(pred.sum())
            self.num_inst += pred.size


@register
class Torch(Loss):
    def __init__(self, name="torch", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class Caffe(Loss):
    def __init__(self, name="caffe", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class CustomMetric(EvalMetric):
    """Wrap a feval(label, pred) function (parity: metric.CustomMetric)."""

    def __init__(self, feval, name=None, allow_extra_outputs=False,
                 output_names=None, label_names=None):
        if name is None:
            name = feval.__name__
            if name.find("<") != -1:
                name = "custom(%s)" % name
        super().__init__(name, output_names, label_names, feval=feval,
                         allow_extra_outputs=allow_extra_outputs)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            check_label_shapes(labels, preds)
        for pred, label in zip(preds, labels):
            label = _as_numpy(label)
            pred = _as_numpy(pred)
            reval = self._feval(label, pred)
            if isinstance(reval, tuple):
                num_inst, sum_metric = reval
                self.sum_metric += sum_metric
                self.num_inst += num_inst
            else:
                self.sum_metric += reval
                self.num_inst += 1


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """(parity: metric.np) wrap a numpy feval as a metric."""
    def feval(label, pred):
        return numpy_feval(label, pred)
    feval.__name__ = numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)
