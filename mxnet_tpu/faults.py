"""Deterministic fault injection: the chaos substrate for the runtime.

No reference counterpart — the reference's failure story was "ps-lite
notices a dead node eventually" (SURVEY.md §5.3). A serving engine with
deadlines, retry budgets and a breaker, and a training loop that resumes
from preemption, are only trustworthy if their failure paths EXECUTE in
CI — so this module provides a process-global, env-gated injection
registry the runtime's own hot paths consult at named SITES:

========================  ===================================================
site                      where it fires
========================  ===================================================
``dispatch``              ``executor._InstrumentedProgram.__call__`` — every
                          jitted-program launch (training step, serving
                          batch, forward)
``d2h``                   ``serving.InferenceEngine._resolve`` — the blocking
                          device-to-host fetch of a served batch
``compile_cache.load``    ``compile_cache.load`` — a persisted-executable
                          read (an injected raise degrades to the reject
                          path: fresh compile, never an error)
``io_next``               ``io.DataIter.__next__`` — one batch produced by
                          the input pipeline
``kv_push``               ``kvstore.KVStore.push`` — one gradient push
``kv_collective``         ``heartbeat.CollectiveGate.arrive_and_wait`` —
                          every pre-collective gate crossing (a raise kills
                          the worker BEFORE it publishes its arrival, so
                          peers see a deterministic mid-training death)
``heartbeat``             ``heartbeat.start_heartbeat`` beat loop — a raise
                          kills the beat thread (a zombie worker: computes,
                          reads as dead), delay= stretches the beat gap
========================  ===================================================

Spec grammar (``MXNET_FAULTS`` env var, or ``configure()``)::

    spec     := rule (";" rule)*
    rule     := site ":" action [":" schedule ("," schedule)*]
    action   := "raise" | "delay=<ms>" | "nan"
    schedule := "n=<K>"      fire ONLY on the Kth call (1-based)
              | "every=<K>"  fire on every Kth call (K, 2K, 3K, ...)
              | "first=<K>"  fire on calls 1..K
              | "p=<prob>"   fire with probability prob per call
              | "seed=<S>"   seed for the p= draw (default 0 — the
                             schedule is DETERMINISTIC either way)

    MXNET_FAULTS="dispatch:raise:p=0.2,seed=7"       # flaky dispatch
    MXNET_FAULTS="d2h:nan:n=3;io_next:delay=50:every=10"

Actions: ``raise`` raises :class:`InjectedFault` (an ``MXNetError``
marked ``transient`` so the serving retry budget applies); ``delay``
sleeps the given milliseconds; ``nan`` asks the SITE to corrupt its
payload (``fire()`` returns ``"nan"`` and the caller applies
:func:`poison` — sites without a float payload treat it as a no-op).

Every injection is counted twice: here (``counts()`` — exact,
independent of the telemetry enable flag, what tests assert on) and in
the telemetry registry (``faults.injected.<site>`` via
``telemetry.record_fault``) so the chaos lane's artifact carries the
fire counts next to the shed/retry counters they caused. The whole
module is inert (one dict check per site) when no spec is configured.
"""
from __future__ import annotations

import os
import random as _pyrandom
import threading
import time

import numpy as np

from .base import MXNetError
from . import telemetry

__all__ = ["InjectedFault", "SITES", "configure", "clear", "active",
           "fire", "counts", "reset_counts", "poison", "spec"]

ENV = "MXNET_FAULTS"

# the named sites the runtime consults — a spec naming anything else is
# a typo that would otherwise never fire, so parsing rejects it
SITES = ("dispatch", "d2h", "compile_cache.load", "io_next", "kv_push",
         "kv_collective", "heartbeat")

_ACTIONS = ("raise", "delay", "nan")


class InjectedFault(MXNetError):
    """An injected failure. ``site`` names where it fired; ``transient``
    is True (the serving retry budget treats injected dispatch faults
    as retryable, exactly like a flaky backend RPC)."""

    def __init__(self, site, message=None):
        super().__init__(message or "injected fault at site %r" % site)
        self.site = site
        self.transient = True


class _Rule:
    __slots__ = ("site", "action", "delay_ms", "n", "every", "first",
                 "p", "seed", "_rng", "fired")

    def __init__(self, site, action, delay_ms=0.0, n=None, every=None,
                 first=None, p=None, seed=0):
        self.site = site
        self.action = action
        self.delay_ms = delay_ms
        self.n = n
        self.every = every
        self.first = first
        self.p = p
        self.seed = seed
        # one private seeded stream per rule: the p= schedule replays
        # identically for a fixed seed regardless of other rules
        self._rng = _pyrandom.Random(seed) if p is not None else None
        self.fired = 0

    def should_fire(self, call_no):
        """Whether this rule fires on the site's ``call_no``-th call
        (1-based). The p= draw happens on EVERY call so the sequence of
        draws — hence the schedule — is deterministic in the seed."""
        if self._rng is not None:
            return self._rng.random() < self.p
        if self.n is not None:
            return call_no == self.n
        if self.every is not None:
            return call_no % self.every == 0
        if self.first is not None:
            return call_no <= self.first
        return True


_lock = threading.Lock()
_rules = {}          # guarded by: _lock
                     # site -> [rule, ...]
_calls = {}          # guarded by: _lock
                     # site -> call count (every consult, fired or not)
_loaded = False      # guarded by: _lock
                     # env spec parsed?
_spec = None         # guarded by: _lock
                     # the active spec string (for introspection)


def _parse_rule(text):
    parts = text.split(":")
    if len(parts) < 2 or len(parts) > 3:
        raise MXNetError(
            "faults: rule %r is not site:action[:schedule]" % text)
    site, action = parts[0].strip(), parts[1].strip()
    if site not in SITES:
        raise MXNetError("faults: unknown site %r (sites: %s)"
                         % (site, ", ".join(SITES)))
    delay_ms = 0.0
    if action.startswith("delay="):
        try:
            delay_ms = float(action[len("delay="):])
        except ValueError:
            raise MXNetError("faults: bad delay in %r" % text)
        action = "delay"
    if action not in _ACTIONS:
        raise MXNetError("faults: unknown action %r (actions: raise, "
                         "delay=<ms>, nan)" % action)
    kw = {}
    if len(parts) == 3:
        for term in parts[2].split(","):
            term = term.strip()
            if not term:
                continue
            k, _, v = term.partition("=")
            try:
                if k == "p":
                    kw["p"] = float(v)
                elif k in ("n", "every", "first", "seed"):
                    kw[k] = int(v)
                else:
                    raise ValueError(k)
            except ValueError:
                raise MXNetError("faults: bad schedule term %r in %r"
                                 % (term, text))
        if sum(k in kw for k in ("n", "every", "first", "p")) > 1:
            raise MXNetError(
                "faults: n=/every=/first=/p= are mutually exclusive "
                "in %r" % text)
        if "p" in kw and not 0.0 <= kw["p"] <= 1.0:
            raise MXNetError("faults: p must be in [0, 1] in %r" % text)
        for k in ("n", "every", "first"):
            if k in kw and kw[k] < 1:
                raise MXNetError("faults: %s must be >= 1 in %r"
                                 % (k, text))
    return _Rule(site, action, delay_ms=delay_ms, **kw)


def parse_spec(spec_text):
    """Parse a spec string into rules; raises ``MXNetError`` on any
    grammar error (a typo'd spec that silently never fires would defeat
    the whole point of a chaos lane)."""
    rules = []
    for chunk in (spec_text or "").split(";"):
        chunk = chunk.strip()
        if chunk:
            rules.append(_parse_rule(chunk))
    return rules


def configure(spec_text):
    """Install a fault spec process-globally (replacing any active one).
    ``None``/empty clears. Raises on grammar errors."""
    global _loaded, _spec
    rules = parse_spec(spec_text) if spec_text else []
    with _lock:
        _rules.clear()
        _calls.clear()
        for r in rules:
            _rules.setdefault(r.site, []).append(r)
        _loaded = True
        _spec = spec_text if rules else None


def clear():
    """Remove every rule and counter (the registry goes inert)."""
    configure(None)


def _ensure_loaded():
    global _loaded
    if _loaded:   # mxlint: disable=lock-discipline -- idempotent one-way latch; a racing loser re-runs configure() with the same env spec
        return
    env_spec = os.environ.get(ENV, "")
    if not env_spec:
        with _lock:
            _loaded = True
        return
    try:
        configure(env_spec)
    except MXNetError as e:
        # an env typo must not brick the process at an arbitrary
        # dispatch site — warn once and run fault-free
        from .log import get_logger
        get_logger("mxnet_tpu.faults").warning(
            "faults: ignoring invalid %s spec: %s", ENV, e)
        configure(None)


def active():
    """Whether any rule is installed (after lazily reading the env)."""
    _ensure_loaded()
    return bool(_rules)   # mxlint: disable=lock-discipline -- GIL-atomic truthiness probe on the inert fast path; fire() re-reads under the lock


def spec():
    """The active spec string, or None."""
    _ensure_loaded()
    with _lock:
        return _spec


def fire(site):
    """Consult the registry at ``site``. Returns None (no injection or
    a delay already served), or ``"nan"`` when the caller should corrupt
    its payload with :func:`poison`; raises :class:`InjectedFault` for a
    ``raise`` rule. One dict lookup when no spec is configured."""
    if not _loaded:   # mxlint: disable=lock-discipline -- GIL-atomic latch probe; the module must cost one read per site when inert
        _ensure_loaded()
    if not _rules:   # mxlint: disable=lock-discipline -- GIL-atomic emptiness probe (the documented one-dict-check fast path); rules re-read under the lock below
        return None
    with _lock:
        rules = _rules.get(site)
        if not rules:
            return None
        call_no = _calls.get(site, 0) + 1
        _calls[site] = call_no
        firing = [r for r in rules if r.should_fire(call_no)]
        for r in firing:
            r.fired += 1
    # account EVERY firing rule and serve every delay BEFORE raising:
    # a raise rule sharing the call with other firing rules must not
    # short-circuit their telemetry counts (the "counted exactly twice"
    # invariant the chaos lane gates on) or skip their delays
    out = None
    raise_after = False
    for r in firing:
        telemetry.record_fault(site)
        # flight record: the postmortem's event ring shows WHICH call
        # the chaos registry hit, interleaved with the sheds/retries/
        # trips it caused
        telemetry.record_event("fault.injected", site=site,
                               action=r.action, call=call_no)
    for r in firing:
        if r.action == "delay":
            time.sleep(r.delay_ms / 1e3)
        elif r.action == "nan":
            out = "nan"
        else:
            raise_after = True
    if raise_after:
        raise InjectedFault(site)
    return out


def counts():
    """{site: {"calls": N, "fired": M}} — exact per-site consult and
    injection counts since the last ``configure``/``reset_counts``.
    Independent of the telemetry enable flag (tests assert on these)."""
    with _lock:
        out = {}
        for site, rules in _rules.items():
            out[site] = {"calls": _calls.get(site, 0),
                         "fired": sum(r.fired for r in rules)}
        return out


def reset_counts():
    """Zero the call/fired counters and REWIND every p= stream to its
    seed — a fresh measurement window replays the same schedule."""
    with _lock:
        _calls.clear()
        for rules in _rules.values():
            for r in rules:
                r.fired = 0
                if r._rng is not None:
                    r._rng = _pyrandom.Random(r.seed)


def poison(arrays):
    """Corrupt-NaN: flip element 0 of every float array to NaN (the
    ``nan`` action's payload transform — what a flipped DRAM bit or a
    bad collective does to a batch). In place where the array is
    writeable, via a copy otherwise; non-float arrays pass through
    untouched. Returns the list (same order)."""
    out = []
    for a in arrays:
        if isinstance(a, np.ndarray) and a.size \
                and np.issubdtype(a.dtype, np.floating):
            if not a.flags.writeable:
                a = a.copy()
            a.reshape(-1)[0] = np.nan
        out.append(a)
    return out
