"""Preemption-safe checkpointing: atomic writes, keep-last-K rotation,
signal-armed saves and exact training resume.

Parity: the reference's checkpoint story is ``model.save_checkpoint``
(three artifacts, SURVEY.md §5.4) written IN PLACE — a preemption mid-
write leaves a truncated ``.params`` file that poisons the next start.
A TPU pod slice is preemptible BY DESIGN (maintenance events, spot
reclaims), so this module upgrades checkpointing from "epoch-end best
effort" to a recovery substrate:

* **atomic artifacts** — every file (params, symbol JSON, optimizer
  states, meta) is written temp + fsync + rename (:func:`atomic_write`
  / :func:`atomic_save_ndarrays`): a reader never observes a partial
  checkpoint, a crash never destroys the previous one. ``model.
  save_checkpoint`` routes through these helpers, so EVERY checkpoint
  writer in the package (Module, FeedForward, callbacks) is atomic.

* **CheckpointManager** — keep-last-K rotation over a prefix,
  ``latest()`` resolution from the newest readable meta record, and
  ``restore()`` that puts back params, optimizer states (including the
  per-parameter update counts the lr schedule reads), and the global
  RNG key — everything ``Module.fit(resume=...)`` needs to continue
  from epoch+batch as if the interruption never happened.

* **signal-armed preemption** — ``arm_signals()`` converts SIGTERM/
  SIGINT into a flag ``fit`` checks at batch boundaries: the loop
  finishes the in-flight batch, saves a mid-epoch checkpoint
  (epoch, nbatch), and raises :class:`TrainingPreempted` — the
  30-second grace window a preemption notice gives is spent writing
  one atomic checkpoint, not unwinding a stack.

Meta record (``<prefix>-NNNN.meta.json``)::

    {"epoch": e, "nbatch": b,      # resume point: epoch e, b batches done
     "param_epoch": NNNN,          # the -NNNN.params file to load
     "rng_state": [...],           # mx.random.get_state()
     "update_counts": {"0": t,..}, # optimizer per-index update counts
     "num_update": t, "optimizer_states": true, "ts": ...}

Counters: ``checkpoint.save`` / ``checkpoint.resume`` /
``training.preempted`` land in the telemetry registry so bench and the
chaos lane can assert exact resume trajectories.
"""
from __future__ import annotations

import json
import os
import re
import signal as _signal
import threading
import time

from .base import MXNetError
from . import telemetry

__all__ = ["CheckpointManager", "TrainingPreempted", "DivergenceError",
           "atomic_write", "atomic_save_ndarrays"]


class TrainingPreempted(MXNetError):
    """``Module.fit`` was interrupted by an armed signal (or a
    programmatic ``request_preempt``) and has saved a resumable
    checkpoint. ``epoch``/``nbatch`` name the resume point; ``prefix``
    the checkpoint it wrote."""

    def __init__(self, message, epoch=None, nbatch=None, prefix=None):
        super().__init__(message)
        self.epoch = epoch
        self.nbatch = nbatch
        self.prefix = prefix


class DivergenceError(MXNetError):
    """The divergence sentinel found non-finite values (loss/params)
    and the policy is ``halt``."""


# ---------------------------------------------------------------------------
# Atomic file helpers
# ---------------------------------------------------------------------------

def _fsync_path(path):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def atomic_write(path, data):
    """Write ``data`` (bytes or str) to ``path`` atomically: temp file
    in the same directory, fsync, rename. A crash at ANY instant leaves
    either the old complete file or the new complete file."""
    if isinstance(data, str):
        data = data.encode()
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = os.path.join(d, ".%s.tmp.%d" % (os.path.basename(path),
                                          os.getpid()))
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def atomic_save_ndarrays(path, save_dict):
    """``nd.save`` semantics with the temp+fsync+rename discipline.
    Remote URIs (``s3://`` etc. through filesystem.register_scheme)
    cannot rename and fall back to a direct save — object stores are
    already last-writer-wins atomic at the object level."""
    from .filesystem import scheme_of
    from .ndarray import save as _nd_save
    if scheme_of(path):
        _nd_save(path, save_dict)
        return
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = os.path.join(d, ".%s.tmp.%d" % (os.path.basename(path),
                                          os.getpid()))
    try:
        _nd_save(tmp, save_dict)
        _fsync_path(tmp)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


# ---------------------------------------------------------------------------
# CheckpointManager
# ---------------------------------------------------------------------------

class CheckpointManager:
    """Keep-last-K atomic checkpoints over ``prefix`` + the preemption
    flag ``Module.fit`` polls at batch boundaries.

    ::

        mgr = mx.CheckpointManager("ckpt/resnet", keep_last=3)
        mod.fit(train, num_epoch=90, checkpoint=mgr)   # auto-save +
                                                       # SIGTERM-safe
        # after a preemption, in a fresh process:
        mod.fit(train, num_epoch=90, checkpoint=mgr, resume=True)

    ``save`` writes ``prefix-NNNN.params`` / ``-symbol.json`` /
    ``-NNNN.states`` / ``-NNNN.meta.json`` (all atomic) where ``NNNN``
    is the resume EPOCH; a mid-epoch save records ``nbatch`` > 0 in the
    meta so resume skips the already-applied batches. ``keep_last``
    bounds disk: older epochs' artifacts are pruned after each save.
    """

    def __init__(self, prefix, keep_last=3):
        self.prefix = str(prefix)
        self.keep_last = max(1, int(keep_last))
        # NOT lock-guarded by design: the armed SIGTERM/SIGINT handler
        # writes this flag, and a signal handler that takes a lock can
        # deadlock against the interrupted frame holding it — single
        # GIL-atomic str-or-None store, polled at batch boundaries
        self._preempt = None            # signal name once requested
        self._armed = {}                # guarded by: self._lock
        self._lock = threading.Lock()

    # -- paths -------------------------------------------------------------
    def _meta_path(self, epoch):
        return "%s-%04d.meta.json" % (self.prefix, epoch)

    def _states_path(self, epoch):
        return "%s-%04d.states" % (self.prefix, epoch)

    def _params_path(self, epoch):
        return "%s-%04d.params" % (self.prefix, epoch)

    # -- save --------------------------------------------------------------
    def save(self, module, epoch, nbatch=0, save_optimizer_states=True):
        """One atomic checkpoint of ``module`` at resume point
        ``(epoch, nbatch)``: params + symbol (via the atomic
        ``model.save_checkpoint``), optimizer states when initialised,
        the RNG key, and the meta record — then prune to ``keep_last``.
        Returns the meta dict."""
        from .model import save_checkpoint as _save_checkpoint
        from . import random as _random
        epoch = int(epoch)
        nbatch = int(nbatch)
        arg_params, aux_params = module.get_params()
        _save_checkpoint(self.prefix, epoch, module.symbol,
                         arg_params, aux_params)
        has_states = bool(save_optimizer_states
                          and getattr(module, "optimizer_initialized",
                                      False))
        if has_states:
            # Module.save_optimizer_states is itself atomic now
            module.save_optimizer_states(self._states_path(epoch))
        meta = {
            "epoch": epoch,
            "nbatch": nbatch,
            "param_epoch": epoch,
            "prefix": os.path.abspath(self.prefix),
            "rng_state": _random.get_state(),
            "optimizer_states": has_states,
            "ts": time.time(),
        }
        # sharding-aware checkpoints: the params file always holds the
        # HOST-GATHERED values (get_params gathers per-shard), and the
        # meta records the layout they were trained under — restore
        # re-shards onto whatever mesh the resuming process binds (a
        # dp-only checkpoint restores onto a dp x mp mesh and vice
        # versa; set_params / _sync_state re-commit to the NEW module's
        # rule-derived placements), so the layout here is provenance,
        # not a constraint
        layout = getattr(module, "partition_summary", None)
        if callable(layout):
            try:
                layout = layout()
            except Exception:
                layout = None
            if layout:
                meta["layout"] = layout
        optimizer = getattr(module, "_optimizer", None)
        if optimizer is not None:
            meta["update_counts"] = {
                str(k): int(v)
                for k, v in optimizer._index_update_count.items()}
            meta["num_update"] = int(optimizer.num_update)
        atomic_write(self._meta_path(epoch), json.dumps(meta,
                                                        sort_keys=True))
        self.prune()
        telemetry.counter_inc("checkpoint.save")
        telemetry.record_event("checkpoint.save", epoch=epoch,
                               nbatch=nbatch)
        return meta

    # -- resolve / load ----------------------------------------------------
    def epochs(self):
        """Sorted epoch ids with a meta record on disk. Matched by
        regex over a directory listing, not glob: ``%04d`` widens past
        4 digits at epoch 10000 (a glob of four ``[0-9]`` would
        silently stop seeing newer checkpoints), and a prefix
        containing glob metacharacters (``run[1]/model``) must not
        make every checkpoint invisible."""
        prefix = os.path.abspath(self.prefix)
        d = os.path.dirname(prefix) or "."
        pat = re.compile(re.escape(os.path.basename(prefix))
                         + r"-(\d{4,})\.meta\.json$")
        try:
            names = os.listdir(d)
        except OSError:
            return []
        out = []
        for name in names:
            m = pat.match(name)
            if m:
                out.append(int(m.group(1)))
        return sorted(out)

    def latest(self):
        """The newest READABLE meta record, or None (no checkpoint yet
        — a fresh start, not an error). A truncated/corrupt meta (a
        non-atomic writer died; ours cannot produce one) is skipped in
        favour of the next-newest."""
        for epoch in reversed(self.epochs()):
            try:
                with open(self._meta_path(epoch)) as f:
                    meta = json.load(f)
                if isinstance(meta, dict) and "epoch" in meta:
                    return meta
            except (OSError, ValueError):
                continue
        return None

    def load(self, meta=None):
        """(symbol, arg_params, aux_params, meta) of ``meta`` (default:
        ``latest()``). Raises when there is nothing to load."""
        from .model import load_checkpoint
        if meta is None:
            meta = self.latest()
        if meta is None:
            raise MXNetError("checkpoint: no checkpoint under prefix %r"
                             % self.prefix)
        sym, arg_params, aux_params = load_checkpoint(
            self.prefix, int(meta["param_epoch"]))
        return sym, arg_params, aux_params, meta

    def restore(self, module, meta=None):
        """Put a checkpoint back into a bound module: params, optimizer
        states + update counts (when both sides have them), and the
        global RNG key. Returns the meta dict used."""
        from . import random as _random
        _, arg_params, aux_params, meta = self.load(meta)
        module.set_params(arg_params, aux_params)
        if meta.get("optimizer_states") \
                and getattr(module, "optimizer_initialized", False):
            states = self._states_path(int(meta["param_epoch"]))
            if os.path.exists(states):
                module.load_optimizer_states(states)
        optimizer = getattr(module, "_optimizer", None)
        if optimizer is not None and meta.get("update_counts"):
            optimizer._index_update_count = {
                int(k): int(v)
                for k, v in meta["update_counts"].items()}
            optimizer.num_update = int(meta.get(
                "num_update", optimizer.num_update))
        if meta.get("rng_state"):
            _random.set_state(meta["rng_state"])
        telemetry.counter_inc("checkpoint.resume")
        telemetry.record_event("checkpoint.resume",
                               epoch=int(meta["epoch"]),
                               nbatch=int(meta.get("nbatch", 0)))
        return meta

    def prune(self):
        """Drop everything but the newest ``keep_last`` epochs'
        artifacts (params/states/meta; the shared ``-symbol.json``
        stays — it is one file and every epoch needs it)."""
        for epoch in self.epochs()[:-self.keep_last]:
            for path in (self._params_path(epoch),
                         self._states_path(epoch),
                         self._meta_path(epoch)):
                try:
                    os.unlink(path)
                except OSError:
                    pass

    # -- preemption flag ---------------------------------------------------
    @property
    def preempt_requested(self):
        """The signal name that requested preemption, or None."""
        return self._preempt

    def request_preempt(self, source="manual"):
        """Set the preemption flag programmatically (what the armed
        signal handler does; tests and external watchers — e.g. a
        maintenance-event poller — call this directly)."""
        self._preempt = str(source)

    def clear_preempt(self):
        self._preempt = None

    def arm_signals(self, signals=(_signal.SIGTERM, _signal.SIGINT)):
        """Install handlers that convert the given signals into the
        preemption flag (checked by ``fit`` at batch boundaries).
        Signal handlers only install on the main thread — elsewhere
        this degrades to a no-op (``request_preempt`` still works).
        Idempotent; ``disarm_signals`` restores the previous handlers."""
        with self._lock:
            for sig in signals:
                if sig in self._armed:
                    continue
                try:
                    prev = _signal.signal(
                        sig, lambda signum, frame:
                        self.request_preempt(
                            _signal.Signals(signum).name))
                except ValueError:      # not the main thread
                    return self
                self._armed[sig] = prev
        return self

    def disarm_signals(self):
        with self._lock:
            for sig, prev in self._armed.items():
                try:
                    _signal.signal(sig, prev)
                except (ValueError, TypeError):
                    pass
            self._armed.clear()
        return self
