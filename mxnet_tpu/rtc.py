"""Runtime kernel compilation.

Parity: reference ``python/mxnet/rtc.py`` — ``CudaModule`` compiles CUDA
C source with NVRTC at runtime (backed by ``src/common/rtc.cc``) and
launches the kernels on NDArrays. The TPU-native equivalent of "hand me
kernel source at runtime" is a **Pallas/JAX module**: the source string
is Python defining kernel functions (jax.numpy or ``pl.pallas_call``
bodies); exports are jitted on first launch, so users get runtime-
compiled custom TPU kernels with the same module/get_kernel/launch flow.

Signatures keep the reference's C syntax — pointer params are NDArrays
(``const float*`` inputs, ``float*`` outputs), scalars pass by value.
A kernel function receives all parameters in order as jax arrays /
scalars and RETURNS the new values of its non-const pointer params (in
declaration order); ``launch`` writes them back into the supplied
NDArrays, preserving the reference's in-place launch semantics on top of
functional XLA. ``grid_dims``/``block_dims`` are accepted for signature
parity; XLA/Mosaic picks the real tiling.
"""
from __future__ import annotations

import re

import jax

from .base import MXNetError
from .ndarray.ndarray import NDArray

__all__ = ["PallasModule", "PallasKernel", "CudaModule", "CudaKernel"]

_DTYPES = {"float": "float32", "double": "float64", "__half": "float16",
           "half": "float16", "uint8_t": "uint8", "int": "int32",
           "int32_t": "int32", "int8_t": "int8", "char": "int8",
           "int64_t": "int64"}


class PallasModule:
    """Compile a source string of jax/pallas kernels at runtime.

    Example::

        source = '''
        import jax.numpy as jnp
        def axpy(alpha, x, y):
            return y + alpha * x
        '''
        module = mx.rtc.PallasModule(source, exports=['axpy'])
        k = module.get_kernel('axpy',
                              'float alpha, const float *x, float *y')
        k.launch([3.0, x, y], mx.tpu(0), (1,1,1), (n,1,1))
    """

    def __init__(self, source, options=(), exports=()):
        if isinstance(options, str):
            options = (options,)
        self._env = {}
        # the source is user code, same trust model as the reference
        # handing CUDA C to NVRTC
        exec(compile(source, "<rtc>", "exec"), self._env)  # noqa: S102
        self._exports = list(exports) if exports else [
            k for k, v in self._env.items()
            if callable(v) and not k.startswith("_")]
        for name in self._exports:
            if name not in self._env:
                raise MXNetError("export %r not defined in source" % name)

    def get_kernel(self, name, signature):
        """Get a launchable kernel; ``signature`` uses C parameter syntax."""
        if name not in self._exports:
            raise MXNetError(
                "%r not in exports %s" % (name, self._exports))
        fn = self._env[name]

        pattern = re.compile(
            r"""^\s*(const)?\s*([\w_]+)\s*(\*)?\s*([\w_]+)?\s*$""")
        args = signature.split(",")
        is_ndarray, dtypes = [], []
        for arg in args:
            match = pattern.match(arg)
            if not match or match.groups()[1] == "const":
                raise MXNetError(
                    "Invalid function prototype \"%s\". Must be in the "
                    "form of \"(const) type (*) (name)\"" % arg)
            is_const, dtype, is_pointer, _ = match.groups()
            if dtype not in _DTYPES:
                raise MXNetError("Unsupported kernel argument type %s" % arg)
            is_ndarray.append(bool(is_pointer))
            dtypes.append((_DTYPES[dtype], not is_const and bool(is_pointer)))
        return PallasKernel(fn, name, is_ndarray, dtypes)


class PallasKernel:
    """A jitted kernel produced by :meth:`PallasModule.get_kernel`."""

    def __init__(self, fn, name, is_ndarray, dtypes):
        self._name = name
        self._is_ndarray = is_ndarray
        self._dtypes = dtypes
        self._jit = jax.jit(fn)

    def launch(self, args, ctx, grid_dims=None, block_dims=None,
               shared_mem=0):
        """Run the kernel; writes results back into mutable NDArray args."""
        del grid_dims, block_dims, shared_mem  # XLA/Mosaic schedules tiling
        if len(args) != len(self._is_ndarray):
            raise MXNetError(
                "kernel %s expects %d arguments, got %d"
                % (self._name, len(self._is_ndarray), len(args)))
        jax_args = []
        out_slots = []
        for i, (arg, is_nd) in enumerate(zip(args, self._is_ndarray)):
            if is_nd:
                if not isinstance(arg, NDArray):
                    raise MXNetError(
                        "arg %d of kernel %s must be an NDArray"
                        % (i, self._name))
                jax_args.append(arg._data)
                if self._dtypes[i][1]:
                    out_slots.append((i, arg))
            else:
                jax_args.append(arg)
        result = self._jit(*jax_args)
        if out_slots:
            if not isinstance(result, (tuple, list)):
                result = (result,)
            if len(result) != len(out_slots):
                raise MXNetError(
                    "kernel %s declared %d mutable pointer params but "
                    "returned %d arrays" % (self._name, len(out_slots),
                                            len(result)))
            for (_, nd), new in zip(out_slots, result):
                nd._set_data(new.astype(nd._data.dtype))


# Reference-compatible aliases (the reference class names are CUDA-flavoured)
CudaModule = PallasModule
CudaKernel = PallasKernel
