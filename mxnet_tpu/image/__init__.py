"""Image pipeline (parity: python/mxnet/image/)."""
from .image import *  # noqa: F401,F403
from . import detection  # noqa: F401
