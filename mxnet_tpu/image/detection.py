"""Detection image pipeline (parity: python/mxnet/image/detection.py).

Provides the DetAug surface the SSD example uses; augmentation operates
on (image, label) pairs where label rows are [cls, x1, y1, x2, y2]
normalised to [0, 1].
"""
from __future__ import annotations

import random

import numpy as np

from ..base import MXNetError
from ..ndarray import array as nd_array
from .image import (Augmenter, ImageIter, imresize, resize_short,
                    color_normalize)

__all__ = ["DetAugmenter", "DetBorrowAug", "DetRandomCropAug",
           "DetHorizontalFlipAug", "CreateDetAugmenter", "ImageDetIter"]


class DetAugmenter:
    """(parity: detection.DetAugmenter)"""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Wrap an image-only augmenter (parity: detection.DetBorrowAug)."""

    def __init__(self, augmenter):
        super().__init__(augmenter=augmenter.dumps() if hasattr(
            augmenter, "dumps") else str(augmenter))
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetHorizontalFlipAug(DetAugmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src, label):
        if random.random() < self.p:
            src = nd_array(src.asnumpy()[:, ::-1])
            label = label.copy()
            tmp = 1.0 - label[:, 3]
            label[:, 3] = 1.0 - label[:, 1]
            label[:, 1] = tmp
        return src, label


class DetRandomCropAug(DetAugmenter):
    """Random crop keeping sufficient box overlap
    (parity: detection.DetRandomCropAug, simplified constraint set)."""

    def __init__(self, min_object_covered=0.1, aspect_ratio_range=(0.75, 1.33),
                 area_range=(0.05, 1.0), max_attempts=50):
        super().__init__()
        self.min_object_covered = min_object_covered
        self.area_range = area_range
        self.aspect_ratio_range = aspect_ratio_range
        self.max_attempts = max_attempts

    def __call__(self, src, label):
        arr = src.asnumpy()
        h, w = arr.shape[:2]
        for _ in range(self.max_attempts):
            area = random.uniform(*self.area_range) * h * w
            ratio = random.uniform(*self.aspect_ratio_range)
            cw = int(np.sqrt(area * ratio))
            ch = int(np.sqrt(area / ratio))
            if cw > w or ch > h:
                continue
            x0 = random.randint(0, w - cw)
            y0 = random.randint(0, h - ch)
            crop = (x0 / w, y0 / h, (x0 + cw) / w, (y0 + ch) / h)
            new_label = self._update_labels(label, crop)
            if new_label is not None:
                out = arr[y0:y0 + ch, x0:x0 + cw]
                return nd_array(out), new_label
        return src, label

    def _update_labels(self, label, crop):
        x1, y1, x2, y2 = crop
        out = label.copy()
        boxes = out[:, 1:5]
        valid = out[:, 0] >= 0
        cx = (boxes[:, 0] + boxes[:, 2]) / 2
        cy = (boxes[:, 1] + boxes[:, 3]) / 2
        keep = valid & (cx > x1) & (cx < x2) & (cy > y1) & (cy < y2)
        if not keep.any():
            return None
        sw, sh = x2 - x1, y2 - y1
        boxes[:, [0, 2]] = np.clip((boxes[:, [0, 2]] - x1) / sw, 0, 1)
        boxes[:, [1, 3]] = np.clip((boxes[:, [1, 3]] - y1) / sh, 0, 1)
        out[:, 1:5] = boxes
        out[:, 0] = np.where(keep, out[:, 0], -1)
        return out


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_mirror=False,
                       mean=None, std=None, **kwargs):
    """(parity: detection.CreateDetAugmenter)"""
    auglist = []
    from .image import ResizeAug, CastAug, ColorNormalizeAug
    if resize > 0:
        auglist.append(DetBorrowAug(ResizeAug(resize)))
    if rand_crop > 0:
        auglist.append(DetRandomCropAug())
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    auglist.append(DetBorrowAug(CastAug()))
    if mean is not None:
        auglist.append(DetBorrowAug(ColorNormalizeAug(
            mean if mean is not True else np.array([123.68, 116.28, 103.53]),
            std if std not in (None, True) else np.array([58.395, 57.12,
                                                          57.375]))))
    return auglist


class ImageDetIter(ImageIter):
    """Detection iterator yielding (B, C, H, W) + (B, M, 5) labels
    (parity: detection.ImageDetIter)."""

    def __init__(self, batch_size, data_shape, label_pad=-1, max_boxes=16,
                 aug_list=None, **kwargs):
        self.max_boxes = max_boxes
        self.label_pad = label_pad
        if aug_list is None:
            aug_list = CreateDetAugmenter(data_shape)
        super().__init__(batch_size, data_shape, label_width=1,
                         aug_list=[], **kwargs)
        self.det_auglist = aug_list

    @property
    def provide_label(self):
        from ..io import DataDesc
        return [DataDesc("label", (self.batch_size, self.max_boxes, 5))]

    def next(self):
        from ..io import DataBatch
        from .image import imdecode
        c, h, w = self.data_shape
        batch_data = np.zeros((self.batch_size, c, h, w), np.float32)
        batch_label = np.full((self.batch_size, self.max_boxes, 5),
                              self.label_pad, np.float32)
        i = 0
        while i < self.batch_size:
            label, s = self.next_sample()
            raw = np.frombuffer(s, np.uint8)
            if raw.size == c * h * w:
                img = nd_array(raw.reshape(h, w, c))
            else:
                img = imdecode(s)
            label = np.asarray(label, np.float32).reshape(-1, 5) \
                if np.asarray(label).size % 5 == 0 else \
                np.zeros((0, 5), np.float32)
            for aug in self.det_auglist:
                img, label = aug(img, label)
            arr = img.asnumpy().astype(np.float32)
            if arr.shape[:2] != (h, w):
                arr = imresize(nd_array(arr.astype(np.uint8)), w, h) \
                    .asnumpy().astype(np.float32)
            batch_data[i] = arr.transpose(2, 0, 1)
            n = min(len(label), self.max_boxes)
            batch_label[i, :n] = label[:n]
            i += 1
        return DataBatch([nd_array(batch_data)], [nd_array(batch_label)],
                         pad=0)
