"""Pure-python image pipeline.

Parity: reference ``python/mxnet/image/image.py`` (ImageIter:999 +
augmenters:482). The reference decodes via OpenCV; this build uses PIL
for JPEG/PNG decode + numpy for augmentation (the C++ RecordIO reader in
src/ accelerates the record scan; decode stays host-side either way —
on TPU the input pipeline budget is host CPU, SURVEY.md §7 "IO
throughput").
"""
from __future__ import annotations

import io as _io
import os
import random

import numpy as np

from ..base import MXNetError
from ..io import DataIter, DataBatch, DataDesc
from ..ndarray import array as nd_array
from ..ndarray.ndarray import NDArray

__all__ = ["imdecode", "imread", "imresize", "copyMakeBorder",
           "resize_short", "fixed_crop",
           "random_crop", "scale_down",
           "center_crop", "color_normalize", "random_size_crop",
           "ResizeAug", "RandomCropAug", "RandomSizedCropAug", "CenterCropAug",
           "HorizontalFlipAug", "CastAug", "ColorNormalizeAug",
           "SequentialAug", "ForceResizeAug", "HueJitterAug", "RandomGrayAug",
           "BrightnessJitterAug", "ContrastJitterAug", "SaturationJitterAug",
           "LightingAug", "ColorJitterAug", "RandomOrderAug",
           "CreateAugmenter", "ImageIter", "Augmenter"]


def _pil():
    try:
        from PIL import Image
        return Image
    except ImportError:
        raise MXNetError("image decode requires PIL in this build")


def imdecode(buf, flag=1, to_rgb=True, out=None):
    """Decode an encoded image buffer to an HWC uint8 NDArray
    (parity: mx.image.imdecode over cv2.imdecode)."""
    Image = _pil()
    img = Image.open(_io.BytesIO(bytes(buf)))
    img = img.convert("RGB" if flag else "L")
    arr = np.asarray(img, np.uint8)
    if arr.ndim == 2:
        arr = arr[:, :, None]
    if not to_rgb and arr.shape[2] == 3:
        arr = arr[:, :, ::-1]
    return nd_array(arr)


def imread(filename, flag=1, to_rgb=True):
    """Read an image file into an NDArray (parity: image.imread — the
    reference routes through cv2.imread; here PIL via imdecode)."""
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag=flag, to_rgb=to_rgb)


def scale_down(src_size, size):
    """Scale ``size`` down to fit in ``src_size`` keeping aspect ratio
    (parity: image.scale_down)."""
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


def imresize(src, w, h, interp=1):
    """(parity: mx.image.imresize)"""
    Image = _pil()
    arr = src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)
    img = Image.fromarray(arr.astype(np.uint8).squeeze())
    img = img.resize((w, h), Image.BILINEAR if interp else Image.NEAREST)
    out = np.asarray(img, np.uint8)
    if out.ndim == 2:
        out = out[:, :, None]
    return nd_array(out)


def copyMakeBorder(src, top, bot, left, right, type=0, value=0.0,
                   values=None, out=None):
    """Pad an HWC image with a border (parity: mx.image.copyMakeBorder
    over the reference's _cvcopyMakeBorder plugin op — plugin/opencv,
    same kwarg names). ``type`` takes the cv2 border codes: 0 CONSTANT
    (``value`` scalar or ``values`` per-channel), 1 REPLICATE,
    2 REFLECT, 3 WRAP, 4 REFLECT_101."""
    arr = src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)
    pad = ((int(top), int(bot)), (int(left), int(right))) \
        + ((0, 0),) * (arr.ndim - 2)
    btype = int(type)
    if btype == 0:
        if values is not None:
            if arr.ndim < 3:
                raise MXNetError(
                    "copyMakeBorder: per-channel values need an image "
                    "with a channel axis (ndim >= 3); got ndim=%d"
                    % arr.ndim)
            # per-channel constant fill: pad each channel separately
            # (pad width excludes the channel axis)
            chan_pad = pad[:-1]
            chans = [np.pad(arr[..., c], chan_pad, mode="constant",
                            constant_values=np.asarray(v, arr.dtype))
                     for c, v in enumerate(
                         np.broadcast_to(np.asarray(values),
                                         (arr.shape[-1],)))]
            padded = np.stack(chans, axis=-1)
        else:
            padded = np.pad(arr, pad, mode="constant",
                            constant_values=np.asarray(value, arr.dtype))
    else:
        mode = {1: "edge", 2: "symmetric", 3: "wrap",
                4: "reflect"}.get(btype)
        if mode is None:
            raise MXNetError("unsupported border type %d" % btype)
        padded = np.pad(arr, pad, mode=mode)
    res = nd_array(padded)
    if out is not None:
        if tuple(out.shape) != tuple(res.shape):
            raise MXNetError(
                "copyMakeBorder: out shape %s != padded shape %s"
                % (tuple(out.shape), tuple(res.shape)))
        out[:] = res
        return out
    return res


def resize_short(src, size, interp=2):
    arr = src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)
    h, w = arr.shape[:2]
    if h > w:
        new_w, new_h = size, int(h * size / w)
    else:
        new_w, new_h = int(w * size / h), size
    return imresize(src, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    arr = src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)
    out = arr[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        return imresize(nd_array(out), size[0], size[1], interp)
    return nd_array(out)


def random_crop(src, size, interp=2):
    arr = src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)
    h, w = arr.shape[:2]
    new_w, new_h = size
    x0 = random.randint(0, max(w - new_w, 0))
    y0 = random.randint(0, max(h - new_h, 0))
    out = fixed_crop(src, x0, y0, min(new_w, w), min(new_h, h), size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=2):
    arr = src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)
    h, w = arr.shape[:2]
    new_w, new_h = size
    x0 = max((w - new_w) // 2, 0)
    y0 = max((h - new_h) // 2, 0)
    out = fixed_crop(src, x0, y0, min(new_w, w), min(new_h, h), size, interp)
    return out, (x0, y0, new_w, new_h)


def random_size_crop(src, size, area, ratio, interp=2):
    arr = src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)
    h, w = arr.shape[:2]
    src_area = h * w
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(10):
        target_area = random.uniform(*area) * src_area
        log_ratio = (np.log(ratio[0]), np.log(ratio[1]))
        aspect = np.exp(random.uniform(*log_ratio))
        new_w = int(round(np.sqrt(target_area * aspect)))
        new_h = int(round(np.sqrt(target_area / aspect)))
        if new_w <= w and new_h <= h:
            x0 = random.randint(0, w - new_w)
            y0 = random.randint(0, h - new_h)
            return fixed_crop(src, x0, y0, new_w, new_h, size, interp), \
                (x0, y0, new_w, new_h)
    return center_crop(src, size, interp)


def color_normalize(src, mean, std=None):
    arr = src.asnumpy().astype(np.float32) if isinstance(src, NDArray) \
        else np.asarray(src, np.float32)
    arr = arr - np.asarray(mean)
    if std is not None:
        arr = arr / np.asarray(std)
    return nd_array(arr)


class Augmenter:
    """(parity: image.Augmenter)"""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, area, ratio, interp=2):
        super().__init__(size=size, area=area, ratio=ratio, interp=interp)
        self.size = size
        self.area = area
        self.ratio = ratio
        self.interp = interp

    def __call__(self, src):
        return random_size_crop(src, self.size, self.area, self.ratio,
                                self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if random.random() < self.p:
            return nd_array(src.asnumpy()[:, ::-1])
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return src.astype(self.typ)


class SequentialAug(Augmenter):
    """Compose a list of augmenters in order (parity: image.SequentialAug)."""

    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        for t in self.ts:
            src = t(src)
        return src


class ForceResizeAug(Augmenter):
    """Resize to exactly (w, h), aspect be damned (parity:
    image.ForceResizeAug)."""

    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class HueJitterAug(Augmenter):
    """Random hue rotation in [-hue, hue] using the YIQ rotation trick
    (parity: image.HueJitterAug — same Gray/I/Q matrix composition)."""

    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue
        self.tyiq = np.array([[0.299, 0.587, 0.114],
                              [0.596, -0.274, -0.321],
                              [0.211, -0.523, 0.311]])
        self.ityiq = np.array([[1.0, 0.956, 0.621],
                               [1.0, -0.272, -0.647],
                               [1.0, -1.107, 1.705]])

    def __call__(self, src):
        alpha = random.uniform(-self.hue, self.hue)
        u = np.cos(alpha * np.pi)
        w = np.sin(alpha * np.pi)
        bt = np.array([[1.0, 0.0, 0.0], [0.0, u, -w], [0.0, w, u]])
        t = np.dot(np.dot(self.ityiq, bt), self.tyiq).T
        arr = src.asnumpy() if isinstance(src, NDArray) else np.asarray(src)
        return nd_array(np.dot(arr.astype(np.float32), t))


class RandomGrayAug(Augmenter):
    """With probability p collapse to 3-channel luminance (parity:
    image.RandomGrayAug)."""

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p
        self.mat = np.array([[0.21, 0.21, 0.21],
                             [0.72, 0.72, 0.72],
                             [0.07, 0.07, 0.07]], np.float32)

    def __call__(self, src):
        if random.random() < self.p:
            arr = src.asnumpy() if isinstance(src, NDArray) \
                else np.asarray(src)
            return nd_array(np.dot(arr.astype(np.float32), self.mat))
        return src


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__(mean=mean, std=std)
        self.mean = mean
        self.std = std

    def __call__(self, src):
        return color_normalize(src, self.mean, self.std)


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + random.uniform(-self.brightness, self.brightness)
        return nd_array(src.asnumpy().astype(np.float32) * alpha)


class ContrastJitterAug(Augmenter):
    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast
        self.coef = np.array([[[0.299, 0.587, 0.114]]])

    def __call__(self, src):
        alpha = 1.0 + random.uniform(-self.contrast, self.contrast)
        arr = src.asnumpy().astype(np.float32)
        gray = (arr * self.coef).sum() * (3.0 / arr.size)
        return nd_array(arr * alpha + gray * (1.0 - alpha))


class SaturationJitterAug(Augmenter):
    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation
        self.coef = np.array([[[0.299, 0.587, 0.114]]])

    def __call__(self, src):
        alpha = 1.0 + random.uniform(-self.saturation, self.saturation)
        arr = src.asnumpy().astype(np.float32)
        gray = (arr * self.coef).sum(axis=2, keepdims=True)
        return nd_array(arr * alpha + gray * (1.0 - alpha))


class LightingAug(Augmenter):
    """AlexNet-style PCA noise (parity: image.LightingAug)."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = np.asarray(eigval)
        self.eigvec = np.asarray(eigvec)

    def __call__(self, src):
        alpha = np.random.normal(0, self.alphastd, size=(3,))
        rgb = np.dot(self.eigvec * alpha, self.eigval)
        return nd_array(src.asnumpy().astype(np.float32) + rgb)


class ColorJitterAug(Augmenter):
    def __init__(self, brightness, contrast, saturation):
        super().__init__(brightness=brightness, contrast=contrast,
                         saturation=saturation)
        self.augs = []
        if brightness > 0:
            self.augs.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            self.augs.append(ContrastJitterAug(contrast))
        if saturation > 0:
            self.augs.append(SaturationJitterAug(saturation))

    def __call__(self, src):
        random.shuffle(self.augs)
        for aug in self.augs:
            src = aug(src)
        return src


class RandomOrderAug(Augmenter):
    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        # private permutation: self.ts is shared across decode worker
        # threads (ImageIter preprocess_threads), so shuffling it in
        # place would corrupt a concurrent iteration
        for t in random.sample(self.ts, len(self.ts)):
            src = t(src)
        return src


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, pca_noise=0, inter_method=2):
    """(parity: image.CreateAugmenter)"""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        auglist.append(RandomSizedCropAug(crop_size, 0.08, (3.0 / 4.0,
                                                            4.0 / 3.0),
                                          inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if pca_noise > 0:
        eigval = np.array([55.46, 4.794, 1.148])
        eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                           [-0.5808, -0.0045, -0.8140],
                           [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    if mean is not None and len(np.atleast_1d(mean)):
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter(DataIter):
    """Pure-python image iterator over RecordIO or an image list
    (parity: image.ImageIter:999)."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root="",
                 shuffle=False, part_index=0, num_parts=1, aug_list=None,
                 imglist=None, data_name="data", label_name="softmax_label",
                 preprocess_threads=None, **kwargs):
        super().__init__(batch_size)
        if preprocess_threads is None:
            from ..base import get_env
            preprocess_threads = get_env("MXNET_CPU_WORKER_NTHREADS", 0, int)
        # decode+augment worker pool (parity: iter_image_recordio_2.cc's
        # multithreaded OpenCV decode, :660-760). PIL releases the GIL
        # during JPEG decode, so threads scale on multi-core hosts; the
        # record scan stays serial (it is two orders of magnitude
        # cheaper). 0/1 = decode inline.
        self._pool = None
        if int(preprocess_threads) > 1:
            import concurrent.futures
            self._pool = concurrent.futures.ThreadPoolExecutor(
                int(preprocess_threads))
        if len(data_shape) != 3 or data_shape[0] not in (1, 3):
            raise MXNetError("data_shape must be (C, H, W)")
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.imgrec = None
        self.imglist = {}
        self.seq = []
        if path_imgrec:
            from .. import recordio
            idx_path = os.path.splitext(path_imgrec)[0] + ".idx"
            if os.path.exists(idx_path):
                self.imgrec = recordio.MXIndexedRecordIO(idx_path,
                                                         path_imgrec, "r")
                self.seq = list(self.imgrec.keys)
            else:
                self.imgrec = recordio.MXRecordIO(path_imgrec, "r")
                self._records = []
                while True:
                    s = self.imgrec.read()
                    if s is None:
                        break
                    self._records.append(s)
                self.seq = list(range(len(self._records)))
        elif path_imglist or imglist is not None:
            if path_imglist:
                with open(path_imglist) as f:
                    for line in f:
                        parts = line.strip().split("\t")
                        label = np.array([float(x) for x in parts[1:-1]],
                                         np.float32)
                        self.imglist[int(parts[0])] = (label, parts[-1])
            else:
                for i, item in enumerate(imglist):
                    self.imglist[i] = (np.array(item[0], np.float32).reshape(-1),
                                       item[1])
            self.seq = list(self.imglist.keys())
        else:
            raise MXNetError("need path_imgrec, path_imglist, or imglist")
        self.path_root = path_root
        self.shuffle = shuffle
        if num_parts > 1:
            self.seq = self.seq[part_index::num_parts]
        self.auglist = aug_list if aug_list is not None else \
            CreateAugmenter(data_shape, **{k: v for k, v in kwargs.items()
                                           if k in ("resize", "rand_crop",
                                                    "rand_resize",
                                                    "rand_mirror", "mean",
                                                    "std", "brightness",
                                                    "contrast", "saturation",
                                                    "pca_noise")})
        self.cur = 0
        self.reset()

    @property
    def provide_data(self):
        return [DataDesc("data", (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 else \
            (self.batch_size, self.label_width)
        return [DataDesc("softmax_label", shape)]

    def reset(self):
        if self.shuffle:
            random.shuffle(self.seq)
        self.cur = 0

    def next_sample(self):
        if self.cur >= len(self.seq):
            raise StopIteration
        idx = self.seq[self.cur]
        self.cur += 1
        if self.imgrec is not None:
            from .. import recordio
            s = self.imgrec.read_idx(idx) if hasattr(self.imgrec, "read_idx") \
                and getattr(self.imgrec, "idx", None) else self._records[idx]
            header, img = recordio.unpack(s)
            return header.label, img
        label, fname = self.imglist[idx]
        with open(os.path.join(self.path_root, fname), "rb") as f:
            img = f.read()
        return label, img

    def next(self):
        # batch buffers come from the pooled host storage manager and are
        # reused across batches (parity: the reference assembles batches
        # into pooled pinned staging memory before the h2d copy). Each
        # iterator owns a private Resource: the shared round-robin
        # temp_space slots (MXNET_EXEC_NUM_TEMP defaults to 1) could be
        # handed to another consumer mid-assembly. NOTE the buffer is not
        # zeroed; every row [0, batch_size) is written below before use —
        # the partial-final-batch path below clears the tail rows.
        if getattr(self, "_batch_space", None) is None:
            from ..resource import Resource
            from ..context import current_context
            self._batch_space = Resource("temp_space", current_context())
        data_shape = (self.batch_size,) + self.data_shape
        batch_data = self._batch_space.get_space(data_shape, np.float32)
        lshape = (self.batch_size,) if self.label_width == 1 else \
            (self.batch_size, self.label_width)
        batch_label = np.zeros(lshape, np.float32)

        def _decode_into(i, label, s):
            c, h, w = self.data_shape
            raw = np.frombuffer(s, np.uint8)
            if raw.size == c * h * w:          # packed raw tensor
                img = nd_array(raw.reshape(h, w, c) if c != 1
                               else raw.reshape(h, w, 1))
            else:
                img = imdecode(s)
            for aug in self.auglist:
                img = aug(img)
            arr = img.asnumpy()
            if arr.shape[:2] != (h, w):
                arr = imresize(nd_array(arr.astype(np.uint8)), w, h).asnumpy()
            batch_data[i] = arr.transpose(2, 0, 1)
            batch_label[i] = label

        # collect up to batch_size samples; a partial FINAL batch is
        # padded, not dropped (reference image.py ImageIter.next:1160 —
        # pad = batch_size - i, zero-filled tail rows)
        samples = []
        for _ in range(self.batch_size):
            try:
                samples.append(self.next_sample())
            except StopIteration:
                break
        if not samples:
            raise StopIteration
        pad = self.batch_size - len(samples)
        if pad:
            # batch_label is freshly zeroed above; only the pooled,
            # reused data buffer needs its tail rows cleared
            batch_data[len(samples):] = 0.0
        if self._pool is not None:
            list(self._pool.map(
                lambda args: _decode_into(args[0], *args[1]),
                enumerate(samples)))
        else:
            for i, (label, s) in enumerate(samples):
                _decode_into(i, label, s)
        return DataBatch([nd_array(batch_data)], [nd_array(batch_label)],
                         pad=pad)
