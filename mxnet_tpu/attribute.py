"""Attribute scoping for the symbolic API.

Parity: reference ``python/mxnet/attribute.py`` (AttrScope). Symbols
created inside ``with mx.AttrScope(ctx_group='dev1'):`` inherit the
scope's attributes unless overridden per-symbol; nested scopes merge with
inner-wins. The reference uses this for model parallelism (``ctx_group``)
and per-layer ``lr_mult``/``wd_mult`` — here ``ctx_group`` additionally
feeds the mesh-sharding annotations of the executor (an attribute naming
a logical device group maps to a ``jax.sharding`` spec instead of an
explicit device id; see parallel/spmd.py).
"""
from __future__ import annotations

import threading

from .name import _ScopedMeta

__all__ = ["AttrScope"]


class _Current(threading.local):
    def __init__(self):
        self.value = None


class AttrScope(metaclass=_ScopedMeta):
    """Attribute manager for scoping symbol attributes."""

    _current = _Current()

    @classmethod
    def _default(cls):
        return AttrScope()

    def __init__(self, **kwargs):
        self._old_scope = None
        for value in kwargs.values():
            if not isinstance(value, str):
                raise ValueError("Attributes need to be string")
        self._attr = kwargs

    def get(self, attr):
        """Merge the scope's attributes under the user's ``attr`` dict."""
        if self._attr:
            ret = self._attr.copy()
            if attr:
                ret.update(attr)
            return ret
        return attr if attr else {}

    def __enter__(self):
        self._old_scope = AttrScope.current
        attr = self._old_scope._attr.copy()
        attr.update(self._attr)
        self._attr = attr
        AttrScope._current.value = self
        return self

    def __exit__(self, ptype, value, trace):
        assert self._old_scope is not None
        AttrScope._current.value = self._old_scope
