"""User-defined operators in Python.

Parity: reference ``python/mxnet/operator.py`` (CustomOp:418,
CustomOpProp:464, register:598; C side src/operator/custom/ runs the
python callbacks on a dedicated thread, async-safe). TPU-native design:
the python forward/backward run as host callbacks via
``jax.pure_callback`` — so a Custom op works both eagerly AND inside
jitted graphs/executors (XLA inserts the host round-trip), which is
the same contract the reference's async custom-op thread provided.
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .base import MXNetError
from .ops.registry import register as _register_op, get_op
from .ndarray.ndarray import NDArray, array as nd_array

__all__ = ["CustomOp", "CustomOpProp", "register", "get_all_registered"]

_custom_props = {}


class CustomOp:
    """Base class for custom op implementations (parity: operator.CustomOp)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst, req, src):
        """(parity: CustomOp.assign)"""
        if req in ("null",):
            return
        if req in ("write", "inplace"):
            dst[:] = src
        elif req == "add":
            dst[:] = dst + src


class CustomOpProp:
    """Metadata + factory for a custom op (parity: operator.CustomOpProp)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def infer_shape(self, in_shape):
        return in_shape, (in_shape[0],) * len(self.list_outputs()), []

    def infer_type(self, in_type):
        return in_type, [in_type[0]] * len(self.list_outputs()), []

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def need_top_grad(self):
        return self.need_top_grad_

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return CustomOp()


def register(reg_name):
    """(parity: mx.operator.register) — also registers into the main op
    registry so nd.Custom / sym.Custom dispatch by op_type."""

    def do_register(prop_cls):
        _custom_props[reg_name] = prop_cls
        return prop_cls

    return do_register


def get_all_registered():
    return dict(_custom_props)


def _custom_impl(*inputs, op_type=None, **params):
    """The 'Custom' op function: host-callback forward with custom-vjp
    host-callback backward."""
    if op_type not in _custom_props:
        raise MXNetError("custom op %r is not registered" % op_type)
    prop = _custom_props[op_type](**{k: str(v) for k, v in params.items()})
    in_shapes = [tuple(x.shape) for x in inputs]
    ishapes, oshapes, ashapes = prop.infer_shape([list(s) for s in in_shapes])
    out_structs = tuple(jax.ShapeDtypeStruct(tuple(s), inputs[0].dtype)
                        for s in oshapes)
    n_out = len(out_structs)

    def host_forward(*arrs):
        in_nd = [nd_array(np.asarray(a)) for a in arrs]
        out_nd = [nd_array(np.zeros(tuple(s), np.asarray(arrs[0]).dtype))
                  for s in oshapes]
        op = prop.create_operator(None, in_shapes, [a.dtype for a in arrs])
        op.forward(is_train=True, req=["write"] * n_out, in_data=in_nd,
                   out_data=out_nd, aux=[])
        outs = tuple(o.asnumpy() for o in out_nd)
        return outs if n_out > 1 else outs[0]

    def host_backward(*arrs):
        # arrs = out_grads + inputs + outputs
        ogs = [nd_array(np.asarray(a)) for a in arrs[:n_out]]
        ins = [nd_array(np.asarray(a)) for a in arrs[n_out:n_out + len(inputs)]]
        outs = [nd_array(np.asarray(a)) for a in arrs[n_out + len(inputs):]]
        igs = [nd_array(np.zeros(s, np.asarray(arrs[0]).dtype))
               for s in in_shapes]
        op = prop.create_operator(None, in_shapes,
                                  [np.asarray(a).dtype for a in arrs])
        op.backward(req=["write"] * len(inputs), out_grad=ogs, in_data=ins,
                    out_data=outs, in_grad=igs, aux=[])
        res = tuple(g.asnumpy() for g in igs)
        return res if len(inputs) > 1 else res[0]

    @jax.custom_vjp
    def _run(*ins):
        out = jax.pure_callback(host_forward, out_structs if n_out > 1
                                else out_structs[0], *ins)
        return out

    def _run_fwd(*ins):
        out = _run(*ins)
        return out, (ins, out)

    def _run_bwd(res, g):
        ins, outs = res
        outs_t = outs if isinstance(outs, tuple) else (outs,)
        g_t = g if isinstance(g, tuple) else (g,)
        in_structs = tuple(jax.ShapeDtypeStruct(tuple(s), ins[0].dtype)
                           for s in in_shapes)
        grads = jax.pure_callback(host_backward,
                                  in_structs if len(ins) > 1 else in_structs[0],
                                  *(tuple(g_t) + tuple(ins) + tuple(outs_t)))
        return grads if isinstance(grads, tuple) else (grads,)

    _run.defvjp(_run_fwd, _run_bwd)
    return _run(*inputs)


_register_op("Custom", nin=-1, defaults={"op_type": None})(_custom_impl)

# inject into the already-generated nd/sym namespaces (this module imports
# after they are populated)
from . import ndarray as _nd_mod            # noqa: E402
from .ndarray import register as _nd_reg    # noqa: E402
from . import symbol as _sym_mod            # noqa: E402
from .symbol import register as _sym_reg    # noqa: E402
_nd_mod.Custom = _nd_reg.make_op_func(get_op("Custom"))
_sym_mod.Custom = _sym_reg.make_sym_func(get_op("Custom"))


# ---------------------------------------------------------------------------
# Legacy python-op classes (parity: operator.py PythonOp:37, NumpyOp:144,
# NDArrayOp:246 — pre-CustomOp API, kept for old user code; bridged onto
# the CustomOp machinery, so they work eagerly and under jit)
# ---------------------------------------------------------------------------

class PythonOp:
    """Base class of legacy python operators (parity: operator.PythonOp)."""

    def __init__(self, need_top_grad=True):
        self.info_ = None
        self.need_top_grad_ = need_top_grad

    def __call__(self, *args, **kwargs):
        return self.get_symbol(*args, **kwargs)

    def get_symbol(self, *args, **kwargs):
        raise NotImplementedError

    def forward(self, in_data, out_data):
        raise NotImplementedError

    def backward(self, out_grad, in_data, out_data, in_grad):
        raise MXNetError("backward is not implemented")

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]]

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def need_top_grad(self):
        return self.need_top_grad_


class _LegacyAdapter(CustomOp):
    """CustomOp running a legacy PythonOp's numpy/NDArray callbacks."""

    def __init__(self, legacy, as_numpy):
        self._legacy = legacy
        self._np = as_numpy

    def _unwrap(self, xs):
        # numpy mode hands the legacy op WRITABLE buffers (asnumpy views
        # of device arrays are read-only); results copy back via dst[:]
        return [np.array(x.asnumpy()) if self._np else x for x in xs]

    def forward(self, is_train, req, in_data, out_data, aux):
        ins = self._unwrap(in_data)
        outs = self._unwrap(out_data)
        self._legacy.forward(in_data=ins, out_data=outs)
        if self._np:
            for dst, src in zip(out_data, outs):
                dst[:] = src

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        ogs = self._unwrap(out_grad)
        ins = self._unwrap(in_data)
        outs = self._unwrap(out_data)
        igs = self._unwrap(in_grad)
        self._legacy.backward(out_grad=ogs, in_data=ins, out_data=outs,
                              in_grad=igs)
        if self._np:
            for dst, src in zip(in_grad, igs):
                dst[:] = src


def _legacy_get_symbol(legacy, as_numpy, args, kwargs):
    class _Prop(CustomOpProp):
        def __init__(self, **_):
            super().__init__(need_top_grad=legacy.need_top_grad())

        def infer_shape(self, in_shape):
            shapes = legacy.infer_shape(in_shape)
            ishapes, oshapes = shapes[0], shapes[1]
            return ishapes, oshapes, []

        def list_arguments(self):
            return legacy.list_arguments()

        def list_outputs(self):
            return legacy.list_outputs()

        def create_operator(self, ctx, in_shapes, in_dtypes):
            return _LegacyAdapter(legacy, as_numpy)

    reg_name = "_legacy_%s_%x" % (type(legacy).__name__, id(legacy))
    register(reg_name)(_Prop)
    from . import symbol as _s
    return _s.Custom(*args, op_type=reg_name, **kwargs)


class NumpyOp(PythonOp):
    """Legacy numpy operator (parity: operator.NumpyOp) — forward/backward
    receive numpy arrays."""

    def get_symbol(self, *args, **kwargs):
        return _legacy_get_symbol(self, True, args, kwargs)


class NDArrayOp(PythonOp):
    """Legacy NDArray operator (parity: operator.NDArrayOp) —
    forward/backward receive NDArrays."""

    def get_symbol(self, *args, **kwargs):
        return _legacy_get_symbol(self, False, args, kwargs)

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad():
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps


__all__ += ["PythonOp", "NumpyOp", "NDArrayOp"]
