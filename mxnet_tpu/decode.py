"""Slot-based continuous batching for autoregressive decode.

The serving tier (``serving.InferenceEngine``) batches STATELESS
forwards: every request is one row, every dispatch forgets. The
workload that actually melts production serving — autoregressive
decode, where each live sequence owns device-resident state (an LSTM
hidden/cell pair, a KV-cached attention history) and produces ONE
token per model step — needs the opposite shape: state stays put,
tokens stream, and the batch composition changes every step.

``DecodeEngine`` runs a fixed pool of S SLOTS. Each slot holds one
live sequence's state inside a cache PYTREE whose leaves are
slot-major ``(S, ...)`` arrays. Sequences are admitted and retired
PER STEP (continuous batching): a finishing sequence frees its slot
at the step boundary and a queued prompt takes it immediately, so
the decode batch stays full while a static whole-batch decoder would
idle its finished lanes until the longest member completes.

Two AOT program families compile through
``executor._InstrumentedProgram`` (cards, ledger, compile-cache and
the recompile diagnosis for free):

* **prefill** — one program per bucketed PROMPT length: gathers one
  slot's state, teacher-forces the padded prompt, writes the slot's
  cache and returns the first generated token.
* **decode** — one program per bucketed ACTIVE-SLOT count: gathers
  the active slots by index, advances every one of them ONE token in
  a single donated-buffer dispatch (the cache pool is donated in and
  out — steady state allocates nothing), scatters the new state back
  and returns the batch's next tokens. Padding lanes carry slot id S
  (out of range): their gathers clip harmlessly and their scatters
  ``mode="drop"``.

Both families bucket their dynamic dimension (prompt length, active
count) to powers of two, so a warmed engine's steady state records
ZERO ``jit_compile`` spans — the decode-smoke lane gates on exactly
that.

The cache pytree is laid out by the SAME ``PartitionRules`` engine
training and serving use (``ring_attention.DECODE_PARTITION_RULES``
names the head-sharded attention layout): a model + cache exceeding
one chip's HBM decodes from N chips without replication. Every cache
leaf is charged per-shard to the buffer ledger under a ``kv_cache``
kind via an engine-held anchor (``spmd.commit_state``) — donation
rebinds the array wrapper every step, so the anchor is what keeps an
OOM postmortem naming the cache instead of an anonymous buffer.

Overload semantics follow serving's: bounded admission
(shed/``QueueOverflow`` or block), per-sequence deadlines enforced
while queued (shed at admission when the slot pool is saturated past
deadline) and while decoding, a transient-failure retry budget, and
a circuit breaker over consecutive dispatch failures. Each request's
causal ``req_id`` rides the whole token stream: ``serve_wait`` →
``serve_prefill`` → ``serve_decode_step`` × N → ``serve_detokenize``
→ ``serve_request`` chain as flow arrows in the perfetto trace.
"""
from __future__ import annotations

import collections
import queue
import threading
import time

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .base import MXNetError
from . import telemetry
from . import flight
from .executor import _InstrumentedProgram, record_dispatch
from .serving import (DeadlineExceeded, QueueOverflow, CircuitOpen,
                      EngineClosed, bucket_sizes, _quiet_recompile,
                      _is_transient, _Request, _SHUTDOWN)
from .parallel.ring_attention import attention, decode_attention

__all__ = ["DecodeEngine", "DecodeResult", "AttentionDecodeCell",
           "LSTMDecodeCell", "DeadlineExceeded", "QueueOverflow",
           "CircuitOpen", "EngineClosed"]


DecodeResult = collections.namedtuple("DecodeResult", ["tokens", "logits"])
DecodeResult.__doc__ = """One finished sequence: ``tokens`` is the
generated id list (prompt excluded, EOS included when hit);
``logits`` is the per-token ``(len(tokens), vocab)`` array when the
engine runs with ``keep_logits=True``, else None."""


# -- decode cells -----------------------------------------------------------
#
# A cell is the pure per-sequence model the engine batches: it owns the
# cache LAYOUT (slot-major leaf shapes + names — the names are what the
# partition rules match) and two jit-pure functions over ONE slot's
# state. The engine vmaps ``step`` over the gathered active slots.

class AttentionDecodeCell:
    """Single-layer KV-cached attention LM — the transformer-shaped
    decode workload. Cache leaves ``cache/k``/``cache/v`` are
    ``(S, H, T, D)``; under ``ring_attention.DECODE_PARTITION_RULES``
    heads shard over ``mp`` together with the head-major projection
    params, so per-token decode needs no resharding."""

    def __init__(self, vocab, embed, heads, head_dim, max_len,
                 dtype=np.float32):
        self.vocab = int(vocab)
        self.embed = int(embed)
        self.heads = int(heads)
        self.head_dim = int(head_dim)
        self.max_len = int(max_len)
        self.dtype = np.dtype(dtype)

    def cache_spec(self, slots):
        shp = (int(slots), self.heads, self.max_len, self.head_dim)
        return {"cache/k": (shp, self.dtype), "cache/v": (shp, self.dtype)}

    def init_params(self, seed=0):
        """Deterministic host-side parameter draw (probes and tests)."""
        rng = np.random.RandomState(seed)
        E, H, D, V = self.embed, self.heads, self.head_dim, self.vocab

        def u(fan, *shape):
            s = 1.0 / np.sqrt(fan)
            return rng.uniform(-s, s, shape).astype(self.dtype)

        return {
            "embed": (rng.standard_normal((V, E)) * 0.05).astype(self.dtype),
            "wq": u(E, E, H, D), "wk": u(E, E, H, D), "wv": u(E, E, H, D),
            "wo": u(H * D, H, D, E),
            "head": u(E, E, V),
        }

    def prefill(self, params, state, tokens, length):
        """Teacher-forced prompt pass over ONE slot: writes k/v for the
        padded prompt positions ``[0, L)``, attends causally, returns
        the new state and the logits at position ``length - 1`` (pad
        positions past ``length`` are never attended — the causal mask
        covers them during prefill, the running-length mask afterwards).
        """
        L = tokens.shape[0]
        x = params["embed"][tokens]                        # (L, E)
        q = jnp.einsum("le,ehd->lhd", x, params["wq"])
        k = jnp.einsum("le,ehd->lhd", x, params["wk"])
        v = jnp.einsum("le,ehd->lhd", x, params["wv"])
        kh, vh = jnp.swapaxes(k, 0, 1), jnp.swapaxes(v, 0, 1)  # (H, L, D)
        kc = state["cache/k"].at[:, :L].set(kh)
        vc = state["cache/v"].at[:, :L].set(vh)
        out = attention(jnp.swapaxes(q, 0, 1)[None], kh[None], vh[None],
                        causal=True)[0]                    # (H, L, D)
        o = lax.dynamic_index_in_dim(out, length - 1, axis=1,
                                     keepdims=False)       # (H, D)
        xl = lax.dynamic_index_in_dim(x, length - 1, axis=0,
                                      keepdims=False)      # (E,)
        h = xl + jnp.einsum("hd,hde->e", o, params["wo"])
        return {"cache/k": kc, "cache/v": vc}, h @ params["head"]

    def step(self, params, state, token, pos):
        """One decode step for ONE slot: write this token's k/v at
        ``pos``, attend over ``[0, pos]``, return new state + logits."""
        x = params["embed"][token]                         # (E,)
        q = jnp.einsum("e,ehd->hd", x, params["wq"])
        k = jnp.einsum("e,ehd->hd", x, params["wk"])
        v = jnp.einsum("e,ehd->hd", x, params["wv"])
        kc = state["cache/k"].at[:, pos].set(k)
        vc = state["cache/v"].at[:, pos].set(v)
        att = decode_attention(q, kc, vc, pos + 1)         # (H, D)
        h = x + jnp.einsum("hd,hde->e", att, params["wo"])
        return {"cache/k": kc, "cache/v": vc}, h @ params["head"]


class LSTMDecodeCell:
    """Single-layer LSTM LM — the RNN-shaped decode workload. The
    cache is just the hidden/cell pair per slot (``cache/h``,
    ``cache/c``: ``(S, hidden)``); the step math is
    ``rnn.rnn_cell.lstm_decode_step`` (same gate packing as the
    symbolic ``LSTMCell``)."""

    def __init__(self, vocab, embed, hidden, max_len,
                 dtype=np.float32):
        self.vocab = int(vocab)
        self.embed = int(embed)
        self.hidden = int(hidden)
        self.max_len = int(max_len)
        self.dtype = np.dtype(dtype)

    def cache_spec(self, slots):
        shp = (int(slots), self.hidden)
        return {"cache/h": (shp, self.dtype), "cache/c": (shp, self.dtype)}

    def init_params(self, seed=0):
        rng = np.random.RandomState(seed)
        E, Hid, V = self.embed, self.hidden, self.vocab

        def u(fan, *shape):
            s = 1.0 / np.sqrt(fan)
            return rng.uniform(-s, s, shape).astype(self.dtype)

        return {
            "embed": (rng.standard_normal((V, E)) * 0.05).astype(self.dtype),
            "wx": u(E, E, 4 * Hid), "wh": u(Hid, Hid, 4 * Hid),
            "b": np.zeros(4 * Hid, self.dtype),
            "head": u(Hid, Hid, V),
        }

    def step(self, params, state, token, pos):
        from .rnn.rnn_cell import lstm_decode_step
        x = params["embed"][token]
        h, c = lstm_decode_step(x, state["cache/h"], state["cache/c"],
                                params["wx"], params["wh"], params["b"])
        return {"cache/h": h, "cache/c": c}, h @ params["head"]

    def prefill(self, params, state, tokens, length):
        """Scan the padded prompt; state updates freeze past ``length``
        so pad tokens never touch the carried h/c; logits come from
        position ``length - 1``. The incoming state is the slot's STALE
        h/c from its previous occupant — unlike the KV cache (where the
        running-length mask hides stale positions) an RNN carry has no
        mask, so prefill must restart it from zero."""
        state = jax.tree_util.tree_map(jnp.zeros_like, state)
        L = tokens.shape[0]

        def body(st, inp):
            tok, i = inp
            new_st, logits = self.step(params, st, tok, i)
            keep = i < length
            st = jax.tree_util.tree_map(
                lambda n, o: jnp.where(keep, n, o), new_st, st)
            return st, logits

        st, all_logits = lax.scan(body, state,
                                  (tokens, jnp.arange(L, dtype=jnp.int32)))
        logits = lax.dynamic_index_in_dim(all_logits, length - 1, axis=0,
                                          keepdims=False)
        return st, logits


# -- the engine -------------------------------------------------------------

class _Anchor:
    """Engine-held ledger anchor: one per cache leaf. The kv_cache
    charge is keyed on this object (``spmd.commit_state``) so it
    survives the per-step donation rebinds and retires when the engine
    is garbage-collected."""
    __slots__ = ("__weakref__",)


class _DecodeRequest(_Request):
    """One live sequence. Reuses serving's ``_Request`` span/req-id
    construction (serve_wait + serve_request entered at submit with the
    causal ``req_id`` ctx) and adds the decode-side cursor: slot index,
    next input token, its cache position, and the output accumulators.
    """

    __slots__ = ("prompt", "max_new", "eos_id", "slot", "next_token",
                 "pos", "out_tokens", "out_logits")

    def __init__(self, prompt, max_new, eos_id, deadline=None):
        super().__init__(arrays=None, rows=1, deadline=deadline)
        self.prompt = prompt             # np.int32 (len,)
        self.max_new = max_new
        self.eos_id = eos_id
        self.slot = None                 # guarded by: engine._lock
        self.next_token = None           # scheduler thread only
        self.pos = None                  # scheduler thread only
        self.out_tokens = []             # scheduler thread only
        self.out_logits = []             # scheduler thread only

    @property
    def finished(self):
        if len(self.out_tokens) >= self.max_new:
            return True
        return self.eos_id is not None and self.out_tokens \
            and self.out_tokens[-1] == self.eos_id


class DecodeEngine:
    """Continuous-batching autoregressive decode over a slot pool.

    Parameters
    ----------
    cell : decode cell (``AttentionDecodeCell`` / ``LSTMDecodeCell`` or
        anything with ``cache_spec``/``prefill``/``step`` and a
        ``max_len``) — the pure per-sequence model
    params : dict name -> array — host parameters; committed
        device-resident (rule-sharded under ``partition_rules``)
    slots : int — the pool size S: max concurrently-decoding sequences
    max_prompt_len : int — longest admissible prompt; prompt buckets
        are the powers of two up to it (default: half the cell's
        ``max_len``)
    max_new_tokens : int — per-request generation cap default
        (``submit(max_new_tokens=)`` overrides); prompt + new tokens
        must fit the cell's ``max_len``
    eos_id : int | None — default early-stop token id
    ctx : Context — single device (default: current context)
    partition_rules / mesh_axes / contexts : the rule-sharded mesh
        wiring, exactly as ``InferenceEngine``: the cache pytree and
        the params commit by first-match rules
        (``ring_attention.DECODE_PARTITION_RULES`` names the
        head-sharded attention layout)
    max_queue : int | None — bounded admission: sequences allowed to
        WAIT for a slot; ``None`` = unbounded
    deadline_ms : float | None — engine-wide default deadline for one
        sequence's whole submit→resolve life; enforced while queued
        (saturated slot pool sheds past-deadline prompts at admission)
        and at every decode step
    overload : "shed" | "block" — full-queue policy, as serving
    retry_budget / retry_backoff_ms — transient dispatch retries
    breaker_threshold / breaker_reset_s — dispatch circuit breaker
    warmup : bool — build every prefill/decode bucket program at
        construction (zero steady-state compiles)
    keep_logits : bool — fetch and return per-token logits rows (the
        bit-exactness harness; costs one extra d2h per step)
    telemetry_logger : optional ``callback.TelemetryLogger`` — the
        engine calls ``log_decode`` each step window
    """

    def __init__(self, cell, params, slots=8, max_prompt_len=None,
                 max_new_tokens=64, eos_id=None, ctx=None,
                 partition_rules=None, mesh_axes=None, contexts=None,
                 max_queue=None, deadline_ms=None, overload="shed",
                 retry_budget=2, retry_backoff_ms=5.0,
                 breaker_threshold=5, breaker_reset_s=30.0,
                 warmup=True, keep_logits=False, telemetry_logger=None):
        self.cell = cell
        self.slots = int(slots)
        if self.slots < 1:
            raise MXNetError("decode: slots must be >= 1")
        self.max_len = int(cell.max_len)
        self.max_prompt_len = int(max_prompt_len) if max_prompt_len \
            else max(1, self.max_len // 2)
        if self.max_prompt_len > self.max_len:
            raise MXNetError("decode: max_prompt_len %d exceeds the "
                             "cell's max_len %d"
                             % (self.max_prompt_len, self.max_len))
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = eos_id
        self.prompt_buckets = bucket_sizes(self.max_prompt_len)
        self.slot_buckets = bucket_sizes(self.slots)
        if overload not in ("shed", "block"):
            raise MXNetError("decode: overload must be 'shed' or "
                             "'block', got %r" % (overload,))
        self.overload = overload
        self.max_queue = None if max_queue is None else max(1,
                                                            int(max_queue))
        self.deadline_s = None if deadline_ms is None \
            else float(deadline_ms) / 1e3
        self._retry_budget = max(0, int(retry_budget))
        self._retry_backoff_s = max(0.0, float(retry_backoff_ms)) / 1e3
        self._breaker_threshold = max(0, int(breaker_threshold))
        self._breaker_reset_s = float(breaker_reset_s)
        self._keep_logits = bool(keep_logits)
        self._logger = telemetry_logger

        # device / mesh wiring — same shape as serving's
        if ctx is None:
            from .context import current_context
            ctx = current_context()
        self._ctx = ctx
        self._device = ctx.jax_device()
        self._mesh_spec = None
        if partition_rules is not None or mesh_axes:
            from .parallel import mesh as _pmesh, spmd as _spmd
            from .parallel.partition import PartitionRules
            if partition_rules is not None \
                    and not isinstance(partition_rules, PartitionRules):
                partition_rules = PartitionRules(partition_rules)
            ctxs = list(contexts) if contexts else [self._ctx]
            mesh = _pmesh.mesh_from_contexts(
                ctxs, axes=dict(mesh_axes) if mesh_axes
                else {_spmd.DP_AXIS: 1, _spmd.MP_AXIS: -1})
            self._mesh_spec = _spmd.rule_spec(mesh, partition_rules)

        # commit params (rule-sharded on a mesh, plain put otherwise)
        self._params = {n: self._put_param(n, np.asarray(v))
                        for n, v in params.items()}
        # the cache pool: slot-major leaves, rule-sharded, charged to
        # the ledger under kind="kv_cache" via per-leaf anchor objects
        # that live as long as the engine (donation rebinds the array
        # wrapper every step — a wrapper-keyed charge would vanish
        # after the first step)
        self._cache_anchors = {n: _Anchor()
                               for n in cell.cache_spec(self.slots)}
        self._cache = self._make_cache()

        # the two AOT program families — the engine's only compile
        # sites, both through the instrumented wrapper (jit-site rule)
        self._prefill_prog = _InstrumentedProgram(
            "decode_prefill", self._make_prefill_impl(),
            jit_kwargs={"donate_argnums": (1,)},
            argnames=("params", "cache", "slot", "tokens", "length"),
            meta={"slots": self.slots, "cell": type(cell).__name__})
        self._prefill_prog.on_compile = self._prefill_compiled
        self._decode_prog = _InstrumentedProgram(
            "decode_step", self._make_decode_impl(),
            jit_kwargs={"donate_argnums": (1,)},
            argnames=("params", "cache", "slot_ids", "tokens",
                      "positions"),
            meta={"slots": self.slots, "cell": type(cell).__name__})

        self._lock = threading.Lock()
        # admission backpressure + close wakeup: Condition over the
        # SAME lock — ``with self._space:`` satisfies every
        # ``guarded by: self._lock`` annotation here
        self._space = threading.Condition(self._lock)
        self._stats = collections.Counter()   # guarded by: self._lock
        self._queued = 0                 # guarded by: self._lock
        self._slot_table = [None] * self.slots   # guarded by: self._lock
        self._breaker_open_at = None     # guarded by: self._lock
        self._consecutive_failures = 0   # guarded by: self._lock
        self._closed = False             # guarded by: self._lock
        self._close_done = False         # guarded by: self._lock
        self._q = queue.Queue()
        self._thread = threading.Thread(target=self._decode_loop,
                                        name="mxtpu-decode-sched",
                                        daemon=True)
        self._thread.start()
        flight.register_engine(self)
        if warmup:
            self.warmup()

    # -- placement ----------------------------------------------------------
    def _put_param(self, name, raw):
        if self._mesh_spec is not None:
            from .parallel import spmd as _spmd
            return _spmd.shard_put(
                raw, self._mesh_spec.param_sharding(name, tuple(raw.shape)))
        return jax.device_put(raw, self._device)

    def _make_cache(self):
        """Fresh zeroed slot pool, committed per the partition rules and
        charged (per-shard bytes) to the ledger as ``kv_cache``. The
        anchors' replace-keyed charges make a REBUILD (cache re-init
        after a poisoned dispatch) update instead of double-count."""
        from .parallel import spmd as _spmd
        cache = {}
        for name, (shape, dtype) in self.cell.cache_spec(self.slots).items():
            raw = np.zeros(shape, dtype)
            sharding = self._mesh_spec.param_sharding(name, shape) \
                if self._mesh_spec is not None else self._device
            cache[name] = _spmd.commit_state(
                raw, sharding, self._cache_anchors[name], kind="kv_cache")
        return cache

    def partition_summary(self):
        """JSON-safe layout description (None without a mesh) — stamped
        onto every bucket program card at warmup."""
        if self._mesh_spec is None:
            return None
        from .parallel.partition import partition_summary as _summary
        shapes = {n: tuple(v.shape) for n, v in self._params.items()}
        shapes.update({n: s for n, (s, _)
                       in self.cell.cache_spec(self.slots).items()})
        return _summary(self._mesh_spec, shapes)

    # -- program bodies ------------------------------------------------------
    def _make_prefill_impl(self):
        cell = self.cell

        def prefill_impl(params, cache, slot, tokens, length):
            state = jax.tree_util.tree_map(lambda l: l[slot], cache)
            state, logits = cell.prefill(params, state, tokens, length)
            cache = jax.tree_util.tree_map(
                lambda l, n: l.at[slot].set(n.astype(l.dtype)),
                cache, state)
            tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return cache, tok, logits

        return prefill_impl

    def _make_decode_impl(self):
        cell = self.cell

        def decode_impl(params, cache, ids, toks, pos):
            # padding lanes carry id == S: the gather clips to a live
            # slot (read-only, harmless), the scatter drops out of
            # bounds — one program per BUCKET, not per active set
            state = jax.tree_util.tree_map(
                lambda l: jnp.take(l, ids, axis=0, mode="clip"), cache)
            state, logits = jax.vmap(
                cell.step, in_axes=(None, 0, 0, 0))(params, state, toks,
                                                    pos)
            cache = jax.tree_util.tree_map(
                lambda l, n: l.at[ids].set(n.astype(l.dtype),
                                           mode="drop"),
                cache, state)
            toks_out = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return cache, toks_out, logits

        return decode_impl

    def _prefill_compiled(self, card):
        telemetry.counter_inc("decode.prefill_compiles")

    # -- buckets / cards -----------------------------------------------------
    def prompt_bucket_for(self, n):
        for b in self.prompt_buckets:
            if b >= n:
                return b
        raise MXNetError("decode: prompt length %d exceeds "
                         "max_prompt_len=%d" % (n, self.max_prompt_len))

    def slot_bucket_for(self, n):
        for b in self.slot_buckets:
            if b >= n:
                return b
        raise MXNetError("decode: %d active slots exceed the pool (%d)"
                         % (n, self.slots))

    def program_cards(self):
        """{card_id: card} for BOTH of this engine's program families —
        one card per (family, bucket) signature."""
        out = {}
        for prog in (self._prefill_prog, self._decode_prog):
            entry = prog.entry
            out.update({k: c for k, c in telemetry.programs().items()
                        if k == entry or k.startswith(entry + "/")})
        return out

    def warmup(self):
        """Build every (prompt bucket x prefill) and (slot bucket x
        decode) program WITHOUT dispatching — after this, steady-state
        traffic is all AOT cache hits and records zero ``jit_compile``
        spans. Planned multi-signature compiles, so the recompile-storm
        warning is quieted for the duration."""
        zlen = np.int32(1)
        zslot = np.int32(0)
        with _quiet_recompile(self._prefill_prog):
            for lb in self.prompt_buckets:
                self._prefill_prog.build(
                    self._params, self._cache, zslot,
                    np.zeros(lb, np.int32), zlen)
        with _quiet_recompile(self._decode_prog):
            for sb in self.slot_buckets:
                pad = np.full(sb, self.slots, np.int32)
                z = np.zeros(sb, np.int32)
                self._decode_prog.build(self._params, self._cache, pad,
                                        z, z)
        layout = self.partition_summary()
        if layout is not None:
            for cid in self.program_cards():
                telemetry.card_annotate(cid, partition=layout)

    # -- request surface -----------------------------------------------------
    def submit(self, prompt, max_new_tokens=None, deadline_ms=None,
               eos_id=None):   # mxlint: hot
        """Enqueue one sequence; returns a Future resolving to a
        ``DecodeResult``. ``prompt`` is a 1-D int token-id array
        (1 <= len <= max_prompt_len; prompt + new tokens must fit the
        cell's ``max_len``). ``deadline_ms`` bounds the whole
        submit→resolve life: expired waiters shed at admission when
        the slot pool is saturated, and a decoding sequence past its
        deadline sheds at the step boundary (``DeadlineExceeded``).
        A full bounded queue sheds (``QueueOverflow``) or blocks per
        the ``overload`` policy; an open breaker fast-fails
        (``CircuitOpen``)."""
        if self._closed:   # mxlint: disable=lock-discipline -- lock-free fast path; re-checked under the lock before enqueue
            raise EngineClosed("decode: engine is closed")
        if self._breaker_tripped():
            with self._lock:
                self._stats["breaker_fastfail"] += 1
                consecutive = self._consecutive_failures
            telemetry.counter_inc("decode.breaker_fastfail")
            raise CircuitOpen(
                "decode: breaker open after %d consecutive dispatch "
                "failures — fast-failing instead of queuing onto a "
                "failing backend (retries again %.1fs after the trip)"
                % (consecutive, self._breaker_reset_s))
        prompt = np.ascontiguousarray(np.asarray(prompt, np.int32))
        if prompt.ndim != 1 or not prompt.size:
            raise MXNetError("decode: prompt must be a non-empty 1-D "
                             "token-id array, got shape %s"
                             % (prompt.shape,))
        if prompt.size > self.max_prompt_len:
            raise MXNetError("decode: prompt length %d exceeds "
                             "max_prompt_len=%d"
                             % (prompt.size, self.max_prompt_len))
        max_new = self.max_new_tokens if max_new_tokens is None \
            else int(max_new_tokens)
        if max_new < 1:
            raise MXNetError("decode: max_new_tokens must be >= 1")
        if prompt.size + max_new > self.max_len:
            raise MXNetError(
                "decode: prompt (%d) + max_new_tokens (%d) exceed the "
                "cell's max_len %d — the cache cannot hold the "
                "sequence" % (prompt.size, max_new, self.max_len))
        dl_s = self.deadline_s if deadline_ms is None \
            else float(deadline_ms) / 1e3
        deadline = None if dl_s is None else time.monotonic() + dl_s
        req = _DecodeRequest(prompt, max_new,
                             self.eos_id if eos_id is None else eos_id,
                             deadline=deadline)

        def _drop_locked(exc, shed=False, deadline_hit=False):
            # admission-rejected: never enqueued, but the spans entered
            # at construction must close and the shed must account.
            # Caller holds self._lock (the _locked-suffix contract).
            req.wait_span.__exit__(None, None, None)
            req.req_span.__exit__(None, None, None)
            if shed:
                self._stats["shed_requests"] += 1
                self._stats["shed.admission"] += 1
                telemetry.counter_inc("decode.shed")
                telemetry.counter_inc("decode.shed.admission")
                telemetry.record_event("decode.shed", req_id=req.req_id,
                                       cause="admission")
                if deadline_hit:
                    telemetry.counter_inc("decode.deadline_exceeded")
            raise exc

        with self._space:
            if self._closed:
                _drop_locked(EngineClosed("decode: engine is closed"))
            while self.max_queue is not None \
                    and self._queued + 1 > self.max_queue:
                if self.overload == "shed":
                    _drop_locked(QueueOverflow(
                        "decode: admission queue full (%d sequences "
                        "waiting for a slot, max_queue=%d) — shedding"
                        % (self._queued, self.max_queue)), shed=True)
                timeout = None if deadline is None \
                    else deadline - time.monotonic()
                if timeout is not None and timeout <= 0 \
                        or not self._space.wait(timeout):
                    _drop_locked(DeadlineExceeded(
                        "decode: deadline expired while blocked on a "
                        "full admission queue (max_queue=%d)"
                        % self.max_queue), shed=True, deadline_hit=True)
                if self._closed:
                    _drop_locked(EngineClosed("decode: engine is "
                                              "closed"))
            self._stats["requests"] += 1
            self._queued += 1
            self._q.put(req)
        telemetry.counter_inc("decode.requests")
        return req.future

    def generate(self, prompt, **kwargs):
        """Blocking convenience: ``submit`` + ``result()``."""
        return self.submit(prompt, **kwargs).result()

    # -- stats / state -------------------------------------------------------
    def stats(self):
        """Engine counters + slot occupancy + the per-token latency
        percentiles + the ledger's view of the cache — what a decode
        health endpoint exports."""
        with self._lock:
            st = dict(self._stats)
            queued = self._queued
            active = sum(1 for s in self._slot_table if s is not None)
            breaker_open = self._breaker_tripped()
            consecutive = self._consecutive_failures
        tok_lat = telemetry.span_stats("serve_decode_step").get(
            "serve_decode_step", {})
        req_lat = telemetry.span_stats("serve_request").get(
            "serve_request", {})
        kv_bytes = self.kv_cache_bytes()
        return {
            "requests": st.get("requests", 0),
            "resolved": st.get("resolved", 0),
            "failed_requests": st.get("failed_requests", 0),
            "shed_requests": st.get("shed_requests", 0),
            "shed_by_cause": {k[len("shed."):]: v for k, v in st.items()
                              if k.startswith("shed.")},
            "tokens": st.get("tokens", 0),
            "steps": st.get("steps", 0),
            "slot_admit": st.get("slot_admit", 0),
            "slot_retire": st.get("slot_retire", 0),
            "queued": queued,
            "max_queue": self.max_queue,
            "overload": self.overload,
            "deadline_ms": None if self.deadline_s is None
            else round(self.deadline_s * 1e3, 3),
            "slots": self.slots,
            "active_slots": active,
            "slot_fill": round(active / self.slots, 4),
            "retries": st.get("retries", 0),
            "dispatch_failures": st.get("dispatch_failures", 0),
            "breaker": {
                "open": breaker_open,
                "threshold": self._breaker_threshold,
                "consecutive_failures": consecutive,
                "trips": st.get("breaker_trips", 0),
                "fastfail": st.get("breaker_fastfail", 0),
            },
            # the ledger interplay: the cache is a NAMED by-kind charge
            # (an OOM postmortem's ledger_top names it, not an
            # anonymous buffer), reported per slot here
            "kv_cache_bytes": kv_bytes,
            "kv_cache_bytes_per_slot": kv_bytes // self.slots,
            "token_latency_ms": {k: tok_lat.get(k) for k in
                                 ("p50_ms", "p95_ms", "p99_ms")}
            if tok_lat else None,
            "request_latency_ms": {k: req_lat.get(k) for k in
                                   ("p50_ms", "p95_ms", "p99_ms")}
            if req_lat else None,
        }

    def kv_cache_bytes(self):
        """Committed device bytes of THIS engine's cache pool —
        per-shard bytes summed over devices, so an mp-sharded pool
        reads 1/mp of replicated. Identical to the figure the pool's
        ``kv_cache`` ledger charge carries, but engine-local (the
        per-context ledger aggregates every engine sharing a mesh)."""
        from .parallel.partition import committed_nbytes
        cache = self._cache        # one racy-but-atomic dict read: the
        # scheduler rebinds the whole dict per step; metadata-only
        # reads on a just-donated leaf are safe
        return int(sum(committed_nbytes(l) for l in cache.values()))

    def overload_state(self):
        """Light lock-held view for the flight recorder's sampler (a
        10 Hz tick must not pay ``stats()``'s percentile sorts)."""
        with self._lock:
            return {
                "queued_rows": self._queued,
                "max_queue_rows": self.max_queue,
                "active_slots": sum(1 for s in self._slot_table
                                    if s is not None),
                "slots": self.slots,
                "breaker_open": self._breaker_tripped(),
                "consecutive_failures": self._consecutive_failures,
                "closed": self._closed,
            }

    def corpus_record(self):
        """One JSON-safe record of measured decode data for the
        persisted card corpus (per-bucket decode cost is planner food).
        None until at least one step ran."""
        from . import compile_cache
        st = self.stats()
        if not st["steps"]:
            return None
        cards = {
            k: {kk: c.get(kk) for kk in
                ("kind", "flops", "bytes_accessed", "peak_bytes",
                 "compile_ms", "deserialize_ms", "source", "dispatches")}
            for k, c in self.program_cards().items()}
        spans = {k: v for k, v in telemetry.span_stats().items()
                 if k in telemetry.DECODE_SPANS}
        return {
            "kind": "decode",
            "ts": time.time(),
            "env": compile_cache.env_meta(),
            "slots": self.slots,
            "slot_buckets": list(self.slot_buckets),
            "prompt_buckets": list(self.prompt_buckets),
            "layout": self.partition_summary(),
            "cell": type(self.cell).__name__,
            "tokens": st["tokens"],
            "steps": st["steps"],
            "requests": st["requests"],
            "kv_cache_bytes": st["kv_cache_bytes"],
            "spans": spans,
            "cards": cards,
        }

    # -- lifecycle -----------------------------------------------------------
    def close(self):
        """Drain and stop: every already-submitted sequence resolves
        (finishes generating, or sheds on its own deadline) before
        close() returns; later submits raise ``EngineClosed``."""
        with self._space:
            already = self._close_done
            self._close_done = True
            if not self._closed:
                self._closed = True
                self._q.put(_SHUTDOWN)
                self._space.notify_all()
        if already:
            return
        self._thread.join()
        try:
            from . import compile_cache
            if compile_cache.corpus_path() is not None:
                rec = self.corpus_record()
                if rec is not None:
                    compile_cache.corpus_append(rec)
        except Exception:
            pass
        if self._logger is not None:
            try:
                self._logger.log_decode(self, force=True)
            except Exception:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- breaker -------------------------------------------------------------
    def _breaker_tripped(self):
        opened = self._breaker_open_at   # mxlint: disable=lock-discipline -- GIL-atomic one-shot read on the submit fast path; stats() re-reads under the lock
        if opened is None:
            return False
        return (time.monotonic() - opened) < self._breaker_reset_s

    def reset_breaker(self):
        """Force the breaker closed (operator override)."""
        with self._lock:
            self._breaker_open_at = None
            self._consecutive_failures = 0

    def _dispatch_failed(self):
        with self._lock:
            self._stats["dispatch_failures"] += 1
            self._consecutive_failures += 1
            consecutive = self._consecutive_failures
            trip = (self._breaker_threshold > 0
                    and consecutive >= self._breaker_threshold)
            if trip:
                self._breaker_open_at = time.monotonic()
                self._stats["breaker_trips"] += 1
        telemetry.counter_inc("decode.dispatch_failures")
        if trip:
            telemetry.counter_inc("decode.breaker_trips")
            telemetry.record_event("decode.breaker_trip",
                                   consecutive=consecutive)
            flight.postmortem("breaker_trip",
                              extra={"engine": self.overload_state(),
                                     "consecutive": consecutive})

    def _dispatch_succeeded(self):
        with self._lock:
            self._consecutive_failures = 0
            self._breaker_open_at = None

    # -- terminal request paths ---------------------------------------------
    def _shed(self, req, cause, exc):
        """Resolve one sequence's future with a structured shed error
        and account it. The spans close on every terminal path (shed
        time is real latency) and the future resolves exactly once."""
        if req.future.done():
            return
        req.wait_span.__exit__(None, None, None)
        req.req_span.__exit__(None, None, None)
        req.future.set_exception(exc)
        with self._lock:
            self._stats["shed_requests"] += 1
            self._stats["shed.%s" % cause] += 1
        telemetry.counter_inc("decode.shed")
        telemetry.counter_inc("decode.shed.%s" % cause)
        telemetry.record_event("decode.shed", req_id=req.req_id,
                               cause=cause)
        if isinstance(exc, DeadlineExceeded):
            telemetry.counter_inc("decode.deadline_exceeded")

    def _fail_requests(self, reqs, exc):
        """Resolve every still-pending member with ``exc``: a failed
        sequence is neither resolved nor shed — without its own counter
        the depth arithmetic would count it queued forever."""
        failed = 0
        for r in reqs:
            if not r.future.done():
                r.wait_span.__exit__(None, None, None)
                r.req_span.__exit__(None, None, None)
                r.future.set_exception(exc)
                failed += 1
        if failed:
            with self._lock:
                self._stats["failed_requests"] += failed
            telemetry.counter_inc("decode.failed_requests", failed)

    def _retire(self, req):
        """One sequence finished: assemble the result on the host
        (``serve_detokenize`` — the flow chain's terminal hop before
        serve_request), free the slot, resolve the future."""
        with telemetry.span("serve_detokenize",
                            ctx={"req_id": req.req_id}):
            logits = None
            if self._keep_logits and req.out_logits:
                logits = np.stack(req.out_logits)
            result = DecodeResult(tokens=list(req.out_tokens),
                                  logits=logits)
        with self._space:
            self._slot_table[req.slot] = None
            self._stats["resolved"] += 1
            self._stats["slot_retire"] += 1
            self._space.notify_all()
        telemetry.counter_inc("decode.resolved")
        telemetry.counter_inc("decode.slot_retire")
        telemetry.record_event("decode.retire", req_id=req.req_id,
                               slot=req.slot, tokens=len(req.out_tokens))
        req.req_span.__exit__(None, None, None)
        if not req.future.done():
            req.future.set_result(result)

    def _shed_active(self, req, cause, exc):
        """Shed a SLOTTED sequence (deadline hit mid-decode): free the
        slot, resolve with the structured error."""
        with self._space:
            self._slot_table[req.slot] = None
            self._stats["slot_retire"] += 1
            self._space.notify_all()
        telemetry.counter_inc("decode.slot_retire")
        self._shed(req, cause, exc)

    # -- scheduler -----------------------------------------------------------
    def _decode_loop(self):   # mxlint: hot
        """The scheduler thread: drain admissions, fill free slots
        (prefill), advance every active slot one token (decode), retire
        finishers — one iteration per generated token per slot. Owns
        the slot cursor state; the slot TABLE itself is mutated under
        the engine lock so stats()/sampler reads never tear."""
        pending = []
        shutting = False
        try:
            while True:
                with self._lock:
                    idle = not any(s is not None
                                   for s in self._slot_table)
                block = idle and not pending and not shutting
                while True:
                    try:
                        item = self._q.get() if block \
                            else self._q.get_nowait()
                    except queue.Empty:
                        break
                    block = False
                    if item is _SHUTDOWN:
                        shutting = True
                        continue
                    pending.append(item)
                self._admit(pending)
                with self._lock:
                    active = [s for s in self._slot_table
                              if s is not None]
                if shutting and not active and not pending:
                    break
                if active:
                    self._step(active)
        except BaseException as e:
            self._scheduler_died(pending, e)
            raise

    def _admit(self, pending):
        """Fill free slots from the waiting line, shedding expired
        waiters: a saturated slot pool must not hold a prompt past its
        deadline just to decode an answer nobody is waiting for."""
        while pending:
            now = time.monotonic()
            with self._lock:
                free = next((i for i, s in enumerate(self._slot_table)
                             if s is None), None)
            if free is None:
                # pool saturated: shed the already-expired waiters now
                for r in [r for r in pending if r.expired(now)]:
                    pending.remove(r)
                    with self._space:
                        self._queued -= 1
                        self._space.notify_all()
                    self._shed(r, "slot_wait", DeadlineExceeded(
                        "decode: deadline expired waiting for a slot "
                        "(pool of %d saturated)" % self.slots))
                return
            req = pending.pop(0)
            with self._space:
                self._queued -= 1
                self._space.notify_all()
            if req.expired(now):
                self._shed(req, "slot_wait", DeadlineExceeded(
                    "decode: deadline expired waiting for a slot "
                    "(pool of %d saturated)" % self.slots))
                continue
            self._prefill_one(req, free)   # mxlint: disable=future-lifecycle -- a raise escaping here hits _decode_loop's BaseException backstop, which resolves every slotted and pending future via _scheduler_died

    def _prefill_one(self, req, slot):
        """Admit one sequence into ``slot``: dispatch its prompt
        bucket's prefill program (writes the slot's cache, returns the
        first generated token) and install the cursor."""
        req.wait_span.__exit__(None, None, None)
        lp = int(req.prompt.size)
        lb = self.prompt_bucket_for(lp)
        toks = np.zeros(lb, np.int32)
        toks[:lp] = req.prompt
        attempt = 0
        while True:
            try:
                record_dispatch("decode_prefill")
                with telemetry.span("serve_prefill",
                                    ctx={"req_id": req.req_id}):
                    self._cache, tok0, logits0 = self._prefill_prog(   # mxlint: disable=thread-race -- the pool is scheduler-thread-owned after __init__; warmup's read happens before the first request can reach the queue
                        self._params, self._cache, np.int32(slot), toks,
                        np.int32(lp))   # mxlint: donates 1
                    tok_host = int(np.asarray(tok0))   # mxlint: disable=host-sync -- the generated token IS the next step's input; the per-token d2h is decode's data dependency, not an avoidable stall
                    row = None
                    if self._keep_logits:
                        row = np.asarray(logits0)   # mxlint: disable=host-sync -- fetched inside the span so per-token latency counts the real fetch
                break
            except Exception as e:
                if attempt < self._retry_budget and _is_transient(e):
                    attempt += 1
                    with self._lock:
                        self._stats["retries"] += 1
                    telemetry.counter_inc("decode.retries")
                    time.sleep(self._retry_backoff_s
                               * (2 ** (attempt - 1)))
                    continue
                self._poisoned("prefill", req, e)
                return
        self._dispatch_succeeded()
        req.slot = slot
        req.next_token = tok_host
        req.pos = lp                      # the next input's position
        req.out_tokens.append(tok_host)
        if row is not None:
            req.out_logits.append(row)
        with self._space:
            self._slot_table[slot] = req
            self._stats["slot_admit"] += 1
            self._stats["tokens"] += 1
        telemetry.counter_inc("decode.slot_admit")
        telemetry.counter_inc("decode.tokens")
        telemetry.record_event("decode.admit", req_id=req.req_id,
                               slot=slot, prompt_len=lp)
        if req.finished:                  # max_new == 1, or instant EOS
            self._retire(req)

    def _step(self, active):   # mxlint: hot
        """ONE continuous-batching decode step: advance every active
        slot one token in a single donated-buffer dispatch at the
        smallest covering slot bucket, append the fetched tokens,
        retire finishers and shed the deadline-expired."""
        n = len(active)
        bucket = self.slot_bucket_for(n)
        ids = np.full(bucket, self.slots, np.int32)   # pad = out of range
        toks = np.zeros(bucket, np.int32)
        pos = np.zeros(bucket, np.int32)
        for i, req in enumerate(active):
            ids[i] = req.slot
            toks[i] = req.next_token
            pos[i] = req.pos
        rids = [r.req_id for r in active]
        attempt = 0
        while True:
            try:
                record_dispatch("decode")
                with telemetry.span("serve_decode_step",
                                    ctx={"req_ids": rids}):
                    self._cache, toks_out, logits_out = self._decode_prog(
                        self._params, self._cache, ids, toks,
                        pos)   # mxlint: donates 1
                    toks_host = np.asarray(toks_out)   # mxlint: disable=host-sync -- the generated tokens ARE the next step's inputs; the per-step d2h is decode's data dependency, not an avoidable stall
                    rows = None
                    if self._keep_logits:
                        rows = np.asarray(logits_out[:n])   # mxlint: disable=host-sync -- fetched inside the span so per-token latency counts the real fetch
                break
            except Exception as e:
                if attempt < self._retry_budget and _is_transient(e):
                    attempt += 1
                    with self._lock:
                        self._stats["retries"] += 1
                    telemetry.counter_inc("decode.retries")
                    time.sleep(self._retry_backoff_s
                               * (2 ** (attempt - 1)))
                    continue
                self._poisoned("decode", None, e)
                return
        self._dispatch_succeeded()
        with self._lock:
            self._stats["steps"] += 1
            self._stats["tokens"] += n
        telemetry.counter_inc("decode.steps")
        telemetry.counter_inc("decode.tokens", n)
        now = time.monotonic()
        for i, req in enumerate(active):
            tok = int(toks_host[i])
            req.out_tokens.append(tok)
            if rows is not None:
                req.out_logits.append(rows[i])
            req.next_token = tok
            req.pos += 1
            if req.finished:
                self._retire(req)
            elif req.expired(now):
                self._shed_active(req, "decode", DeadlineExceeded(
                    "decode: deadline expired after %d tokens"
                    % len(req.out_tokens)))
        if self._logger is not None:
            try:
                self._logger.log_decode(self)
            except Exception:
                pass

    def _poisoned(self, phase, req, exc):
        """A prefill/decode dispatch failed for good. The cache pool
        was DONATED into the failed call — its buffers may be consumed
        — so every slotted sequence's state is unrecoverable: fail them
        all, rebuild a zeroed pool, keep serving new admissions. Feeds
        the breaker like any dispatch failure."""
        self._dispatch_failed()
        err = MXNetError(
            "decode: %s dispatch failed (%s: %s) — the donated cache "
            "pool is poisoned; every in-flight sequence failed and the "
            "pool was rebuilt" % (phase, type(exc).__name__, exc))
        with self._lock:
            active = [s for s in self._slot_table if s is not None]
            self._slot_table = [None] * self.slots
        victims = list(active)
        if req is not None:
            victims.append(req)
        self._fail_requests(victims, err)
        with self._space:
            self._space.notify_all()
        self._cache = self._make_cache()
        telemetry.record_event("decode.pool_rebuilt", phase=phase,
                               failed=len(victims))
        flight.postmortem("decode_dispatch_failure", exc=exc,
                          extra={"engine": self.overload_state(),
                                 "phase": phase,
                                 "req_ids": [r.req_id for r in victims]})

    def _scheduler_died(self, pending, exc):
        """Terminal cleanup for a dying scheduler thread: every
        pending, queued and slotted sequence resolves with a structured
        error, blocked submitters wake into EngineClosed, and a
        postmortem names the count (the mxlife guarantee: no admitted
        sequence is ever left unresolved)."""
        with self._space:
            self._closed = True
            self._space.notify_all()
        left = list(pending)
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is not _SHUTDOWN:
                left.append(item)
        with self._space:
            self._queued -= len(left)
            active = [s for s in self._slot_table if s is not None]
            self._slot_table = [None] * self.slots
            self._space.notify_all()
        err = MXNetError(
            "decode: scheduler thread died (%s: %s) — the engine is "
            "closed and this sequence was never completed"
            % (type(exc).__name__, exc))
        for r in left:
            self._shed(r, "scheduler_death", err)
        self._fail_requests(active, err)
        flight.postmortem("decode_scheduler_death", exc=exc,
                          extra={"engine": self.overload_state(),
                                 "failed_requests": len(left)
                                 + len(active)})
