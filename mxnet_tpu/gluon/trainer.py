"""Gluon Trainer.

Parity: reference ``python/mxnet/gluon/trainer.py`` (Trainer:27,
_init_kvstore:108, step:156). On TPU the kvstore leg is in-process (see
kvstore.py); grads are already averaged across mesh shards by the
compiled program when running sharded, so step() = rescale + fused
optimizer update per parameter.
"""
from __future__ import annotations

from ..base import MXNetError
from .. import optimizer as opt
from ..model import _create_kvstore
from .parameter import ParameterDict, Parameter

__all__ = ["Trainer"]


class Trainer:
    """(parity: gluon.Trainer)"""

    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore="device", compression_params=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise MXNetError("params must be a ParameterDict/list of "
                             "Parameters")
        self._params = []
        for param in params:
            if not isinstance(param, Parameter):
                raise MXNetError("invalid parameter %r" % (param,))
            self._params.append(param)
        self._compression_params = compression_params
        optimizer_params = dict(optimizer_params or {})
        self._scale = float(optimizer_params.get("rescale_grad", 1.0))
        self._contexts = self._check_contexts()
        self._init_optimizer(optimizer, optimizer_params)
        self._kv_initialized = False
        self._kvstore_type = kvstore
        self._kvstore = None
        self._update_on_kvstore = None

    def _check_contexts(self):
        contexts = None
        for param in self._params:
            ctx = param.list_ctx() if param._data is not None else None
            if contexts is None:
                contexts = ctx
        return contexts or []

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            if optimizer_params and set(optimizer_params) - {"rescale_grad"}:
                raise MXNetError("optimizer_params must be None when "
                                 "optimizer is an instance")
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        self._updaters = opt.get_updater(self._optimizer)

    def _init_kvstore(self):
        """(parity: trainer._init_kvstore:108)"""
        arg_arrays = {p.name: p.data() for p in self._params}
        kvstore, update_on_kvstore = _create_kvstore(
            self._kvstore_type, 1, arg_arrays)
        self._kvstore = kvstore
        self._update_on_kvstore = update_on_kvstore
        if kvstore is not None:
            if self._compression_params:
                kvstore.set_gradient_compression(self._compression_params)
            for i, param in enumerate(self._params):
                kvstore.init(i, param.data())
            if update_on_kvstore:
                kvstore.set_optimizer(self._optimizer)
        self._kv_initialized = True

    @property
    def learning_rate(self):
        return self._optimizer.lr

    def set_learning_rate(self, lr):
        self._optimizer.lr = lr

    def step(self, batch_size, ignore_stale_grad=False):
        """Apply one optimizer step, normalising grads by batch_size
        (parity: trainer.step:156)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._allreduce_grads()
        self._update(ignore_stale_grad)

    def allreduce_grads(self):
        if not self._kv_initialized:
            self._init_kvstore()
        self._allreduce_grads()

    def _allreduce_grads(self):
        if self._kvstore is None or self._update_on_kvstore:
            return
        for i, param in enumerate(self._params):
            if param.grad_req != "null":
                self._kvstore.push(i, param.grad())
                self._kvstore.pull(i, out=param.grad())

    def update(self, batch_size, ignore_stale_grad=False):
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def _update(self, ignore_stale_grad=False):
        live = [(i, p) for i, p in enumerate(self._params)
                if p.grad_req != "null"]
        if self._update_on_kvstore:
            for i, param in live:
                self._kvstore.push(i, param.grad())
                self._kvstore.pull(i, out=param.data())
        else:
            # whole parameter set in one fused dispatch (FusedUpdater)
            self._updaters.update_batch([i for i, _ in live],
                                        [p.grad() for _, p in live],
                                        [p.data() for _, p in live])

    def save_states(self, fname):
        assert self._optimizer is not None
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname, dump_optimizer=True)
        else:
            with open(fname, "wb") as f:
                f.write(self._updaters.get_states(dump_optimizer=True))

    def load_states(self, fname):
        if not self._kv_initialized:
            self._init_kvstore()
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
            self._optimizer = self._kvstore._updater.optimizer
        else:
            with open(fname, "rb") as f:
                self._updaters.set_states(f.read())
            self._optimizer = self._updaters.optimizer
