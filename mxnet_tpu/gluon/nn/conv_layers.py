"""Convolution and pooling gluon layers.

Parity: reference ``python/mxnet/gluon/nn/conv_layers.py`` (_Conv base,
Conv1D/2D/3D, Conv2DTranspose/3DTranspose, Max/Avg pooling 1/2/3D, global
variants).
"""
from __future__ import annotations

import numpy as np

from ...base import MXNetError
from ... import layout as _layout
from ..block import HybridBlock

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose", "Conv2DTranspose",
           "Conv3DTranspose", "MaxPool1D", "MaxPool2D", "MaxPool3D",
           "AvgPool1D", "AvgPool2D", "AvgPool3D", "GlobalMaxPool1D",
           "GlobalMaxPool2D", "GlobalMaxPool3D", "GlobalAvgPool1D",
           "GlobalAvgPool2D", "GlobalAvgPool3D"]


def _tuple(v, n):
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    return tuple(int(x) for x in v)


class _Conv(HybridBlock):
    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, in_channels, activation, use_bias,
                 weight_initializer, bias_initializer, transposed=False,
                 output_padding=0, layout=None, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            ndim = len(kernel_size)
            self._channels = channels
            self._in_channels = in_channels
            self._kernel = kernel_size
            self._strides = _tuple(strides, ndim)
            self._padding = _tuple(padding, ndim)
            self._dilation = _tuple(dilation, ndim)
            self._groups = groups
            self._act_type = activation
            self._transposed = transposed
            self._output_padding = _tuple(output_padding, ndim)
            self._layout = _layout.resolve(layout, ndim)
            self._channels_last = bool(self._layout) and \
                self._layout.endswith("C")
            if transposed:
                if self._channels_last:
                    raise MXNetError(
                        "transposed conv supports channels-first layouts only")
                wshape = (in_channels, channels // groups) + kernel_size
            elif self._channels_last:
                wshape = (channels,) + kernel_size + \
                    (in_channels // groups if in_channels else 0,)
            else:
                wshape = (channels, in_channels // groups if in_channels
                          else 0) + kernel_size
            self.weight = self.params.get("weight", shape=wshape,
                                          init=weight_initializer,
                                          allow_deferred_init=True)
            if use_bias:
                from ... import initializer as _init
                self.bias = self.params.get(
                    "bias", shape=(channels,),
                    init=_init.create(bias_initializer)
                    if isinstance(bias_initializer, str) else bias_initializer)
            else:
                self.bias = None

    def _shape_hook(self, x, *args):
        if self._transposed:
            self.weight._update_shape(
                (x.shape[1], self._channels // self._groups) + self._kernel)
        elif self._channels_last:
            self.weight._update_shape(
                (self._channels,) + self._kernel +
                (x.shape[-1] // self._groups,))
        else:
            self.weight._update_shape(
                (self._channels, x.shape[1] // self._groups) + self._kernel)

    def hybrid_forward(self, F, x, weight, bias=None):
        if self._transposed:
            out = F.Deconvolution(x, weight, bias, kernel=self._kernel,
                                  stride=self._strides, pad=self._padding,
                                  dilate=self._dilation,
                                  adj=self._output_padding,
                                  num_filter=self._channels,
                                  num_group=self._groups,
                                  no_bias=bias is None)
        else:
            out = F.Convolution(x, weight, bias, kernel=self._kernel,
                                stride=self._strides, pad=self._padding,
                                dilate=self._dilation,
                                num_filter=self._channels,
                                num_group=self._groups, no_bias=bias is None,
                                layout=self._layout)
        if self._act_type is not None:
            out = F.Activation(out, act_type=self._act_type)
        return out


def _make_conv(name, ndim, transposed=False):
    class Conv(_Conv):
        def __init__(self, channels, kernel_size, strides=1, padding=0,
                     output_padding=0, dilation=1, groups=1, layout=None,
                     activation=None, use_bias=True, weight_initializer=None,
                     bias_initializer="zeros", in_channels=0, prefix=None,
                     params=None):
            kernel_size = _tuple(kernel_size, ndim)
            super().__init__(channels, kernel_size, strides, padding,
                             dilation, groups, in_channels, activation,
                             use_bias, weight_initializer, bias_initializer,
                             transposed=transposed,
                             output_padding=output_padding, layout=layout,
                             prefix=prefix, params=params)
    Conv.__name__ = name
    Conv.__qualname__ = name
    return Conv


Conv1D = _make_conv("Conv1D", 1)
Conv2D = _make_conv("Conv2D", 2)
Conv3D = _make_conv("Conv3D", 3)
Conv1DTranspose = _make_conv("Conv1DTranspose", 1, transposed=True)
Conv2DTranspose = _make_conv("Conv2DTranspose", 2, transposed=True)
Conv3DTranspose = _make_conv("Conv3DTranspose", 3, transposed=True)


class _Pooling(HybridBlock):
    def __init__(self, pool_size, strides, padding, ceil_mode, global_pool,
                 pool_type, layout=None, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._pool_size = pool_size
        self._strides = strides if strides is not None else pool_size
        self._padding = padding
        self._global_pool = global_pool
        self._pool_type = pool_type
        self._ceil_mode = ceil_mode
        self._layout = _layout.resolve(layout, len(pool_size))

    def _alias(self):
        return "pool"

    def hybrid_forward(self, F, x):
        return F.Pooling(
            x, kernel=self._pool_size, stride=self._strides,
            pad=self._padding, pool_type=self._pool_type,
            global_pool=self._global_pool,
            pooling_convention="full" if self._ceil_mode else "valid",
            layout=self._layout)


def _make_pool(name, ndim, pool_type, global_pool=False):
    class Pool(_Pooling):
        def __init__(self, pool_size=2, strides=None, padding=0,
                     ceil_mode=False, layout=None, prefix=None, params=None):
            if global_pool:
                pool_size, strides, padding = (1,) * ndim, (1,) * ndim, \
                    (0,) * ndim
            else:
                pool_size = _tuple(pool_size, ndim)
                strides = _tuple(strides, ndim) if strides is not None else None
                padding = _tuple(padding, ndim)
            super().__init__(pool_size, strides, padding, ceil_mode,
                             global_pool, pool_type, layout=layout,
                             prefix=prefix, params=params)
    Pool.__name__ = name
    Pool.__qualname__ = name
    return Pool


MaxPool1D = _make_pool("MaxPool1D", 1, "max")
MaxPool2D = _make_pool("MaxPool2D", 2, "max")
MaxPool3D = _make_pool("MaxPool3D", 3, "max")
AvgPool1D = _make_pool("AvgPool1D", 1, "avg")
AvgPool2D = _make_pool("AvgPool2D", 2, "avg")
AvgPool3D = _make_pool("AvgPool3D", 3, "avg")
GlobalMaxPool1D = _make_pool("GlobalMaxPool1D", 1, "max", global_pool=True)
GlobalMaxPool2D = _make_pool("GlobalMaxPool2D", 2, "max", global_pool=True)
GlobalMaxPool3D = _make_pool("GlobalMaxPool3D", 3, "max", global_pool=True)
GlobalAvgPool1D = _make_pool("GlobalAvgPool1D", 1, "avg", global_pool=True)
GlobalAvgPool2D = _make_pool("GlobalAvgPool2D", 2, "avg", global_pool=True)
GlobalAvgPool3D = _make_pool("GlobalAvgPool3D", 3, "avg", global_pool=True)
