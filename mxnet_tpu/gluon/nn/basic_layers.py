"""Basic gluon layers.

Parity: reference ``python/mxnet/gluon/nn/basic_layers.py`` (Sequential,
HybridSequential, Dense, Dropout, BatchNorm, Embedding, Flatten,
Activation, LeakyReLU, InstanceNorm, + LayerNorm as the attention-era
addition).
"""
from __future__ import annotations

import numpy as np

from ...base import MXNetError
from ..block import Block, HybridBlock
from ..parameter import DeferredInitializationError

__all__ = ["Lambda", "HybridLambda",
           "Sequential", "HybridSequential", "Dense", "Dropout", "BatchNorm",
           "FusedBNAddReLU",
           "Embedding", "Flatten", "Activation", "LeakyReLU", "InstanceNorm",
           "LayerNorm"]


class Sequential(Block):
    """(parity: nn.Sequential)"""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers)
            return net
        return layers

    def __iter__(self):
        return iter(self._children.values())

    def hybridize(self, active=True, **kwargs):
        if self._children and all(isinstance(c, HybridBlock)
                                  for c in self._children.values()):
            import warnings
            warnings.warn("All children are HybridBlocks; consider "
                          "HybridSequential for one fused program.")
        super().hybridize(active, **kwargs)


class HybridSequential(HybridBlock):
    """(parity: nn.HybridSequential)"""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def _forward_eager(self, x, *args):
        for block in self._children.values():
            x = block(x)
        return x

    def hybrid_forward(self, F, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers)
            return net
        return layers

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """(parity: nn.Dense) — MXU-bound y = act(xW^T + b)."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype=np.float32, weight_initializer=None,
                 bias_initializer="zeros", in_units=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self._units = units
            self._flatten = flatten
            self._act_type = activation
            self.weight = self.params.get(
                "weight", shape=(units, in_units), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True)
            if use_bias:
                from ... import initializer as _init
                self.bias = self.params.get(
                    "bias", shape=(units,), dtype=dtype,
                    init=_init.create(bias_initializer)
                    if isinstance(bias_initializer, str) else bias_initializer,
                    allow_deferred_init=True)
            else:
                self.bias = None

    def _shape_hook(self, x, *args):
        in_units = int(np.prod(x.shape[1:])) if self._flatten else x.shape[-1]
        self.weight._update_shape((self._units, in_units))

    def hybrid_forward(self, F, x, weight, bias=None):
        out = F.FullyConnected(x, weight, bias, num_hidden=self._units,
                               no_bias=bias is None, flatten=self._flatten)
        if self._act_type is not None:
            out = F.Activation(out, act_type=self._act_type)
        return out

    def __repr__(self):
        return "Dense(%s -> %d)" % (self.weight.shape[1] if self.weight.shape
                                    else None, self._units)


class Dropout(HybridBlock):
    """(parity: nn.Dropout)"""

    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        return F.Dropout(x, p=self._rate, axes=self._axes)


class BatchNorm(HybridBlock):
    """(parity: nn.BatchNorm) with running stats as null-grad params."""

    def __init__(self, axis=None, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones", running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            if axis is None:
                # reference default is axis=1 (NCHW); under a channels-last
                # default layout (mxnet_tpu.layout) the channel dim is last
                from ... import layout as _layout
                axis = -1 if _layout.default_is_channels_last() else 1
            self._axis = axis
            self._momentum = momentum
            self._epsilon = epsilon
            self._center = center
            self._scale = scale
            self._use_global_stats = use_global_stats
            from ... import initializer as _init
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=_init.create(gamma_initializer),
                allow_deferred_init=True, differentiable=scale)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=_init.create(beta_initializer),
                allow_deferred_init=True, differentiable=center)
            self.running_mean = self.params.get(
                "running_mean", grad_req="null", shape=(in_channels,),
                init=_init.create(running_mean_initializer),
                allow_deferred_init=True, differentiable=False)
            self.running_var = self.params.get(
                "running_var", grad_req="null", shape=(in_channels,),
                init=_init.create(running_variance_initializer),
                allow_deferred_init=True, differentiable=False)

    def _shape_hook(self, x, *args):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            p._update_shape((c,))

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        return F.BatchNorm(x, gamma, beta, running_mean, running_var,
                           eps=self._epsilon, momentum=self._momentum,
                           fix_gamma=not self._scale,
                           use_global_stats=self._use_global_stats,
                           axis=self._axis)


class FusedBNAddReLU(BatchNorm):
    """ResNet block tail — BN-apply + residual-add + ReLU — as ONE op
    (``_contrib_BatchNormAddReLU``, ops/nn.py; Pallas kernel when the
    channel axis is last). Same parameters and moving-stat contract as
    BatchNorm; takes (x, residual) and returns relu(bn(x) + residual).
    The model zoo flips blocks onto this tail when
    MXNET_FUSED_BN_ADD_RELU=1 (see PERF.md for the measured A/B)."""

    def hybrid_forward(self, F, x, addend, gamma, beta, running_mean,
                       running_var):
        return F._contrib_BatchNormAddReLU(
            x, addend, gamma, beta, running_mean, running_var,
            eps=self._epsilon, momentum=self._momentum,
            fix_gamma=not self._scale,
            use_global_stats=self._use_global_stats, axis=self._axis)


class InstanceNorm(HybridBlock):
    """(parity: nn.InstanceNorm)"""

    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            from ... import initializer as _init
            self._epsilon = epsilon
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=_init.create(gamma_initializer),
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=_init.create(beta_initializer),
                allow_deferred_init=True)

    def _shape_hook(self, x, *args):
        c = x.shape[1]
        self.gamma._update_shape((c,))
        self.beta._update_shape((c,))

    def hybrid_forward(self, F, x, gamma, beta):
        return F.InstanceNorm(x, gamma, beta, eps=self._epsilon)


class LayerNorm(HybridBlock):
    """Layer normalisation (new-framework addition for attention models)."""

    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 in_channels=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self._axis = axis
            self._epsilon = epsilon
            self.gamma = self.params.get("gamma", shape=(in_channels,),
                                         allow_deferred_init=True,
                                         differentiable=scale)
            self.beta = self.params.get("beta", shape=(in_channels,),
                                        allow_deferred_init=True,
                                        differentiable=center)

    def _shape_hook(self, x, *args):
        c = x.shape[self._axis]
        self.gamma._update_shape((c,))
        self.beta._update_shape((c,))

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._axis,
                           eps=self._epsilon)


class Embedding(HybridBlock):
    """(parity: nn.Embedding) — sharded variants live in mxnet_tpu.parallel."""

    def __init__(self, input_dim, output_dim, dtype=np.float32,
                 weight_initializer=None, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self._input_dim = input_dim
            self._output_dim = output_dim
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim), dtype=dtype,
                init=weight_initializer)

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, input_dim=self._input_dim,
                           output_dim=self._output_dim)


class Flatten(HybridBlock):
    """(parity: nn.Flatten)"""

    def hybrid_forward(self, F, x):
        return F.Flatten(x)


class Activation(HybridBlock):
    """(parity: nn.Activation)"""

    def __init__(self, activation, prefix=None, params=None):
        self._act_type = activation
        super().__init__(prefix=prefix, params=params)

    def _alias(self):
        return self._act_type

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type)


class LeakyReLU(HybridBlock):
    """(parity: nn.LeakyReLU)"""

    def __init__(self, alpha, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha)


class Lambda(Block):
    """Wrap a function as a Block (parity: nn.Lambda; accepts an mx.nd
    function name or a callable)."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            import mxnet_tpu.ndarray as F
            if not hasattr(F, function):
                raise MXNetError("function %r not found in mx.nd" % function)
            self._func_impl = getattr(F, function)
        else:
            self._func_impl = function

    def forward(self, *args):
        return self._func_impl(*args)

    def __repr__(self):
        return "Lambda(%s)" % getattr(self._func_impl, "__name__",
                                      self._func_impl)


class HybridLambda(HybridBlock):
    """Wrap a function as a HybridBlock (parity: nn.HybridLambda)."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            self._func_name = function
            self._func_impl = None
        else:
            self._func_impl = function
            self._func_name = getattr(function, "__name__", "lambda")

    def hybrid_forward(self, F, x, *args):
        if self._func_impl is not None:
            return self._func_impl(F, x, *args)
        return getattr(F, self._func_name)(x, *args)

    def __repr__(self):
        return "HybridLambda(%s)" % self._func_name
