"""Gluon Parameter / ParameterDict.

Parity: reference ``python/mxnet/gluon/parameter.py`` (Parameter with
deferred shape init, grad_req handling, ParameterDict with prefix
scoping). TPU note: a Parameter holds ONE array (mesh sharding replaces
per-device copies — list_ctx/list_data return single-element lists).
"""
from __future__ import annotations

from collections import OrderedDict

import numpy as np

from ..base import MXNetError
from ..context import Context, current_context, cpu
from .. import initializer as init
from ..initializer import InitDesc
from ..ndarray.ndarray import NDArray, zeros as nd_zeros
from .. import imperative as _imp

__all__ = ["Parameter", "ParameterDict"]


class DeferredInitializationError(MXNetError):
    pass


class Parameter:
    """(parity: gluon.Parameter)"""

    def __init__(self, name, grad_req="write", shape=None, dtype=np.float32,
                 lr_mult=1.0, wd_mult=1.0, init=None, allow_deferred_init=False,
                 differentiable=True, stype="default", grad_stype="default"):
        self.name = name
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self._differentiable = differentiable
        self._grad_req = grad_req if differentiable else "null"
        self._data = None
        self._grad = None
        self._deferred_init = None
        self._stype = stype

    def __repr__(self):
        return "Parameter %s (shape=%s, dtype=%s)" % (self.name, self.shape,
                                                      self.dtype)

    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        if req not in ("write", "add", "null"):
            raise MXNetError("invalid grad_req %r" % req)
        if not self._differentiable:
            req = "null"
        self._grad_req = req
        if self._data is not None:
            if req == "null":
                self._grad = None
                self._data._tape = None
            else:
                self._init_grad()

    def _shape_complete(self):
        return self.shape is not None and all(s > 0 for s in self.shape)

    def initialize(self, init=None, ctx=None, default_init=None,
                   force_reinit=False):
        """(parity: Parameter.initialize)"""
        if default_init is None:
            default_init = _default_init()
        if self._data is not None and not force_reinit:
            return
        if ctx is None:
            ctx = current_context()
        if isinstance(ctx, (list, tuple)):
            ctx = ctx[0]
        if not self._shape_complete():
            if not self.allow_deferred_init:
                raise MXNetError("Cannot initialize %r: shape unknown (%s). "
                                 "Pass input data once or specify shape."
                                 % (self.name, self.shape))
            self._deferred_init = (init, ctx, default_init)
            return
        self._finish_init(init, ctx, default_init)

    def _finish_init(self, initializer, ctx, default_init):
        from ..initializer import Initializer, create as _init_create
        arr = nd_zeros(self.shape, ctx=ctx, dtype=self.dtype)
        desc = InitDesc(self.name, {"__init__": ""})
        # a param-specific init applies as a WEIGHT init regardless of
        # the parameter's name suffix; only the global default goes
        # through suffix dispatch (parity: reference parameter.py
        # _finish_deferred_init + initializer.py __call__). Initializers
        # with their own dispatch (Mixed, Load, FusedRNN via an
        # overridden __call__) route themselves.
        specific = initializer or self.init
        if specific is None:
            default_init(desc, arr)
        else:
            if isinstance(specific, str):
                specific = _init_create(specific)
            if type(specific).__call__ is not Initializer.__call__:
                specific(desc, arr)
            else:
                specific._init_weight(desc, arr)
        self._data = arr
        if self._grad_req != "null":
            self._init_grad()
        self._deferred_init = None

    def _finish_deferred_init(self):
        if self._deferred_init is None:
            raise DeferredInitializationError(
                "Parameter %r has unknown shape" % self.name)
        initializer, ctx, default_init = self._deferred_init
        if not self._shape_complete():
            raise DeferredInitializationError(
                "Parameter %r still has unknown shape %s" % (self.name,
                                                             self.shape))
        self._finish_init(initializer, ctx, default_init)

    def _init_grad(self):
        self._grad = nd_zeros(self._data.shape, ctx=self._data.context,
                              dtype=self._data.dtype)
        _imp.mark_variables([self._data], [self._grad], [self._grad_req])

    def _check_initialized(self):
        if self._data is None:
            if self._deferred_init is not None:
                raise DeferredInitializationError(
                    "Parameter %r deferred; run a forward pass first"
                    % self.name)
            raise MXNetError("Parameter %r is not initialized; call "
                             ".initialize()" % self.name)

    def _update_shape(self, shape):
        """Fill deferred shape from real input (called by layers)."""
        shape = tuple(int(s) for s in shape)
        if self.shape is not None:
            merged = tuple(n if o == 0 else o
                           for o, n in zip(self.shape, shape))
            self.shape = merged
        else:
            self.shape = shape
        if self._deferred_init is not None and self._shape_complete():
            self._finish_deferred_init()

    # -- access ------------------------------------------------------------
    def data(self, ctx=None):
        self._check_initialized()
        return self._data

    def list_data(self):
        self._check_initialized()
        return [self._data]

    def grad(self, ctx=None):
        self._check_initialized()
        if self._grad is None:
            raise MXNetError("Parameter %r has grad_req='null'" % self.name)
        return self._grad

    def list_grad(self):
        return [self.grad()]

    def list_ctx(self):
        self._check_initialized()
        return [self._data.context]

    def set_data(self, data):
        if self._data is None:
            # allow setting before init (used by load)
            self.shape = tuple(data.shape)
            self._data = data.copy() if isinstance(data, NDArray) else data
            if self._grad_req != "null":
                self._init_grad()
            return
        data.copyto(self._data)

    def zero_grad(self):
        if self._grad is not None:
            self._grad[:] = 0

    def reset_ctx(self, ctx):
        pass  # single logical device; sharding handles placement

    def cast(self, dtype):
        self.dtype = dtype
        if self._data is not None:
            self._data = self._data.astype(dtype)
            if self._grad is not None:
                self._grad = self._grad.astype(dtype)
                _imp.mark_variables([self._data], [self._grad],
                                    [self._grad_req])

    def var(self):
        from ..symbol import Variable
        return Variable(self.name, shape=self.shape, lr_mult=self.lr_mult,
                        wd_mult=self.wd_mult, dtype=self.dtype)


def _default_init():
    return init.Uniform(0.07)


class ParameterDict:
    """(parity: gluon.ParameterDict)"""

    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params = OrderedDict()
        self._shared = shared

    def __repr__(self):
        s = "\n".join(repr(p) for p in self._params.values())
        return "ParameterDict %s(\n%s\n)" % (self._prefix, s)

    def __getitem__(self, key):
        return self._params[key]

    def __iter__(self):
        return iter(self._params)

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    @property
    def prefix(self):
        return self._prefix

    def _get_impl(self, name):
        if name in self._params:
            return self._params[name]
        if self._shared is not None and name in self._shared._params:
            self._params[name] = self._shared._params[name]
            return self._params[name]
        return None

    def get(self, name, **kwargs):
        """(parity: ParameterDict.get) create-or-retrieve with attr merge."""
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            param = Parameter(name, **kwargs)
            self._params[name] = param
        else:
            for k, v in kwargs.items():
                if getattr(param, k, None) is not None and k in ("shape",
                                                                 "dtype"):
                    continue
                if v is not None:
                    setattr(param, k, v)
        return param

    def update(self, other):
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise MXNetError("duplicate parameter %r" % k)
            self._params[k] = v

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        for p in self.values():
            p.initialize(init=None, ctx=ctx, default_init=init or
                         _default_init(), force_reinit=force_reinit)

    def zero_grad(self):
        for p in self.values():
            p.zero_grad()

    def reset_ctx(self, ctx):
        pass

    def setattr(self, name, value):
        for p in self.values():
            setattr(p, name, value)

    def save(self, filename, strip_prefix=""):
        from ..ndarray import save as nd_save
        arg_dict = {}
        for param in self.values():
            block = param.list_data()
            weight = block[0]
            if not param.name.startswith(strip_prefix):
                raise MXNetError("prefix %r not in param name %r"
                                 % (strip_prefix, param.name))
            arg_dict[param.name[len(strip_prefix):]] = weight
        nd_save(filename, arg_dict)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix=""):
        from ..ndarray import load as nd_load
        arg_dict = {restore_prefix + k: v for k, v in nd_load(filename).items()}
        if not allow_missing:
            for name in self.keys():
                if name not in arg_dict:
                    raise MXNetError("Parameter %r missing in file %r"
                                     % (name, filename))
        for name, arr in arg_dict.items():
            if name not in self._params:
                if not ignore_extra:
                    raise MXNetError("Parameter %r in file is not in this "
                                     "ParameterDict" % name)
                continue
            self._params[name].set_data(arr)
