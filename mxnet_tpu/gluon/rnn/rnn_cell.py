"""Gluon RNN cells.

Parity: reference ``python/mxnet/gluon/rnn/rnn_cell.py`` (RecurrentCell,
RNNCell, LSTMCell, GRUCell, SequentialRNNCell, BidirectionalCell,
DropoutCell, ZoneoutCell, ResidualCell) — the step-at-a-time API; the
fused layers (rnn_layer.py) are the performance path on TPU.
"""
from __future__ import annotations

from ...base import MXNetError
from ..block import HybridBlock

__all__ = ["RecurrentCell", "HybridRecurrentCell", "ModifierCell",
           "RNNCell", "LSTMCell", "GRUCell",
           "SequentialRNNCell", "BidirectionalCell", "DropoutCell",
           "ZoneoutCell", "ResidualCell"]


class RecurrentCell(HybridBlock):
    """(parity: rnn_cell.RecurrentCell)"""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        from ... import ndarray as F
        func = func or F.zeros
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            state = func(shape=info["shape"], **kwargs)
            states.append(state)
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """(parity: RecurrentCell.unroll)"""
        from ... import ndarray as F
        self.reset()
        axis = layout.find("T")
        batch_axis = layout.find("N")
        batch_size = inputs.shape[batch_axis]
        if begin_state is None:
            begin_state = self.begin_state(batch_size)
        states = begin_state
        outputs = []
        steps = F.SliceChannel(inputs, num_outputs=length, axis=axis,
                               squeeze_axis=True)
        if not isinstance(steps, (list, tuple)):
            steps = [steps]
        for i in range(length):
            output, states = self(steps[i], states)
            outputs.append(output)
        if merge_outputs is None or merge_outputs:
            outputs = [o.expand_dims(axis) for o in outputs]
            outputs = F.Concat(*outputs, dim=axis)
        return outputs, states

    def __call__(self, inputs, states):
        self._counter += 1
        return super().__call__(inputs, *states)

    def _forward_eager(self, x, *states):
        params = {}
        from ..parameter import DeferredInitializationError
        try:
            for name, p in self._reg_params.items():
                params[name] = p.data()
        except DeferredInitializationError:
            self._infer_param_shapes(x, *states)
            for name, p in self._reg_params.items():
                params[name] = p.data()
        from ... import ndarray as F
        return self.hybrid_forward(F, x, list(states), **params)


# the reference's cells hybridize; RecurrentCell here IS hybrid-capable
HybridRecurrentCell = RecurrentCell


class _BaseRNNCell(RecurrentCell):
    def __init__(self, hidden_size, i2h_weight_initializer,
                 h2h_weight_initializer, i2h_bias_initializer,
                 h2h_bias_initializer, input_size, num_gates, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            from ... import initializer as _init
            self._hidden_size = hidden_size
            self._input_size = input_size
            ng = num_gates
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=(ng * hidden_size, input_size),
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=(ng * hidden_size, hidden_size),
                init=h2h_weight_initializer)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(ng * hidden_size,),
                init=_maybe_init(i2h_bias_initializer))
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(ng * hidden_size,),
                init=_maybe_init(h2h_bias_initializer))

    def _shape_hook(self, x, *args):
        self.i2h_weight._update_shape(
            (self.i2h_weight.shape[0], x.shape[-1]))

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]


def _maybe_init(v):
    from ... import initializer as _init
    if isinstance(v, str):
        return _init.create(v)
    return v


class RNNCell(_BaseRNNCell):
    """(parity: rnn_cell.RNNCell)"""

    def __init__(self, hidden_size, activation="tanh",
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, prefix=None, params=None):
        super().__init__(hidden_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, input_size, 1, prefix, params)
        self._activation = activation

    def _alias(self):
        return "rnn"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias,
                               num_hidden=self._hidden_size)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=self._hidden_size)
        output = F.Activation(i2h + h2h, act_type=self._activation)
        return output, [output]


class LSTMCell(_BaseRNNCell):
    """(parity: rnn_cell.LSTMCell; gate order i,f,c,o)"""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        super().__init__(hidden_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, input_size, 4, prefix, params)

    def _alias(self):
        return "lstm"

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        H = self._hidden_size
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias, num_hidden=4 * H)
        h2h = F.FullyConnected(states[0], h2h_weight, h2h_bias,
                               num_hidden=4 * H)
        gates = i2h + h2h
        slices = F.SliceChannel(gates, num_outputs=4, axis=1)
        in_gate = F.sigmoid(slices[0])
        forget_gate = F.sigmoid(slices[1])
        in_transform = F.tanh(slices[2])
        out_gate = F.sigmoid(slices[3])
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * F.tanh(next_c)
        return next_h, [next_h, next_c]


class GRUCell(_BaseRNNCell):
    """(parity: rnn_cell.GRUCell; gate order r,z,n)"""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        super().__init__(hidden_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, input_size, 3, prefix, params)

    def _alias(self):
        return "gru"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        H = self._hidden_size
        prev_h = states[0]
        i2h = F.FullyConnected(inputs, i2h_weight, i2h_bias, num_hidden=3 * H)
        h2h = F.FullyConnected(prev_h, h2h_weight, h2h_bias, num_hidden=3 * H)
        i2h_s = F.SliceChannel(i2h, num_outputs=3, axis=1)
        h2h_s = F.SliceChannel(h2h, num_outputs=3, axis=1)
        reset_gate = F.sigmoid(i2h_s[0] + h2h_s[0])
        update_gate = F.sigmoid(i2h_s[1] + h2h_s[1])
        next_h_tmp = F.tanh(i2h_s[2] + reset_gate * h2h_s[2])
        next_h = (1 - update_gate) * next_h_tmp + update_gate * prev_h
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    """(parity: rnn_cell.SequentialRNNCell)"""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        info = []
        for cell in self._children.values():
            info.extend(cell.state_info(batch_size))
        return info

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._children.values():
            n = len(cell.state_info())
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.extend(state)
        return inputs, next_states

    def __len__(self):
        return len(self._children)


class DropoutCell(RecurrentCell):
    """(parity: rnn_cell.DropoutCell)"""

    def __init__(self, rate, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._rate = rate

    def state_info(self, batch_size=0):
        return []

    def __call__(self, inputs, states):
        from ... import ndarray as F
        if self._rate > 0:
            inputs = F.Dropout(inputs, p=self._rate)
        return inputs, states


class ModifierCell(RecurrentCell):
    def __init__(self, base_cell):
        super().__init__(prefix=base_cell.prefix + self._alias(),
                         params=None)
        self.base_cell = base_cell

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)


class ZoneoutCell(ModifierCell):
    """(parity: rnn_cell.ZoneoutCell)"""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def _alias(self):
        return "zoneout"

    def __call__(self, inputs, states):
        from ... import ndarray as F
        from ... import autograd
        output, new_states = self.base_cell(inputs, states)
        if autograd.is_training():
            if self.zoneout_outputs > 0:
                mask = F.Dropout(F.ones_like(output), p=self.zoneout_outputs)
                prev = self._prev_output if self._prev_output is not None \
                    else F.zeros_like(output)
                output = F.where(mask, output, prev)
            if self.zoneout_states > 0:
                new_states = [
                    F.where(F.Dropout(F.ones_like(ns), p=self.zoneout_states),
                            ns, s)
                    for ns, s in zip(new_states, states)]
        self._prev_output = output
        return output, new_states


class ResidualCell(ModifierCell):
    """(parity: rnn_cell.ResidualCell)"""

    def _alias(self):
        return "residual"

    def __call__(self, inputs, states):
        output, states = self.base_cell(inputs, states)
        return output + inputs, states


class BidirectionalCell(RecurrentCell):
    """(parity: rnn_cell.BidirectionalCell)"""

    def __init__(self, l_cell, r_cell, prefix="bi_", params=None):
        super().__init__(prefix=prefix, params=params)
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")

    def state_info(self, batch_size=0):
        return (self._children["l_cell"].state_info(batch_size)
                + self._children["r_cell"].state_info(batch_size))

    def __call__(self, inputs, states):
        raise MXNetError("BidirectionalCell supports only unroll()")

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        from ... import ndarray as F
        self.reset()
        axis = layout.find("T")
        batch_size = inputs.shape[layout.find("N")]
        if begin_state is None:
            begin_state = self.begin_state(batch_size)
        l_cell = self._children["l_cell"]
        r_cell = self._children["r_cell"]
        n_l = len(l_cell.state_info())
        l_out, l_states = l_cell.unroll(length, inputs, begin_state[:n_l],
                                        layout, merge_outputs=True)
        rev = F.reverse(inputs, axis=axis)
        r_out, r_states = r_cell.unroll(length, rev, begin_state[n_l:],
                                        layout, merge_outputs=True)
        r_out = F.reverse(r_out, axis=axis)
        outputs = F.Concat(l_out, r_out, dim=2)
        return outputs, l_states + r_states
