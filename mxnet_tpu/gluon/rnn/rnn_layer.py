"""Fused gluon RNN layers (RNN / LSTM / GRU).

Parity: reference ``python/mxnet/gluon/rnn/rnn_layer.py`` which routes to
the fused ``RNN`` op (cuDNN in the reference; lax.scan here — see
ops/rnn.py for the packed parameter layout these layers produce).
"""
from __future__ import annotations

import numpy as np

from ...base import MXNetError
from ..block import HybridBlock
from ...ops.rnn import rnn_param_size, _GATES

__all__ = ["RNN", "LSTM", "GRU"]


class _RNNLayer(HybridBlock):
    def __init__(self, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, mode, prefix=None, params=None,
                 **kwargs):
        super().__init__(prefix=prefix, params=params)
        if layout not in ("TNC", "NTC"):
            raise MXNetError("layout must be TNC or NTC")
        with self.name_scope():
            self._hidden_size = hidden_size
            self._num_layers = num_layers
            self._layout = layout
            self._dropout = dropout
            self._dir = 2 if bidirectional else 1
            self._input_size = input_size
            self._mode = mode
            # per-layer parameters with reference naming (rnn_layer.py creates
            # l0_i2h_weight etc.); they are packed into the fused op's flat
            # vector in hybrid_forward (layout documented in ops/rnn.py)
            ng = _GATES[mode]
            self._param_names = []
            for layer in range(num_layers):
                for d in range(self._dir):
                    suffix = "" if d == 0 else "_r"
                    in_sz = input_size if layer == 0 else \
                        hidden_size * self._dir
                    for kind, shape in [
                            ("i2h_weight", (ng * hidden_size, in_sz)),
                            ("h2h_weight", (ng * hidden_size, hidden_size)),
                            ("i2h_bias", (ng * hidden_size,)),
                            ("h2h_bias", (ng * hidden_size,))]:
                        name = "l%d%s_%s" % (layer, suffix, kind)
                        p = self.params.get(name, shape=shape,
                                            allow_deferred_init=True)
                        setattr(self, name, p)
                        self._param_names.append(name)

    def _shape_hook(self, x, *args):
        in_sz = x.shape[-1]
        self._input_size = in_sz
        ng = _GATES[self._mode]
        H = self._hidden_size
        for layer in range(self._num_layers):
            layer_in = in_sz if layer == 0 else H * self._dir
            for d in range(self._dir):
                suffix = "" if d == 0 else "_r"
                getattr(self, "l%d%s_i2h_weight" % (layer, suffix)) \
                    ._update_shape((ng * H, layer_in))

    def state_info(self, batch_size=0):
        num = self._num_layers * self._dir
        info = [{"shape": (num, batch_size, self._hidden_size),
                 "__layout__": "LNC"}]
        if self._mode == "lstm":
            info.append({"shape": (num, batch_size, self._hidden_size),
                         "__layout__": "LNC"})
        return info

    def begin_state(self, batch_size=0, func=None, **kwargs):
        """(parity: rnn_layer.begin_state)"""
        from ... import ndarray as F
        func = func or F.zeros
        return [func(shape=info["shape"], **kwargs)
                for info in self.state_info(batch_size)]

    def __call__(self, inputs, states=None):
        if states is None:
            batch = inputs.shape[self._layout.find("N")]
            states = self.begin_state(batch)
            skip_states = True
        else:
            skip_states = False
            if not isinstance(states, (list, tuple)):
                states = [states]
        out = super().__call__(inputs, *states)
        if skip_states:
            return out[0] if isinstance(out, (list, tuple)) else out
        if not isinstance(out, (list, tuple)):
            return out, []
        return out[0], list(out[1:])

    def hybrid_forward(self, F, inputs, *states, **params):
        flat = [F.Reshape(params[name], shape=(-1,))
                for name in self._param_names]
        parameters = F.Concat(*flat, dim=0) if len(flat) > 1 else flat[0]
        if self._layout == "NTC":
            inputs = F.swapaxes(inputs, dim1=0, dim2=1)
        rnn_args = [inputs, parameters] + list(states)
        outs = F.RNN(*rnn_args, state_size=self._hidden_size,
                     num_layers=self._num_layers, mode=self._mode,
                     bidirectional=self._dir == 2, p=self._dropout,
                     state_outputs=True)
        if not isinstance(outs, (list, tuple)):
            outs = [outs]
        output = outs[0]
        if self._layout == "NTC":
            output = F.swapaxes(output, dim1=0, dim2=1)
        return [output] + list(outs[1:])

    def __repr__(self):
        return "%s(%s, %d, layers=%d)" % (type(self).__name__, self._mode,
                                          self._hidden_size, self._num_layers)


class RNN(_RNNLayer):
    """(parity: gluon.rnn.RNN)"""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False, input_size=0,
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size,
                         "rnn_" + activation, **kwargs)


class LSTM(_RNNLayer):
    """(parity: gluon.rnn.LSTM)"""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, "lstm", **kwargs)


class GRU(_RNNLayer):
    """(parity: gluon.rnn.GRU)"""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, "gru", **kwargs)
