"""Pretrained-weight store for the Gluon model zoo.

Parity: reference ``python/mxnet/gluon/model_zoo/model_store.py``
(get_model_file/purge). The reference downloads sha1-pinned blobs from
the Apache repo; this build runs zero-egress, so resolution order is:

1. ``{root}/{name}.params`` (or ``{name}-*.params``, the reference's
   hash-suffixed naming) on the local filesystem;
2. ``MXNET_GLUON_REPO`` pointing at a ``file://`` directory laid out the
   same way (the reference honours the same env var for mirrors);
3. otherwise a clear error telling the user where to place the file.

Blob format is the reference checkpoint format (``nd.save`` dict with
``arg:``/``aux:`` prefixes as written by ``Block.save_params``), so
params exported from the reference load unchanged. Weights are stored in
the reference's channels-first layouts — load into models built with the
default (NCHW) layout.
"""
from __future__ import annotations

import glob
import os
import shutil

from ...base import MXNetError

__all__ = ["get_model_file", "purge", "short_hash"]

# the reference's published sha1 pins (model_store.py:28-51) — data, kept
# so reference-named blobs (``name-<hash8>.params``) resolve identically
_checksums = {name: sha1 for sha1, name in [
    ("44335d1f0046b328243b32a26a4fbd62d9057b45", "alexnet"),
    ("f27dbf2dbd5ce9a80b102d89c7483342cd33cb31", "densenet121"),
    ("b6c8a95717e3e761bd88d145f4d0a214aaa515dc", "densenet161"),
    ("2603f878403c6aa5a71a124c4a3307143d6820e9", "densenet169"),
    ("1cdbc116bc3a1b65832b18cf53e1cb8e7da017eb", "densenet201"),
    ("ed47ec45a937b656fcc94dabde85495bbef5ba1f", "inceptionv3"),
    ("d2b128fa89477c2e20061607a53a8d9f66ce239d", "resnet101_v1"),
    ("6562166cd597a6328a32a0ce47bb651df80b3bbb", "resnet152_v1"),
    ("38d6d423c22828718ec3397924b8e116a03e6ac0", "resnet18_v1"),
    ("4dc2c2390a7c7990e0ca1e53aeebb1d1a08592d1", "resnet34_v1"),
    ("2a903ab21260c85673a78fe65037819a843a1f43", "resnet50_v1"),
    ("8aacf80ff4014c1efa2362a963ac5ec82cf92d5b", "resnet18_v2"),
    ("0ed3cd06da41932c03dea1de7bc2506ef3fb97b3", "resnet34_v2"),
    ("eb7a368774aa34a12ed155126b641ae7556dad9d", "resnet50_v2"),
    ("264ba4970a0cc87a4f15c96e25246a1307caf523", "squeezenet1.0"),
    ("33ba0f93753c83d86e1eb397f38a667eaf2e9376", "squeezenet1.1"),
    ("dd221b160977f36a53f464cb54648d227c707a05", "vgg11"),
    ("ee79a8098a91fbe05b7a973fed2017a6117723a8", "vgg11_bn"),
    ("6bc5de58a05a5e2e7f493e2d75a580d83efde38c", "vgg13"),
    ("7d97a06c3c7a1aecc88b6e7385c2b373a249e95e", "vgg13_bn"),
    ("649467530119c0f78c4859999e264e7bf14471a9", "vgg16"),
    ("6b9dbe6194e5bfed30fd7a7c9a71f7e5a276cb14", "vgg16_bn"),
    ("f713436691eee9a20d70a145ce0d53ed24bf7399", "vgg19"),
    ("9730961c9cea43fd7eeefb00d792e386c45847d6", "vgg19_bn")]}


def _candidates(name, root):
    out = [os.path.join(root, name + ".params")]
    out.extend(sorted(glob.glob(os.path.join(root, name + "-*.params"))))
    return out


def get_model_file(name, root="~/.mxnet/models/"):
    """Return the local path of the pretrained blob for ``name``
    (parity: model_store.get_model_file)."""
    root = os.path.expanduser(root)
    for path in _candidates(name, root):
        if os.path.exists(path):
            return path
    repo = os.environ.get("MXNET_GLUON_REPO", "")
    if repo.startswith("file://"):
        src_root = repo[len("file://"):]
        for src in _candidates(name, src_root):
            if os.path.exists(src):
                os.makedirs(root, exist_ok=True)
                dst = os.path.join(root, os.path.basename(src))
                shutil.copyfile(src, dst)
                return dst
    raise MXNetError(
        "pretrained weights for %r not found under %r (zero-egress build: "
        "place the reference-format .params file there, or set "
        "MXNET_GLUON_REPO=file:///path/to/mirror)" % (name, root))


def purge(root="~/.mxnet/models/"):
    """Remove cached model blobs (parity: model_store.purge)."""
    root = os.path.expanduser(root)
    if os.path.isdir(root):
        for f in glob.glob(os.path.join(root, "*.params")):
            os.remove(f)


def short_hash(name):
    """First 8 hex chars of the model's weight-file hash (parity:
    model_store.short_hash — keyed off the registered checksum table)."""
    if name not in _checksums:
        raise ValueError("Pretrained model for %s is not available." % name)
    return _checksums[name][:8]
