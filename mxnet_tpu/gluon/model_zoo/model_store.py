"""Pretrained-weight store for the Gluon model zoo.

Parity: reference ``python/mxnet/gluon/model_zoo/model_store.py``
(get_model_file/purge). The reference downloads sha1-pinned blobs from
the Apache repo; this build runs zero-egress, so resolution order is:

1. ``{root}/{name}.params`` (or ``{name}-*.params``, the reference's
   hash-suffixed naming) on the local filesystem;
2. ``MXNET_GLUON_REPO`` pointing at a ``file://`` directory laid out the
   same way (the reference honours the same env var for mirrors);
3. otherwise a clear error telling the user where to place the file.

Blob format is the reference checkpoint format (``nd.save`` dict with
``arg:``/``aux:`` prefixes as written by ``Block.save_params``), so
params exported from the reference load unchanged. Weights are stored in
the reference's channels-first layouts — load into models built with the
default (NCHW) layout.
"""
from __future__ import annotations

import glob
import os
import shutil

from ...base import MXNetError

__all__ = ["get_model_file", "purge"]


def _candidates(name, root):
    out = [os.path.join(root, name + ".params")]
    out.extend(sorted(glob.glob(os.path.join(root, name + "-*.params"))))
    return out


def get_model_file(name, root="~/.mxnet/models/"):
    """Return the local path of the pretrained blob for ``name``
    (parity: model_store.get_model_file)."""
    root = os.path.expanduser(root)
    for path in _candidates(name, root):
        if os.path.exists(path):
            return path
    repo = os.environ.get("MXNET_GLUON_REPO", "")
    if repo.startswith("file://"):
        src_root = repo[len("file://"):]
        for src in _candidates(name, src_root):
            if os.path.exists(src):
                os.makedirs(root, exist_ok=True)
                dst = os.path.join(root, os.path.basename(src))
                shutil.copyfile(src, dst)
                return dst
    raise MXNetError(
        "pretrained weights for %r not found under %r (zero-egress build: "
        "place the reference-format .params file there, or set "
        "MXNET_GLUON_REPO=file:///path/to/mirror)" % (name, root))


def purge(root="~/.mxnet/models/"):
    """Remove cached model blobs (parity: model_store.purge)."""
    root = os.path.expanduser(root)
    if os.path.isdir(root):
        for f in glob.glob(os.path.join(root, "*.params")):
            os.remove(f)
