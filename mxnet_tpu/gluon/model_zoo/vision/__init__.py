"""Vision model zoo (parity: python/mxnet/gluon/model_zoo/vision)."""
from . import resnet as _resnet
from . import alexnet as _alexnet
from . import vgg as _vgg
from . import squeezenet as _squeezenet
from . import densenet as _densenet
from . import inception as _inception
from . import mobilenet as _mobilenet

from .resnet import *  # noqa: F401,F403
from .alexnet import *  # noqa: F401,F403
from .vgg import *  # noqa: F401,F403
from .squeezenet import *  # noqa: F401,F403
from .densenet import *  # noqa: F401,F403
from .inception import *  # noqa: F401,F403
from .mobilenet import *  # noqa: F401,F403

from ....base import MXNetError


_models = {}
for _mod in (_resnet, _alexnet, _vgg, _squeezenet, _densenet, _inception,
             _mobilenet):
    for _name in _mod.__all__:
        _obj = getattr(_mod, _name)
        if callable(_obj) and _name[0].islower() and not _name.startswith("get_"):
            _models[_name] = _obj

# the reference's get_model keys use dots (model_store.py naming); python
# function identifiers cannot, so register both spellings
for _ref, _fn in [("mobilenet1.0", "mobilenet1_0"),
                  ("mobilenet0.75", "mobilenet0_75"),
                  ("mobilenet0.5", "mobilenet0_5"),
                  ("mobilenet0.25", "mobilenet0_25"),
                  ("squeezenet1.0", "squeezenet1_0"),
                  ("squeezenet1.1", "squeezenet1_1"),
                  ("inceptionv3", "inception_v3")]:
    if _fn in _models:
        _models[_ref] = _models[_fn]


def get_model(name, **kwargs):
    """(parity: model_zoo.vision.get_model)"""
    name = name.lower()
    if name not in _models:
        raise MXNetError("model %r not in zoo (have: %s)"
                         % (name, sorted(_models)))
    return _models[name](**kwargs)
