"""Gluon: the imperative-first neural-network API.

Parity: reference ``python/mxnet/gluon/__init__.py``.
"""
from .parameter import DeferredInitializationError, Parameter, ParameterDict
from .block import Block, HybridBlock, SymbolBlock
from .trainer import Trainer
from . import nn
from . import rnn
from . import loss
from . import data
from . import utils
from . import model_zoo
from . import contrib
