"""Gluon losses.

Parity: reference ``python/mxnet/gluon/loss.py`` (L2Loss, L1Loss,
SigmoidBinaryCrossEntropyLoss, SoftmaxCrossEntropyLoss, KLDivLoss,
CTCLoss, + Huber/Hinge additions from the era).
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from .block import HybridBlock

__all__ = ["Loss", "L2Loss", "L1Loss", "SigmoidBinaryCrossEntropyLoss",
           "SquaredHingeLoss", "LogisticLoss", "TripletLoss",
           "SigmoidBCELoss", "SoftmaxCrossEntropyLoss", "SoftmaxCELoss",
           "KLDivLoss", "CTCLoss", "HuberLoss", "HingeLoss"]


def _apply_weighting(F, loss, weight=None, sample_weight=None):
    """(parity: loss._apply_weighting)"""
    if sample_weight is not None:
        loss = F.broadcast_mul(loss, sample_weight)
    if weight is not None:
        loss = loss * weight
    return loss


def _reshape_like(F, x, y):
    return x.reshape(y.shape)


class Loss(HybridBlock):
    """(parity: loss.Loss)"""

    def __init__(self, weight, batch_axis, **kwargs):
        super().__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError


class L2Loss(Loss):
    """0.5 * (pred - label)^2 (parity: loss.L2Loss)"""

    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(pred - label)
        loss = _apply_weighting(F, loss, self._weight / 2, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class L1Loss(Loss):
    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(pred - label)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class SigmoidBinaryCrossEntropyLoss(Loss):
    """(parity: loss.SigmoidBinaryCrossEntropyLoss) — numerically stable
    log-sum-exp form."""

    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        if not self._from_sigmoid:
            max_val = F.relu(-pred)
            loss = pred - pred * label + max_val + \
                F.log(F.exp(-max_val) + F.exp(-pred - max_val))
        else:
            eps = 1e-12
            loss = -(F.log(pred + eps) * label
                     + F.log(1.0 - pred + eps) * (1.0 - label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class SoftmaxCrossEntropyLoss(Loss):
    """(parity: loss.SoftmaxCrossEntropyLoss)"""

    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        if self._sparse_label:
            loss = -F.pick(pred, label, axis=self._axis, keepdims=True)
        else:
            label = _reshape_like(F, label, pred)
            loss = -F.sum(pred * label, axis=self._axis, keepdims=True)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class KLDivLoss(Loss):
    """(parity: loss.KLDivLoss)"""

    def __init__(self, from_logits=True, axis=-1, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = F.log_softmax(pred, axis=self._axis)
        loss = label * (F.log(label + 1e-12) - pred)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class HuberLoss(Loss):
    def __init__(self, rho=1.0, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.abs(pred - label)
        loss = F.where(loss > self._rho,
                       loss - 0.5 * self._rho,
                       (0.5 / self._rho) * F.square(loss))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class HingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.relu(self._margin - pred * label)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class SquaredHingeLoss(Loss):
    """(parity: loss.SquaredHingeLoss) L = max(0, margin - pred*label)^2,
    labels in {-1, 1}."""

    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        loss = F.square(F.relu(self._margin - pred * label))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class LogisticLoss(Loss):
    """(parity: loss.LogisticLoss) log(1 + exp(-pred*label)); binary
    label_format maps {0,1} -> {-1,1}."""

    def __init__(self, weight=None, batch_axis=0, label_format="signed",
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        if label_format not in ("signed", "binary"):
            raise MXNetError("label_format must be signed or binary")
        self._label_format = label_format

    def hybrid_forward(self, F, pred, label, sample_weight=None):
        label = _reshape_like(F, label, pred)
        if self._label_format == "binary":
            label = 2 * label - 1
        # log(1+exp(-z)) = relu(-z) + log1p(exp(-|z|)), the stable form
        z = pred * label
        loss = F.relu(-z) + F.log1p(F.exp(-F.abs(z)))
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return F.mean(loss, axis=self._batch_axis, exclude=True)


class TripletLoss(Loss):
    """(parity: loss.TripletLoss) max(|a-p|^2 - |a-n|^2 + margin, 0)
    summed over the feature axes."""

    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def hybrid_forward(self, F, pred, positive, negative,
                       sample_weight=None):
        positive = _reshape_like(F, positive, pred)
        negative = _reshape_like(F, negative, pred)
        loss = F.sum(F.square(pred - positive) - F.square(pred - negative),
                     axis=self._batch_axis, exclude=True)
        loss = F.relu(loss + self._margin)
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return loss


class CTCLoss(Loss):
    """CTC loss (parity: loss.CTCLoss over the reference's warpctc/contrib
    CTC kernels). Implemented with a jax log-domain forward algorithm —
    lax.scan over time, vectorised over batch (XLA-friendly)."""

    def __init__(self, layout="NTC", label_layout="NT", weight=None,
                 **kwargs):
        if layout not in ("NTC", "TNC"):
            raise MXNetError("layout must be NTC or TNC")
        super().__init__(weight, 0, **kwargs)
        self._layout = layout
        self._label_layout = label_layout

    def hybrid_forward(self, F, pred, label, pred_lengths=None,
                       label_lengths=None, sample_weight=None):
        import jax.numpy as jnp
        from ..ndarray.ndarray import NDArray, _wrap
        from .. import imperative as _imp
        from ..ops.registry import get_op
        if self._layout == "TNC":
            pred = pred.swapaxes(0, 1)
        if self._label_layout == "TN":
            label = label.swapaxes(0, 1)
        loss = _imp.invoke(get_op("_ctc_loss"),
                           [pred, label] +
                           ([pred_lengths] if pred_lengths is not None else []) +
                           ([label_lengths] if label_lengths is not None else []),
                           {})
        loss = _apply_weighting(F, loss, self._weight, sample_weight)
        return loss
