"""Gluon datasets (parity: python/mxnet/gluon/data/dataset.py)."""
from __future__ import annotations

from ...base import MXNetError
from ...ndarray.ndarray import NDArray

__all__ = ["Dataset", "SimpleDataset", "ArrayDataset", "RecordFileDataset"]


class Dataset:
    """(parity: data.Dataset)"""

    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError

    def raw_item(self, idx):
        """Item as a host-only (numpy/bytes) tree, or None if this
        dataset cannot produce one. The DataLoader's process workers are
        accelerator-free by contract (a forked child must never touch
        the PJRT client), so only datasets with a raw path ride them —
        the reference's fork-safety concern, solved in its engine by
        pthread_atfork (SURVEY.md §2.1), lands here instead."""
        return None

    def transform(self, fn, lazy=True):
        trans = _LazyTransformDataset(self, fn)
        if lazy:
            return trans
        return SimpleDataset([trans[i] for i in range(len(trans))])

    def transform_first(self, fn, lazy=True):
        return self.transform(_TransformFirstClosure(fn), lazy)


def _is_host_tree(item):
    import numpy as np
    if isinstance(item, (tuple, list)):
        return all(_is_host_tree(x) for x in item)
    return isinstance(item, (np.ndarray, np.generic, bytes, bytearray,
                             int, float))


class SimpleDataset(Dataset):
    def __init__(self, data):
        self._data = data

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        return self._data[idx]

    def raw_item(self, idx):
        item = self._data[idx]
        return item if _is_host_tree(item) else None


class _LazyTransformDataset(Dataset):
    def __init__(self, data, fn):
        self._data = data
        self._fn = fn

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        item = self._data[idx]
        if isinstance(item, tuple):
            return self._fn(*item)
        return self._fn(item)


class _TransformFirstClosure:
    def __init__(self, fn):
        self._fn = fn

    def __call__(self, x, *args):
        if args:
            return (self._fn(x),) + args
        return self._fn(x)


class ArrayDataset(Dataset):
    """(parity: data.ArrayDataset)"""

    def __init__(self, *args):
        assert len(args) > 0
        self._length = len(args[0])
        self._data = []
        for data in args:
            assert len(data) == self._length, "all arrays must be same length"
            self._data.append(data)

    def __getitem__(self, idx):
        if len(self._data) == 1:
            return self._data[0][idx]
        return tuple(d[idx] for d in self._data)

    def raw_item(self, idx):
        cols = self._raw_columns()
        if len(cols) == 1:
            return cols[0][idx]
        return tuple(c[idx] for c in cols)

    def _raw_columns(self):
        """Host-only column views, materialised ONCE (in the parent — the
        DataLoader probes raw_item(0) before forking, so device-backed
        columns are pulled to numpy before any worker exists)."""
        import numpy as np
        cached = getattr(self, "_raw_cols", None)
        if cached is None:
            cached = [np.asarray(d.asnumpy() if isinstance(d, NDArray)
                                 else d) for d in self._data]
            self._raw_cols = cached
        return cached

    def __len__(self):
        return self._length


class RecordFileDataset(Dataset):
    """(parity: data.RecordFileDataset over RecordIO)"""

    def __init__(self, filename):
        from ... import recordio
        idx_file = filename[:filename.rfind(".")] + ".idx"
        self._record = recordio.MXIndexedRecordIO(idx_file, filename, "r")

    def __getitem__(self, idx):
        return self._record.read_idx(self._record.keys[idx])

    raw_item = __getitem__          # record bytes are host-only already

    def __len__(self):
        return len(self._record.keys)
