"""Vision datasets (parity: python/mxnet/gluon/data/vision.py).

Zero-egress build: datasets read standard local files (MNIST idx,
CIFAR-10 binary batches) from their `root` directory instead of
downloading; a synthetic fallback is available for smoke tests.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct

import numpy as np

from ...base import MXNetError
from ...ndarray import array as nd_array
from .dataset import Dataset, RecordFileDataset

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageRecordDataset", "ImageFolderDataset"]


class _DownloadedDataset(Dataset):
    def __init__(self, root, train, transform):
        self._root = os.path.expanduser(root)
        self._train = train
        self._transform = transform
        self._data = None
        self._label = None
        self._get_data()

    def __getitem__(self, idx):
        if self._transform is not None:
            return self._transform(nd_array(self._data[idx]),
                                   self._label[idx])
        return nd_array(self._data[idx]), self._label[idx]

    def raw_item(self, idx):
        # transforms take NDArrays, which an accelerator-free worker
        # process cannot build — those datasets fall back to threads
        if self._transform is not None:
            return None
        return self._data[idx], self._label[idx]

    def __len__(self):
        return len(self._label)

    def _get_data(self):
        raise NotImplementedError


class MNIST(_DownloadedDataset):
    """MNIST from local idx files (parity: vision.MNIST)."""

    _train_files = ("train-images-idx3-ubyte", "train-labels-idx1-ubyte")
    _test_files = ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte")

    def __init__(self, root="~/.mxnet/datasets/mnist", train=True,
                 transform=None):
        super().__init__(root, train, transform)

    def _read(self, img_path, lbl_path):
        def _open(p):
            if os.path.exists(p):
                return open(p, "rb")
            if os.path.exists(p + ".gz"):
                return gzip.open(p + ".gz", "rb")
            raise MXNetError("dataset file %r not found (zero-egress build: "
                             "place files locally)" % p)
        with _open(lbl_path) as f:
            struct.unpack(">II", f.read(8))
            label = np.frombuffer(f.read(), dtype=np.uint8).astype(np.int32)
        with _open(img_path) as f:
            _, num, rows, cols = struct.unpack(">IIII", f.read(16))
            data = np.frombuffer(f.read(), dtype=np.uint8)
            data = data.reshape(num, rows, cols, 1)
        return data, label

    def _get_data(self):
        files = self._train_files if self._train else self._test_files
        img = os.path.join(self._root, files[0])
        lbl = os.path.join(self._root, files[1])
        self._data, self._label = self._read(img, lbl)


class FashionMNIST(MNIST):
    """(parity: vision.FashionMNIST — same idx format)"""

    def __init__(self, root="~/.mxnet/datasets/fashion-mnist", train=True,
                 transform=None):
        super().__init__(root, train, transform)


class CIFAR10(_DownloadedDataset):
    """CIFAR-10 from the local binary batches (parity: vision.CIFAR10)."""

    def __init__(self, root="~/.mxnet/datasets/cifar10", train=True,
                 transform=None):
        super().__init__(root, train, transform)

    def _get_data(self):
        if self._train:
            files = ["data_batch_%d.bin" % i for i in range(1, 6)]
        else:
            files = ["test_batch.bin"]
        data_list, label_list = [], []
        for fname in files:
            path = os.path.join(self._root, fname)
            if not os.path.exists(path):
                raise MXNetError("dataset file %r not found (zero-egress "
                                 "build: place files locally)" % path)
            with open(path, "rb") as f:
                raw = np.frombuffer(f.read(), dtype=np.uint8)
            raw = raw.reshape(-1, 3073)
            label_list.append(raw[:, 0].astype(np.int32))
            data_list.append(
                raw[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1))
        self._data = np.concatenate(data_list)
        self._label = np.concatenate(label_list)


class CIFAR100(_DownloadedDataset):
    def __init__(self, root="~/.mxnet/datasets/cifar100", train=True,
                 fine_label=True, transform=None):
        self._fine = fine_label
        super().__init__(root, train, transform)

    def _get_data(self):
        fname = "train.bin" if self._train else "test.bin"
        path = os.path.join(self._root, fname)
        if not os.path.exists(path):
            raise MXNetError("dataset file %r not found" % path)
        with open(path, "rb") as f:
            raw = np.frombuffer(f.read(), dtype=np.uint8)
        raw = raw.reshape(-1, 3074)
        self._label = raw[:, 1 if self._fine else 0].astype(np.int32)
        self._data = raw[:, 2:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)


class ImageRecordDataset(RecordFileDataset):
    """Dataset over a packed image RecordIO file (parity:
    vision.ImageRecordDataset)."""

    def __init__(self, filename, flag=1, transform=None):
        super().__init__(filename)
        self._flag = flag
        self._transform = transform

    def __getitem__(self, idx):
        from ... import recordio
        from ...image import image as img_mod
        record = super().__getitem__(idx)
        header, payload = recordio.unpack(record)
        image = img_mod.imdecode(payload, flag=self._flag)
        label = header.label
        if self._transform is not None:
            return self._transform(image, label)
        return image, label

    def raw_item(self, idx):
        return None   # decode emits NDArrays; thread workers handle it


class ImageFolderDataset(Dataset):
    """Folder-per-class image dataset (parity:
    vision.ImageFolderDataset): root/<label>/<image>.jpg."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = [".jpg", ".jpeg", ".png"]
        self._list_images(self._root)

    def _list_images(self, root):
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(root)):
            path = os.path.join(root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for filename in sorted(os.listdir(path)):
                if os.path.splitext(filename)[1].lower() in self._exts:
                    self.items.append((os.path.join(path, filename), label))

    def __getitem__(self, idx):
        from ...image import image as img_mod
        img = img_mod.imread(self.items[idx][0], flag=self._flag)
        label = self.items[idx][1]
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self.items)
