"""Gluon DataLoader.

Parity: reference ``python/mxnet/gluon/data/dataloader.py:73-115`` which
uses multiprocessing workers + POSIX-shm NDArrays
(``cpu_shared_storage_manager.h``). Two worker modes:

- THREADS (default): batch assembly is numpy/PIL-bound and releases the
  GIL; device transfer overlaps via PJRT async ``device_put``.
- PROCESSES (``thread_pool=False``, the reference's mode): forked
  workers that are **accelerator-free by contract** — a forked child
  must never touch the PJRT client (the reference re-arms its engine via
  pthread_atfork; no such hook exists for an XLA runtime). Workers
  therefore assemble batches from ``Dataset.raw_item`` numpy trees and
  ship them through POSIX shared memory (the reference's shm NDArray
  trick); the parent wraps them into NDArrays. Datasets without a raw
  path (e.g. with NDArray-consuming transforms) fall back to threads
  with a warning.
"""
from __future__ import annotations

import queue as _queue
import threading
import warnings

import numpy as np

from ...base import MXNetError
from ...ndarray.ndarray import NDArray
from ...ndarray import array as nd_array

__all__ = ["DataLoader", "default_batchify_fn"]


def _numpy_batchify(data):
    """Worker-side batchify over raw numpy items (no NDArray creation)."""
    if isinstance(data[0], (tuple, list)):
        return [_numpy_batchify(list(i)) for i in zip(*data)]
    return np.stack([np.asarray(d) for d in data])


def _tree_to_shm(tree, shm_list):
    """numpy tree -> picklable descriptor; arrays move into POSIX shm.
    The segment STAYS registered with the (fork-shared) resource
    tracker as a crash-cleanup net; the consumer unregisters when it
    unlinks, so the normal path produces no double-unlink warnings."""
    from multiprocessing import shared_memory
    if isinstance(tree, list):
        return ("list", [_tree_to_shm(t, shm_list) for t in tree])
    arr = np.ascontiguousarray(tree)
    shm = shared_memory.SharedMemory(create=True, size=max(arr.nbytes, 1))
    shm.buf[:arr.nbytes] = arr.tobytes()
    shm_list.append(shm)
    return ("shm", shm.name, arr.shape, str(arr.dtype))


def _tree_from_shm(desc):
    """Descriptor -> NDArray tree; copies out of shm then unlinks it."""
    from multiprocessing import shared_memory
    if desc[0] == "list":
        return [_tree_from_shm(d) for d in desc[1]]
    _, name, shape, dtype = desc
    from multiprocessing import resource_tracker
    shm = shared_memory.SharedMemory(name=name)
    try:
        arr = np.frombuffer(shm.buf, dtype=dtype)[:int(np.prod(shape))] \
            .reshape(shape).copy()
    finally:
        shm.close()
        shm.unlink()
        # attaching re-registered the segment in this process AND the
        # producer registered it at create; drop both claims now that
        # it is unlinked (fork shares one tracker, so this silences the
        # exit-time double-unlink warning while keeping the tracker as
        # the crash net for unconsumed segments)
        for _ in range(2):
            try:
                resource_tracker.unregister(shm._name, "shared_memory")
            except Exception:
                break
    return nd_array(arr)


def _proc_worker(dataset, idx_q, out_q):
    """Forked worker: numpy + shm only — never touches jax/PJRT."""
    while True:
        job = idx_q.get()
        if job is None:
            return
        seq, indices = job
        try:
            items = [dataset.raw_item(int(i)) for i in indices]
            batch = _numpy_batchify(items)
            shms = []
            desc = _tree_to_shm(batch, shms)
            out_q.put((seq, desc, None))
            for s in shms:
                s.close()         # parent owns the segment now
        except Exception as e:    # surface worker errors to the parent
            out_q.put((seq, None, "%s: %s" % (type(e).__name__, e)))


def default_batchify_fn(data):
    """(parity: dataloader.default_batchify_fn)"""
    if isinstance(data[0], NDArray):
        return nd_array(np.stack([d.asnumpy() for d in data]))
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(list(i)) for i in data]
    data = np.asarray(data)
    return nd_array(data)


class _BatchSampler:
    def __init__(self, length, batch_size, shuffle, last_batch):
        self._length = length
        self._batch_size = batch_size
        self._shuffle = shuffle
        self._last_batch = last_batch
        self._carry = np.zeros(0, np.int64)   # rollover residue

    def __iter__(self):
        order = np.arange(self._length)
        if self._shuffle:
            np.random.shuffle(order)
        if self._last_batch == "rollover" and len(self._carry):
            order = np.concatenate([self._carry, order])
            self._carry = np.zeros(0, np.int64)
        n = len(order) // self._batch_size * self._batch_size
        for i in range(0, n, self._batch_size):
            yield order[i:i + self._batch_size]
        rem = order[n:]
        if len(rem):
            if self._last_batch == "keep":
                yield rem
            elif self._last_batch == "rollover":
                # incomplete batch carries into the NEXT epoch (reference
                # sampler.BatchSampler 'rollover' semantics)
                self._carry = rem
            elif self._last_batch == "discard":
                return

    def __len__(self):
        n, b = self._length, self._batch_size
        if self._last_batch == "discard":
            return n // b
        if self._last_batch == "rollover":
            return (len(self._carry) + n) // b
        return (n + b - 1) // b


class DataLoader:
    """(parity: gluon.data.DataLoader)"""

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None,
                 thread_pool=True):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise MXNetError("batch_size required when no batch_sampler")
            if sampler is not None:
                if shuffle:
                    raise MXNetError("shuffle is exclusive with a custom "
                                     "sampler (reference contract)")
                from .sampler import BatchSampler
                batch_sampler = BatchSampler(sampler, batch_size,
                                             last_batch or "keep")
            else:
                batch_sampler = _BatchSampler(len(dataset), batch_size,
                                              shuffle, last_batch or "keep")
        elif sampler is not None:
            raise MXNetError("batch_sampler is exclusive with sampler")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = max(0, num_workers)
        self._prefetch = max(2, prefetch or 2 * max(self._num_workers, 1))
        self._thread_pool = thread_pool

    def __len__(self):
        return len(self._batch_sampler)

    def _make_batch(self, indices):
        return self._batchify_fn([self._dataset[int(i)] for i in indices])

    def __iter__(self):
        if self._num_workers == 0:
            for indices in self._batch_sampler:
                yield self._make_batch(indices)
            return
        if not self._thread_pool:
            # probe the raw path IN THE PARENT: device-backed columns get
            # pulled to host here, before any fork
            if self._batchify_fn is not default_batchify_fn:
                warnings.warn("DataLoader: custom batchify_fn cannot run "
                              "in accelerator-free worker processes; "
                              "falling back to threads")
            elif self._dataset.raw_item(0) is None:
                warnings.warn("DataLoader: dataset has no raw (host-only) "
                              "item path; falling back to threads")
            else:
                yield from self._process_iter()
                return
        yield from self._threaded_iter()

    def _process_iter(self):
        import multiprocessing as mp
        ctx = mp.get_context("fork")
        idx_q = ctx.Queue()
        out_q = ctx.Queue()
        jobs = [(i, np.asarray(ix))
                for i, ix in enumerate(self._batch_sampler)]
        n_batches = len(jobs)
        # backpressure: at most `prefetch` batches in flight — workers
        # only get a new job when the parent consumes one (the process
        # analogue of the threaded path's bounded out queue; unbounded
        # production would fill /dev/shm with unconsumed segments)
        in_flight = min(self._prefetch, n_batches)
        for job in jobs[:in_flight]:
            idx_q.put(job)
        feed_next = in_flight
        procs = [ctx.Process(target=_proc_worker,
                             args=(self._dataset, idx_q, out_q),
                             daemon=True)
                 for _ in range(self._num_workers)]
        for p in procs:
            p.start()
        pending = {}

        def _drain_pending():
            """Release shm of every produced-but-unconsumed batch: both
            the reordering buffer and results still sitting in out_q
            (workers already closed their handles — the parent must
            attach+unlink or the segments outlive the epoch)."""
            while True:
                try:
                    _seq, desc, err = out_q.get_nowait()
                except _queue.Empty:
                    break
                if err is None:
                    pending[_seq] = desc
            for desc in pending.values():
                try:
                    _tree_from_shm(desc)
                except Exception:
                    pass
            pending.clear()

        try:
            next_seq = 0
            received = 0
            empty_strikes = 0
            while received < n_batches:
                try:
                    seq, desc, err = out_q.get(timeout=5.0)
                except _queue.Empty:
                    # a DEAD worker that still held a job can never post
                    # its result: any death + sustained silence = hang,
                    # raise instead of spinning (strikes reset on
                    # progress, so a dead-but-finished worker is fine
                    # while the others keep producing)
                    empty_strikes += 1
                    if empty_strikes >= 3 and \
                            any(not p.is_alive() for p in procs):
                        raise MXNetError(
                            "DataLoader worker process died without "
                            "reporting a result (killed/OOM?)")
                    continue
                empty_strikes = 0
                if err is not None:
                    raise MXNetError("DataLoader worker failed: %s" % err)
                received += 1
                if feed_next < n_batches:
                    idx_q.put(jobs[feed_next])
                    feed_next += 1
                pending[seq] = desc
                while next_seq in pending:
                    yield _tree_from_shm(pending.pop(next_seq))
                    next_seq += 1
            while next_seq in pending:
                yield _tree_from_shm(pending.pop(next_seq))
                next_seq += 1
        finally:
            for _ in range(self._num_workers):
                idx_q.put(None)
            # give workers a beat to flush results already in transit,
            # then reclaim every unconsumed segment before terminating
            for p in procs:
                p.join(timeout=0.2)
            _drain_pending()
            for p in procs:
                p.terminate()
            for p in procs:
                p.join(timeout=5)

    def _threaded_iter(self):
        out_q = _queue.Queue(maxsize=self._prefetch)
        idx_q = _queue.Queue()
        n_batches = 0
        for indices in self._batch_sampler:
            idx_q.put((n_batches, indices))
            n_batches += 1
        results = {}
        lock = threading.Lock()

        def worker():
            while True:
                try:
                    seq, indices = idx_q.get_nowait()
                except _queue.Empty:
                    return
                batch = self._make_batch(indices)
                out_q.put((seq, batch))

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(self._num_workers)]
        for t in threads:
            t.start()
        next_seq = 0
        received = 0
        pending = {}
        while received < n_batches:
            seq, batch = out_q.get()
            received += 1
            pending[seq] = batch
            while next_seq in pending:
                yield pending.pop(next_seq)
                next_seq += 1
        while next_seq in pending:
            yield pending.pop(next_seq)
            next_seq += 1
