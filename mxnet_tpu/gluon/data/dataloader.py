"""Gluon DataLoader.

Parity: reference ``python/mxnet/gluon/data/dataloader.py:73-115`` which
uses multiprocessing workers + POSIX-shm NDArrays. TPU-native design:
worker THREADS + a bounded prefetch queue — batch assembly is numpy-bound
and releases the GIL; device transfer overlaps via PJRT async
``device_put``, which replaces the reference's shared-memory trick.
"""
from __future__ import annotations

import queue as _queue
import threading

import numpy as np

from ...base import MXNetError
from ...ndarray.ndarray import NDArray
from ...ndarray import array as nd_array

__all__ = ["DataLoader", "default_batchify_fn"]


def default_batchify_fn(data):
    """(parity: dataloader.default_batchify_fn)"""
    if isinstance(data[0], NDArray):
        return nd_array(np.stack([d.asnumpy() for d in data]))
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(list(i)) for i in data]
    data = np.asarray(data)
    return nd_array(data)


class _BatchSampler:
    def __init__(self, length, batch_size, shuffle, last_batch):
        self._length = length
        self._batch_size = batch_size
        self._shuffle = shuffle
        self._last_batch = last_batch

    def __iter__(self):
        order = np.arange(self._length)
        if self._shuffle:
            np.random.shuffle(order)
        n = self._length // self._batch_size * self._batch_size
        for i in range(0, n, self._batch_size):
            yield order[i:i + self._batch_size]
        rem = self._length - n
        if rem:
            if self._last_batch == "keep":
                yield order[n:]
            elif self._last_batch == "rollover":
                yield order[n:]  # simplified: no cross-epoch carry
            elif self._last_batch == "discard":
                return

    def __len__(self):
        n, b = self._length, self._batch_size
        if self._last_batch == "discard":
            return n // b
        return (n + b - 1) // b


class DataLoader:
    """(parity: gluon.data.DataLoader)"""

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, prefetch=None):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise MXNetError("batch_size required when no batch_sampler")
            batch_sampler = _BatchSampler(len(dataset), batch_size,
                                          shuffle, last_batch or "keep")
        self._batch_sampler = batch_sampler
        self._batchify_fn = batchify_fn or default_batchify_fn
        self._num_workers = max(0, num_workers)
        self._prefetch = max(2, prefetch or 2 * max(self._num_workers, 1))

    def __len__(self):
        return len(self._batch_sampler)

    def _make_batch(self, indices):
        return self._batchify_fn([self._dataset[int(i)] for i in indices])

    def __iter__(self):
        if self._num_workers == 0:
            for indices in self._batch_sampler:
                yield self._make_batch(indices)
            return
        yield from self._threaded_iter()

    def _threaded_iter(self):
        out_q = _queue.Queue(maxsize=self._prefetch)
        idx_q = _queue.Queue()
        n_batches = 0
        for indices in self._batch_sampler:
            idx_q.put((n_batches, indices))
            n_batches += 1
        results = {}
        lock = threading.Lock()

        def worker():
            while True:
                try:
                    seq, indices = idx_q.get_nowait()
                except _queue.Empty:
                    return
                batch = self._make_batch(indices)
                out_q.put((seq, batch))

        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(self._num_workers)]
        for t in threads:
            t.start()
        next_seq = 0
        received = 0
        pending = {}
        while received < n_batches:
            seq, batch = out_q.get()
            received += 1
            pending[seq] = batch
            while next_seq in pending:
                yield pending.pop(next_seq)
                next_seq += 1
        while next_seq in pending:
            yield pending.pop(next_seq)
            next_seq += 1
