"""Gluon Blocks: imperative-first layers with optional graph capture.

Parity: reference ``python/mxnet/gluon/block.py`` (Block:121,
HybridBlock:319, hybridize:277 → CachedOp). TPU-native design: a
hybridized block's forward is traced ONCE per input signature into a
jitted XLA program (``_CachedOp``) — the exact contract of the
reference's CachedOp (cached_op.cc:171-322, re-plan per signature), but
the "graph" is XLA's, so fusion/memory planning come free. Under
``autograd.record`` the whole cached program becomes ONE tape node whose
backward is a second jitted program (forward rematerialised — HBM is the
scarce resource on TPU, recompute is the standard trade).

BatchNorm-style running-stat updates inside a traced program are
collected as extra outputs and written back after execution
(ops/common.aux_collector), keeping the compiled function pure.
"""
from __future__ import annotations

import re
import threading

import numpy as np

import jax

from ..base import MXNetError, NameManager
from ..context import current_context
from .. import autograd
from .. import imperative as _imp
from ..imperative import TapeNode
from ..ndarray.ndarray import NDArray, _wrap
from ..ops import common as _common
from .parameter import Parameter, ParameterDict, DeferredInitializationError

__all__ = ["Block", "HybridBlock", "SymbolBlock"]


class _BlockScope:
    """Name scoping (parity: block._BlockScope)."""

    _current = threading.local()

    def __init__(self, block):
        self._block = block
        self._counter = {}
        self._old_scope = None

    @staticmethod
    def create(prefix, params, hint):
        current = getattr(_BlockScope._current, "value", None)
        if current is None:
            if prefix is None:
                prefix = NameManager.current.get(None, hint) + "_"
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, params)
            return prefix, params
        if prefix is None:
            count = current._counter.get(hint, 0)
            current._counter[hint] = count + 1
            prefix = "%s%d_" % (hint, count)
        if params is None:
            parent = current._block.params
            params = ParameterDict(parent.prefix + prefix, parent._shared)
        else:
            params = ParameterDict(params.prefix, params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        if self._block._empty_prefix:
            return self
        self._old_scope = getattr(_BlockScope._current, "value", None)
        _BlockScope._current.value = self
        return self

    def __exit__(self, *exc):
        if self._block._empty_prefix:
            return
        _BlockScope._current.value = self._old_scope


class Block:
    """(parity: gluon.Block:121)"""

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _BlockScope.create(
            prefix, params, self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith("_") \
            else self._prefix
        self._scope = _BlockScope(self)
        self._children = {}
        self._reg_params = {}

    def _alias(self):
        return self.__class__.__name__.lower()

    def __repr__(self):
        s = "{name}(\n{modstr}\n)"
        modstr = "\n".join("  ({key}): {block}".format(
            key=key, block=_indent(repr(block), 2))
            for key, block in self._children.items())
        return s.format(name=self.__class__.__name__, modstr=modstr)

    def __setattr__(self, name, value):
        if isinstance(value, Block):
            existing = getattr(self, "_children", None)
            if existing is not None:
                self._children[name] = value
        elif isinstance(value, Parameter):
            if hasattr(self, "_reg_params"):
                self._reg_params[name] = value
        super().__setattr__(name, value)

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    def name_scope(self):
        return self._scope

    @property
    def params(self):
        return self._params

    def collect_params(self, select=None):
        """(parity: Block.collect_params) including children, optionally
        filtered by regex."""
        ret = ParameterDict(self._params.prefix)
        if select is None:
            ret.update(self.params)
        else:
            pattern = re.compile(select)
            ret.update({k: v for k, v in self.params.items()
                        if pattern.match(k)})
        for child in self._children.values():
            ret.update(child.collect_params(select=select))
        return ret

    def register_child(self, block, name=None):
        self._children[name or str(len(self._children))] = block

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        from ..initializer import Uniform
        self.collect_params().initialize(init or Uniform(0.07), ctx=ctx,
                                         force_reinit=force_reinit)

    def save_params(self, filename):
        self.collect_params().save(filename, strip_prefix=self.prefix)

    save_parameters = save_params

    def load_params(self, filename, ctx=None, allow_missing=False,
                    ignore_extra=False):
        self.collect_params().load(filename, ctx, allow_missing, ignore_extra,
                                   restore_prefix=self.prefix)

    load_parameters = load_params

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for _, param in self.params.items():
            param.cast(dtype)

    def hybridize(self, active=True, **kwargs):
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    def __call__(self, *args):
        return self.forward(*args)

    def forward(self, *args):
        raise NotImplementedError


class HybridBlock(Block):
    """(parity: gluon.HybridBlock:319)"""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._cached_op = None

    def hybridize(self, active=True, **kwargs):
        self._active = active
        self._cached_op = None
        super().hybridize(active, **kwargs)

    def cast(self, dtype):
        self._cached_op = None
        super().cast(dtype)

    def infer_shape(self, *args):
        self._deferred_infer(args)

    def infer_type(self, *args):
        """Infer parameter dtypes from example inputs (parity:
        block.infer_type — shapes and dtypes flow through the same
        abstract forward here)."""
        self._deferred_infer(args)

    def export(self, path):
        """Write ``path-symbol.json`` + ``path-0000.params`` in the
        checkpoint format (parity: block.export). The graph is captured
        by re-running the block on a Symbol input; parameters must be
        initialised (run one forward first)."""
        from .. import symbol as sym_mod
        out = self(sym_mod.Variable("data"))
        if isinstance(out, (list, tuple)):
            out = sym_mod.Group(list(out))
        out.save("%s-symbol.json" % path)
        aux_names = set(out.list_auxiliary_states())
        from ..ndarray import save as nd_save
        blob = {}
        for name, param in self.collect_params().items():
            prefix = "aux:" if name in aux_names else "arg:"
            blob[prefix + name] = param.data()
        nd_save("%s-0000.params" % path, blob)

    def _deferred_infer(self, args):
        """Run an abstract forward to fill deferred param shapes."""
        try:
            structs = [jax.ShapeDtypeStruct(a.shape, a._data.dtype)
                       for a in args]

            def probe(*raw):
                nd_in = [_wrap(r) for r in raw]
                with autograd.pause():
                    out = self._forward_eager(*nd_in)
                outs = out if isinstance(out, (list, tuple)) else [out]
                return tuple(o._data for o in outs)
            with _common.rng_scope(jax.random.key(0)):
                jax.eval_shape(probe, *structs)
        except DeferredInitializationError:
            raise
        except Exception:
            raise

    def __call__(self, *args):
        from ..symbol import Symbol as _Sym
        if args and isinstance(args[0], _Sym):
            # symbolic capture (reference: hybrid_forward(F=symbol) when
            # called on Symbols) — powers export()
            return self._forward_symbol(*args)
        if self._active and not _common.state().graph_capturing:
            return self._call_cached_op(*args)
        return self._forward_eager(*args)

    def _forward_symbol(self, x, *args):
        from .. import symbol as F
        params = {name: F.Variable(p.name)
                  for name, p in self._reg_params.items()}
        return self.hybrid_forward(F, x, *args, **params)

    # -- eager path --------------------------------------------------------
    def _forward_eager(self, x, *args):
        params = {}
        try:
            for name, p in self._reg_params.items():
                params[name] = p.data()
        except DeferredInitializationError:
            self._infer_param_shapes(x, *args)
            for name, p in self._reg_params.items():
                params[name] = p.data()
        from .. import ndarray as F
        return self.hybrid_forward(F, x, *args, **params)

    def _infer_param_shapes(self, *args):
        """Default deferred-shape inference hook; layers override
        shape-specific logic via their own _update_shapes."""
        self._shape_hook(*args)
        for p in self._reg_params.values():
            if p._deferred_init is not None:
                p._finish_deferred_init()

    def _shape_hook(self, *args):
        raise DeferredInitializationError(
            "Block %r has deferred parameters and no shape hook; specify "
            "in_units/in_channels" % self.name)

    def forward(self, x, *args):
        return self.__call__(x, *args)

    def hybrid_forward(self, F, x, *args, **kwargs):
        raise NotImplementedError

    # -- cached (jitted) path ---------------------------------------------
    def _call_cached_op(self, *args):
        if self._cached_op is None:
            self._cached_op = _CachedOp(self)
        return self._cached_op(*args)


class _CachedOp:
    """Trace-and-cache executor for a HybridBlock.

    Parity: reference Imperative::CachedOp (src/imperative/cached_op.cc) —
    one compiled program per input signature, gradient support, aux-state
    writeback.
    """

    def __init__(self, block):
        self.block = block
        self._cache = {}

    def __call__(self, *inputs):
        block = self.block
        # materialise params (triggers deferred init through one eager call)
        try:
            params = list(block.collect_params().values())
            param_nds = [p.data() for p in params]
        except DeferredInitializationError:
            with autograd.pause():
                block._forward_eager(*inputs)
            params = list(block.collect_params().values())
            param_nds = [p.data() for p in params]
        train = autograd.is_training()
        raw_inputs = [x._data for x in inputs]
        key = (tuple((tuple(r.shape), str(r.dtype)) for r in raw_inputs),
               train)
        entry = self._cache.get(key)
        if entry is None:
            entry = self._build(key, train, inputs)
        fwd, grads_fn, aux_targets, n_out, single = entry

        rng = _take_rng_key()
        raw_params = [p._data for p in param_nds]
        out_raw, aux_raw = fwd(raw_params, raw_inputs, rng)
        for target, val in zip(aux_targets, aux_raw):
            target._set_data(val)

        out_nds = [_wrap(r) for r in out_raw]
        if autograd.is_recording():
            all_in = param_nds + list(inputs)
            parents = [nd._tape if nd._tape is not None else None
                       for nd in all_in]
            if any(p is not None for p in parents):
                captured = (raw_params, raw_inputs, rng)

                def vjp_fn(out_cts):
                    p_cts, i_cts = grads_fn(captured[0], captured[1],
                                            captured[2], tuple(out_cts))
                    return tuple(p_cts) + tuple(i_cts)

                node = TapeNode(parents, vjp_fn,
                                [jax.ShapeDtypeStruct(o.shape, o.dtype)
                                 for o in out_raw], "CachedOp")
                for i, o in enumerate(out_nds):
                    o._tape = (node, i)
        return out_nds[0] if single else out_nds

    def _build(self, key, train, example_inputs):
        block = self.block
        params = list(block.collect_params().values())
        single_holder = [True]
        aux_targets = []

        def run_block(raw_params, raw_inputs, rng):
            collector = []
            originals = [p._data._data for p in params]
            st = _common.state()
            was_capturing = st.graph_capturing
            try:
                st.graph_capturing = True
                with autograd.pause(train_mode=train), \
                        _common.rng_scope(rng), \
                        _aux_collect(collector):
                    for p, r in zip(params, raw_params):
                        p._data._set_data(r)
                    nd_in = [_wrap(r) for r in raw_inputs]
                    out = block._forward_eager(*nd_in)
            finally:
                st.graph_capturing = was_capturing
                for p, orig in zip(params, originals):
                    p._data._set_data(orig)
            if isinstance(out, (list, tuple)):
                single_holder[0] = False   # mxlint: disable=trace-purity -- trace-time capture by design: populated once by the eval_shape probe below; holds a host bool, not a tracer
                outs = list(out)
            else:
                outs = [out]
            aux_targets.clear()   # mxlint: disable=trace-purity -- trace-time capture by design: refreshed per trace so retraces stay consistent; holds graph targets, not tracers
            aux_targets.extend(t for t, _ in collector)   # mxlint: disable=trace-purity -- trace-time capture by design: refreshed per trace so retraces stay consistent; holds graph targets, not tracers
            return tuple(o._data for o in outs), tuple(v for _, v in collector)

        fwd = jax.jit(run_block)

        n_params = len(params)

        def grads(raw_params, raw_inputs, rng, out_cts):
            def f(ps, ins):
                outs, _aux = run_block(ps, ins, rng)
                return outs
            outs, vjp = jax.vjp(f, raw_params, raw_inputs)
            cts = tuple(
                c if c is not None else _zeros_like_struct(o)
                for c, o in zip(out_cts, outs))
            p_cts, i_cts = vjp(cts)
            return p_cts, i_cts

        grads_fn = jax.jit(grads)

        # trace once now to populate aux_targets/single
        raw_inputs = [x._data for x in example_inputs]
        raw_params = [p.data()._data for p in params]
        _ = jax.eval_shape(lambda ps, ins, rng: run_block(ps, ins, rng),
                           raw_params, raw_inputs, jax.random.key(0))
        entry = (fwd, grads_fn, list(aux_targets), None, single_holder[0])
        self._cache[key] = entry
        return entry


def _zeros_like_struct(o):
    import jax.numpy as jnp
    return jnp.zeros(o.shape, o.dtype)


def _take_rng_key():
    from .. import random as _random
    return _random.take_key()


def make_pure_fn(block, train=False):
    """Extract a pure jax function from a (initialized) HybridBlock.

    Returns (fn, raw_params, names) where
    ``fn(raw_params_list, raw_inputs_list, rng) -> (outputs_tuple,
    aux_updates)`` and ``aux_updates`` maps param-list index -> new value
    (BatchNorm running stats). Used by bench/SPMD/graft entry to hand the
    whole model to jax.jit / jax.value_and_grad directly.
    """
    params = list(block.collect_params().values())
    names = [p.name for p in params]
    id_to_idx = {id(p._data): i for i, p in enumerate(params)}

    def fn(raw_params, raw_inputs, rng):
        collector = []
        originals = [p._data._data for p in params]
        st = _common.state()
        was_capturing = st.graph_capturing
        try:
            st.graph_capturing = True
            with autograd.pause(train_mode=train), _common.rng_scope(rng), \
                    _aux_collect(collector):
                for p, r in zip(params, raw_params):
                    p._data._set_data(r)
                nd_in = [_wrap(r) for r in raw_inputs]
                out = block._forward_eager(*nd_in)
        finally:
            st.graph_capturing = was_capturing
            for p, orig in zip(params, originals):
                p._data._set_data(orig)
        outs = out if isinstance(out, (list, tuple)) else [out]
        aux = {id_to_idx[id(t._data)]: v for t, v in collector
               if id(t._data) in id_to_idx}
        return tuple(o._data for o in outs), aux

    raw_params = [p.data()._data for p in params]
    return fn, raw_params, names


class _aux_collect:
    """Install the aux-update collector (see ops/common + imperative.invoke)."""

    def __init__(self, collector):
        self._collector = collector
        self._old = None

    def __enter__(self):
        st = _common.state()
        self._old = getattr(st, "aux_collector", None)
        st.aux_collector = self._collector
        return self

    def __exit__(self, *exc):
        _common.state().aux_collector = self._old


def _indent(s, num_spaces):
    lines = s.split("\n")
    if len(lines) == 1:
        return s
    first = lines.pop(0)
    return first + "\n" + "\n".join(" " * num_spaces + line for line in lines)


class SymbolBlock(HybridBlock):
    """Wrap a Symbol as a Block (parity: gluon.SymbolBlock) — used to load
    Module-trained symbolic models into gluon code."""

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix="", params=params)
        from ..symbol import Symbol, Group
        if isinstance(outputs, (list, tuple)):
            outputs = Group(outputs)
        if not isinstance(inputs, (list, tuple)):
            inputs = [inputs]
        self._out_symbol = outputs
        self._in_names = [i.name if isinstance(i, Symbol) else i
                          for i in inputs]
        arg_names = outputs.list_arguments()
        aux_names = set(outputs.list_auxiliary_states())
        for name in arg_names + sorted(aux_names):
            if name not in self._in_names:
                self._params.get(name, allow_deferred_init=True,
                                 grad_req="null" if name in aux_names
                                 else "write")
        from ..executor import _GraphProgram
        self._prog = _GraphProgram(outputs)

    def forward(self, *args):
        raw_args = {}
        for name, arr in zip(self._in_names, args):
            raw_args[name] = arr._data
        shapes = {n: a.shape for n, a in zip(self._in_names, args)}
        arg_shapes, _, aux_shapes = self._out_symbol.infer_shape_partial(**shapes)
        all_names = self._out_symbol.list_arguments()
        for n, s in zip(all_names, arg_shapes):
            if n in self._params._params and s is not None:
                p = self._params._params[n]
                if p.shape is None or 0 in (p.shape or (0,)):
                    p._update_shape(s)
        aux_names = self._out_symbol.list_auxiliary_states()
        for n, s in zip(aux_names, aux_shapes):
            if n in self._params._params and s is not None:
                p = self._params._params[n]
                if p.shape is None or 0 in (p.shape or (0,)):
                    p._update_shape(s)
        arg_dict = dict(raw_args)
        aux_dict = {}
        for n, p in self._params._params.items():
            if n in aux_names:
                aux_dict[n] = p.data()._data
            elif n not in arg_dict:
                arg_dict[n] = p.data()._data
        outs, aux_up = self._prog.eval_graph(
            arg_dict, aux_dict, _take_rng_key(), autograd.is_training())
        out_nds = [_wrap(o) for o in outs]
        return out_nds[0] if len(out_nds) == 1 else out_nds

    def hybrid_forward(self, F, *args, **kwargs):
        raise MXNetError("SymbolBlock uses its stored symbol")
