"""Convolutional recurrent cells for Gluon.

Parity: reference ``python/mxnet/gluon/contrib/rnn/conv_rnn_cell.py`` —
the 1/2/3-D Conv{RNN,LSTM,GRU}Cell families. The recurrence replaces the
dense i2h/h2h projections with convolutions over the spatial dims, so
states are feature maps ``(batch, channels, *spatial)``. The h2h padding
is derived from its kernel/dilation so the state's spatial shape is
preserved across steps (the reference's requirement for a well-formed
recurrence). TPU note: each step's convs lower straight onto the MXU;
``unroll`` keeps the whole sequence in one traced program.
"""
from ...rnn import HybridRecurrentCell
from ...rnn.rnn_cell import _maybe_init

__all__ = ["Conv1DRNNCell", "Conv2DRNNCell", "Conv3DRNNCell",
           "Conv1DLSTMCell", "Conv2DLSTMCell", "Conv3DLSTMCell",
           "Conv1DGRUCell", "Conv2DGRUCell", "Conv3DGRUCell"]


def _tuple(v, ndim, what):
    if isinstance(v, int):
        return (v,) * ndim
    v = tuple(v)
    if len(v) != ndim:
        raise ValueError("%s must have %d elements, got %r"
                         % (what, ndim, v))
    return v


def _conv_out(size, kernel, pad, dilate):
    return tuple(s + 2 * p - d * (k - 1)
                 for s, k, p, d in zip(size, kernel, pad, dilate))


class _BaseConvRNNCell(HybridRecurrentCell):
    """Shared machinery: gate convs over input and state."""

    def __init__(self, input_shape, hidden_channels, i2h_kernel, h2h_kernel,
                 i2h_pad, i2h_dilate, h2h_dilate, i2h_weight_initializer,
                 h2h_weight_initializer, i2h_bias_initializer,
                 h2h_bias_initializer, dims, conv_layout, activation,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_channels = hidden_channels
        self._input_shape = tuple(input_shape)
        if len(self._input_shape) != dims + 1:
            raise ValueError(
                "input_shape must have %d elements (channels + %d spatial"
                " dims), got %r" % (dims + 1, dims, self._input_shape))
        self._conv_layout = conv_layout
        self._channel_axis = conv_layout.find("C")
        self._activation = activation
        self._dims = dims
        self._i2h_kernel = _tuple(i2h_kernel, dims, "i2h_kernel")
        self._h2h_kernel = _tuple(h2h_kernel, dims, "h2h_kernel")
        for k in self._h2h_kernel:
            if k % 2 == 0:
                raise ValueError(
                    "h2h_kernel must be odd so the state's spatial shape "
                    "is preserved; got %r" % (self._h2h_kernel,))
        self._i2h_pad = _tuple(i2h_pad, dims, "i2h_pad")
        self._i2h_dilate = _tuple(i2h_dilate, dims, "i2h_dilate")
        self._h2h_dilate = _tuple(h2h_dilate, dims, "h2h_dilate")
        # SAME padding for the recurrent conv
        self._h2h_pad = tuple(d * (k - 1) // 2 for d, k in
                              zip(self._h2h_dilate, self._h2h_kernel))
        if self._channel_axis == 1:          # NC[spatial]
            in_ch = self._input_shape[0]
            spatial = self._input_shape[1:]
        else:                                # N[spatial]C (channels-last)
            in_ch = self._input_shape[-1]
            spatial = self._input_shape[:-1]
        out_spatial = _conv_out(spatial, self._i2h_kernel, self._i2h_pad,
                                self._i2h_dilate)
        if self._channel_axis == 1:
            self._state_shape = (hidden_channels,) + out_spatial
        else:
            self._state_shape = out_spatial + (hidden_channels,)
        ng = self._num_gates
        if self._channel_axis == 1:
            i2h_wshape = (ng * hidden_channels, in_ch) + self._i2h_kernel
            h2h_wshape = (ng * hidden_channels,
                          hidden_channels) + self._h2h_kernel
        else:   # channels-last weight layout (ops/nn.py:160)
            i2h_wshape = (ng * hidden_channels,) + self._i2h_kernel \
                + (in_ch,)
            h2h_wshape = (ng * hidden_channels,) + self._h2h_kernel \
                + (hidden_channels,)
        with self.name_scope():
            self.i2h_weight = self.params.get(
                "i2h_weight", shape=i2h_wshape,
                init=i2h_weight_initializer, allow_deferred_init=True)
            self.h2h_weight = self.params.get(
                "h2h_weight", shape=h2h_wshape,
                init=h2h_weight_initializer)
            self.i2h_bias = self.params.get(
                "i2h_bias", shape=(ng * hidden_channels,),
                init=_maybe_init(i2h_bias_initializer))
            self.h2h_bias = self.params.get(
                "h2h_bias", shape=(ng * hidden_channels,),
                init=_maybe_init(h2h_bias_initializer))

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size,) + self._state_shape,
                 "__layout__": self._conv_layout}]

    def _conv_gates(self, F, inputs, state, i2h_weight, h2h_weight,
                    i2h_bias, h2h_bias):
        ng = self._num_gates
        layout = self._conv_layout if self._channel_axis != 1 else None
        i2h = F.Convolution(inputs, i2h_weight, i2h_bias,
                            kernel=self._i2h_kernel, pad=self._i2h_pad,
                            dilate=self._i2h_dilate, layout=layout,
                            num_filter=ng * self._hidden_channels)
        h2h = F.Convolution(state, h2h_weight, h2h_bias,
                            kernel=self._h2h_kernel, pad=self._h2h_pad,
                            dilate=self._h2h_dilate, layout=layout,
                            num_filter=ng * self._hidden_channels)
        return i2h, h2h

    def _split_gates(self, F, gates, num):
        return F.SliceChannel(gates, num_outputs=num,
                              axis=self._channel_axis)

    def _act(self, F, x):
        if self._activation in ("tanh", "relu", "sigmoid", "softsign"):
            return F.Activation(x, act_type=self._activation)
        return getattr(F, self._activation)(x)


class _ConvRNNCell(_BaseConvRNNCell):
    _num_gates = 1

    def _alias(self):
        return "conv_rnn"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._conv_gates(F, inputs, states[0], i2h_weight,
                                    h2h_weight, i2h_bias, h2h_bias)
        out = self._act(F, i2h + h2h)
        return out, [out]


class _ConvLSTMCell(_BaseConvRNNCell):
    _num_gates = 4    # i, f, c, o — the reference/cudnn gate order

    def _alias(self):
        return "conv_lstm"

    def state_info(self, batch_size=0):
        info = super().state_info(batch_size)
        return info + [dict(info[0])]

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._conv_gates(F, inputs, states[0], i2h_weight,
                                    h2h_weight, i2h_bias, h2h_bias)
        gates = i2h + h2h
        sl = self._split_gates(F, gates, 4)
        in_gate = F.sigmoid(sl[0])
        forget_gate = F.sigmoid(sl[1])
        in_transform = self._act(F, sl[2])
        out_gate = F.sigmoid(sl[3])
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * self._act(F, next_c)
        return next_h, [next_h, next_c]


class _ConvGRUCell(_BaseConvRNNCell):
    _num_gates = 3    # r, z, n

    def _alias(self):
        return "conv_gru"

    def hybrid_forward(self, F, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prev = states[0]
        i2h, h2h = self._conv_gates(F, inputs, prev, i2h_weight,
                                    h2h_weight, i2h_bias, h2h_bias)
        i2h_s = self._split_gates(F, i2h, 3)
        h2h_s = self._split_gates(F, h2h, 3)
        reset = F.sigmoid(i2h_s[0] + h2h_s[0])
        update = F.sigmoid(i2h_s[1] + h2h_s[1])
        cand = self._act(F, i2h_s[2] + reset * h2h_s[2])
        next_h = (1 - update) * cand + update * prev
        return next_h, [next_h]


def _make(cell_base, dims, alias_doc):
    """Build the public N-D class over a gate family base."""

    class Cell(cell_base):
        __doc__ = alias_doc

        def __init__(self, input_shape, hidden_channels, i2h_kernel,
                     h2h_kernel, i2h_pad=0, i2h_dilate=1, h2h_dilate=1,
                     i2h_weight_initializer=None,
                     h2h_weight_initializer=None,
                     i2h_bias_initializer="zeros",
                     h2h_bias_initializer="zeros",
                     conv_layout=None, activation="tanh",
                     prefix=None, params=None):
            layouts = {1: "NCW", 2: "NCHW", 3: "NCDHW"}
            super().__init__(
                input_shape=input_shape,
                hidden_channels=hidden_channels,
                i2h_kernel=i2h_kernel, h2h_kernel=h2h_kernel,
                i2h_pad=i2h_pad, i2h_dilate=i2h_dilate,
                h2h_dilate=h2h_dilate,
                i2h_weight_initializer=i2h_weight_initializer,
                h2h_weight_initializer=h2h_weight_initializer,
                i2h_bias_initializer=i2h_bias_initializer,
                h2h_bias_initializer=h2h_bias_initializer,
                dims=dims, conv_layout=conv_layout or layouts[dims],
                activation=activation, prefix=prefix, params=params)

    return Cell


_DOC = ("(parity: gluon.contrib.rnn.%s — convolutional %s recurrence "
        "over %d spatial dim%s)")

Conv1DRNNCell = _make(_ConvRNNCell, 1,
                      _DOC % ("Conv1DRNNCell", "RNN", 1, ""))
Conv2DRNNCell = _make(_ConvRNNCell, 2,
                      _DOC % ("Conv2DRNNCell", "RNN", 2, "s"))
Conv3DRNNCell = _make(_ConvRNNCell, 3,
                      _DOC % ("Conv3DRNNCell", "RNN", 3, "s"))
Conv1DLSTMCell = _make(_ConvLSTMCell, 1,
                       _DOC % ("Conv1DLSTMCell", "LSTM", 1, ""))
Conv2DLSTMCell = _make(_ConvLSTMCell, 2,
                       _DOC % ("Conv2DLSTMCell", "LSTM", 2, "s"))
Conv3DLSTMCell = _make(_ConvLSTMCell, 3,
                       _DOC % ("Conv3DLSTMCell", "LSTM", 3, "s"))
Conv1DGRUCell = _make(_ConvGRUCell, 1,
                      _DOC % ("Conv1DGRUCell", "GRU", 1, ""))
Conv2DGRUCell = _make(_ConvGRUCell, 2,
                      _DOC % ("Conv2DGRUCell", "GRU", 2, "s"))
Conv3DGRUCell = _make(_ConvGRUCell, 3,
                      _DOC % ("Conv3DGRUCell", "GRU", 3, "s"))

for _name in __all__:
    globals()[_name].__name__ = _name
    globals()[_name].__qualname__ = _name
