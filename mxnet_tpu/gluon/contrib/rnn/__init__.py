"""Contrib recurrent building blocks (parity: gluon/contrib/rnn/)."""
from .conv_rnn_cell import *   # noqa: F401,F403
from .rnn_cell import *        # noqa: F401,F403
from .conv_rnn_cell import __all__ as _conv_all
from .rnn_cell import __all__ as _cell_all

__all__ = list(_conv_all) + list(_cell_all)
