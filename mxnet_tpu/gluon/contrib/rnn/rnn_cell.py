"""Contrib recurrent cells.

Parity: reference ``python/mxnet/gluon/contrib/rnn/rnn_cell.py`` —
``VariationalDropoutCell`` (Gal & Ghahramani variational dropout: ONE
mask per sequence for inputs / states / outputs, resampled only on
``reset()``).
"""
from ...rnn import ModifierCell

__all__ = ["VariationalDropoutCell"]


class VariationalDropoutCell(ModifierCell):
    """Same dropout mask across every time step (unlike DropoutCell's
    fresh per-step masks); masks for inputs/states/outputs are
    independent. Masks live until ``reset()`` — manual stepping must
    reset between sequences, exactly as the reference documents."""

    def __init__(self, base_cell, drop_inputs=0.0, drop_states=0.0,
                 drop_outputs=0.0):
        super().__init__(base_cell)
        self.drop_inputs = drop_inputs
        self.drop_states = drop_states
        self.drop_outputs = drop_outputs
        self.drop_inputs_mask = None
        self.drop_states_mask = None
        self.drop_outputs_mask = None

    def _alias(self):
        return "vardrop"

    def reset(self):
        super().reset()
        self.drop_inputs_mask = None
        self.drop_states_mask = None
        self.drop_outputs_mask = None

    def __call__(self, inputs, states):
        from .... import ndarray as F
        from .... import autograd
        if autograd.is_training():
            if self.drop_inputs:
                if self.drop_inputs_mask is None:
                    self.drop_inputs_mask = F.Dropout(
                        F.ones_like(inputs), p=self.drop_inputs)
                inputs = inputs * self.drop_inputs_mask
            if self.drop_states:
                if self.drop_states_mask is None:
                    self.drop_states_mask = F.Dropout(
                        F.ones_like(states[0]), p=self.drop_states)
                states = [states[0] * self.drop_states_mask] \
                    + list(states[1:])
        output, new_states = self.base_cell(inputs, states)
        if autograd.is_training() and self.drop_outputs:
            if self.drop_outputs_mask is None:
                self.drop_outputs_mask = F.Dropout(
                    F.ones_like(output), p=self.drop_outputs)
            output = output * self.drop_outputs_mask
        return output, new_states
