"""Gluon contrib namespace (parity: python/mxnet/gluon/contrib/)."""
from . import rnn
