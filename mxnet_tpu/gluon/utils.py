"""Gluon utilities (parity: python/mxnet/gluon/utils.py)."""
from __future__ import annotations

import math

from ..base import MXNetError
from ..ndarray.ndarray import NDArray

__all__ = ["split_data", "split_and_load", "clip_global_norm", "check_sha1",
           "download"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """(parity: utils.split_data)"""
    size = data.shape[batch_axis]
    if size < num_slice:
        raise MXNetError("batch size %d < num_slice %d" % (size, num_slice))
    if even_split and size % num_slice != 0:
        raise MXNetError("uneven split of %d into %d" % (size, num_slice))
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        begin = i * step
        end = (i + 1) * step if i < num_slice - 1 else size
        slices.append(data.slice_axis(batch_axis, begin, end))
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """(parity: utils.split_and_load). On a mesh-sharded program the split
    is logical; arrays stay whole and XLA shards them."""
    from ..ndarray import array
    if not isinstance(data, NDArray):
        data = array(data)
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm):
    """(parity: utils.clip_global_norm)"""
    if not arrays:
        raise MXNetError("arrays must be non-empty")
    total = 0.0
    for arr in arrays:
        n = arr.norm().asscalar()
        total += float(n) ** 2
    total = math.sqrt(total)
    if total > max_norm:
        scale = max_norm / (total + 1e-8)
        for arr in arrays:
            arr *= scale
    return total


def check_sha1(filename, sha1_hash):
    import hashlib
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None):
    """Gated: this build runs zero-egress; point `path` at a local file
    (parity surface for code that calls gluon.utils.download)."""
    import os
    if path is not None and os.path.exists(path) and not overwrite:
        return path
    raise MXNetError("download is unavailable in the zero-egress TPU build; "
                     "place the file at the target path manually")
