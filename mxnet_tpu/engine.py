"""Engine: host-side dependency scheduler + engine knobs.

Parity: reference ``python/mxnet/engine.py`` (set_bulk_size / bulk) plus a
Python face for the native dependency engine (src/engine.cc — the
TPU-native re-design of ``src/engine/threaded_engine*.cc``).

Division of labour on TPU:

* **Device ops** are scheduled by PJRT/XLA — jax dispatches
  asynchronously in program order, so the reference's per-device engine
  worker threads have no equivalent to build; ``mx.nd.waitall`` is the
  WaitForAll of that implicit engine.
* **Host ops** (RecordIO prefetch, augmentation, async checkpoint
  writes) still need real dataflow scheduling — that is this engine:
  push callables with read/write variable sets; per-variable versioned
  queues grant concurrent readers / exclusive writers in push order,
  exactly the reference's ThreadedVar discipline
  (threaded_engine.h:66-217).

``MXNET_ENGINE_TYPE=NaiveEngine`` runs pushed work synchronously in the
caller (the reference's prescribed debugging mode,
threaded_engine.h:355-368); ``MXNET_CPU_WORKER_NTHREADS`` sizes the pool.
"""
from __future__ import annotations

import ctypes
import os
import threading

from .base import MXNetError, get_env

__all__ = ["Engine", "get", "set_bulk_size", "bulk", "NaiveEngine"]

_lib_lock = threading.Lock()
_LIB = None
_TRIED = False


def _lib():
    global _LIB, _TRIED
    with _lib_lock:
        if _TRIED:
            return _LIB
        _TRIED = True
        path = os.path.join(os.path.dirname(__file__), "_lib",
                            "libmxtpu_engine.so")
        if not os.path.exists(path):
            return None
        try:
            L = ctypes.CDLL(path)
        except OSError:
            return None
        L.eng_create.restype = ctypes.c_void_p
        L.eng_create.argtypes = [ctypes.c_int, ctypes.c_int]
        L.eng_destroy.argtypes = [ctypes.c_void_p]
        L.eng_new_var.restype = ctypes.c_int64
        L.eng_new_var.argtypes = [ctypes.c_void_p]
        L.eng_delete_var.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        L.eng_push.argtypes = [
            ctypes.c_void_p, _CB, ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int, ctypes.c_int]
        L.eng_wait_for_var.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        L.eng_wait_all.argtypes = [ctypes.c_void_p]
        _LIB = L
        return _LIB


_CB = ctypes.CFUNCTYPE(None, ctypes.c_void_p)


class Var:
    """An engine variable — names a unit of mutable host state."""

    __slots__ = ("id", "_engine")

    def __init__(self, vid, engine):
        self.id = vid
        self._engine = engine


class Engine:
    """Native threaded dependency engine over host worker threads.

    ``push(fn, const_vars=[...], mutable_vars=[...])`` schedules ``fn``
    once every read dependency's prior writers and every write
    dependency's prior accessors have completed. Falls back to a pure-
    Python synchronous engine when the native library isn't built.
    """

    def __init__(self, num_workers=None, naive=None):
        if num_workers is None:
            num_workers = get_env("MXNET_CPU_WORKER_NTHREADS",
                                  os.cpu_count() or 4, int)
        if naive is None:
            naive = get_env("MXNET_ENGINE_TYPE", "") == "NaiveEngine"
        self._naive = bool(naive)
        self._L = _lib()
        self._pending = {}          # token -> python fn, until it runs
        self._pending_lock = threading.Lock()
        self._next_token = 1        # 0 would arrive as NULL/None in C
        self._h = None
        self._py_var = 0
        # ONE persistent CFUNCTYPE per engine; per-op fns are plain
        # Python objects looked up by the token smuggled through the
        # C `void* arg`. This sidesteps the closure-lifetime hazard of
        # freeing a per-op CFUNCTYPE while C is still returning through
        # its libffi trampoline.
        self._cb = _CB(self._dispatch)
        if self._L is not None:
            self._h = ctypes.c_void_p(
                self._L.eng_create(int(num_workers), int(self._naive)))

    # -- vars -------------------------------------------------------------
    def new_var(self):
        if self._h:
            return Var(self._L.eng_new_var(self._h), self)
        self._py_var += 1
        return Var(self._py_var, self)

    def delete_var(self, var):
        if self._h:
            self._L.eng_delete_var(self._h, var.id)

    # -- push -------------------------------------------------------------
    def push(self, fn, const_vars=(), mutable_vars=(), priority=0):
        """Schedule ``fn()`` with the given read/write sets.

        Raises if the sets overlap (reference CheckDuplicate,
        threaded_engine.h:409 — overlapping const/mutable vars would
        self-deadlock the grant queues).
        """
        cids = [v.id for v in const_vars]
        mids = [v.id for v in mutable_vars]
        if set(cids) & set(mids):
            raise MXNetError("const_vars and mutable_vars overlap")
        if len(set(mids)) != len(mids):
            raise MXNetError("duplicate mutable vars")
        if self._h is None:
            fn()  # pure-python fallback: synchronous
            return
        with self._pending_lock:
            token = self._next_token
            self._next_token += 1
            self._pending[token] = fn
        c_arr = (ctypes.c_int64 * max(len(cids), 1))(*(cids or [0]))
        m_arr = (ctypes.c_int64 * max(len(mids), 1))(*(mids or [0]))
        self._L.eng_push(self._h, self._cb, ctypes.c_void_p(token), c_arr,
                         len(cids), m_arr, len(mids), int(priority))

    def _dispatch(self, arg):
        # runs on a native worker thread (ctypes acquires the GIL)
        token = int(arg) if arg else 0
        with self._pending_lock:
            fn = self._pending.pop(token, None)
        if fn is None:
            return
        try:
            fn()
        except Exception:  # never let an exception cross into C
            import traceback
            traceback.print_exc()

    def wait_for_var(self, var):
        if self._h:
            self._L.eng_wait_for_var(self._h, var.id)

    def wait_all(self):
        if self._h:
            self._L.eng_wait_all(self._h)

    def __del__(self):
        h, self._h = self._h, None
        if h and self._L is not None:
            try:
                self._L.eng_destroy(h)
            except Exception:
                pass


def NaiveEngine():
    """Synchronous engine (parity: MXNET_ENGINE_TYPE=NaiveEngine)."""
    return Engine(naive=True)


_default = None
_default_lock = threading.Lock()


def get():
    """The process-wide engine singleton (parity: Engine::Get)."""
    global _default
    with _default_lock:
        if _default is None:
            _default = Engine()
        return _default


# ---------------------------------------------------------------------------
# Bulk-execution knobs (parity: mx.engine.set_bulk_size / bulk).
# On TPU "bulking" is jit scope: ops inside one jitted function compile
# into ONE XLA program, which is a strictly stronger form of the
# reference's engine-op bundling. The knob is kept for API parity and
# read by the imperative layer as a hint only.
# ---------------------------------------------------------------------------

_bulk_size = 15  # reference default MXNET_EXEC_BULK_EXEC_MAX_NODE_TRAIN-ish


def set_bulk_size(size):
    """Set size limit on bulk execution; returns the previous size."""
    global _bulk_size
    prev, _bulk_size = _bulk_size, int(size)
    return prev


class _BulkScope:
    def __init__(self, size):
        self._size = size
        self._old_size = None

    def __enter__(self):
        self._old_size = set_bulk_size(self._size)
        return self

    def __exit__(self, ptype, value, trace):
        set_bulk_size(self._old_size)


def bulk(size):
    """Scope for bundling many small ops (see module docstring)."""
    return _BulkScope(size)
