"""RecordIO file format: pack/unpack + readers/writers.

Parity: reference ``python/mxnet/recordio.py`` (MXRecordIO,
MXIndexedRecordIO, IRHeader, pack/unpack) over dmlc-core's RecordIO.
Binary layout matches the dmlc format: per record a little-endian uint32
magic (0xced7230a), a uint32 whose upper 3 bits are the continue-flag and
lower 29 bits the length, the payload, then padding to 4-byte alignment —
so files packed by this module are structurally the reference's format.
A C++ reader (src/recordio.cc) accelerates bulk scans when built.
"""
from __future__ import annotations

import ctypes
import os
import struct
from collections import namedtuple

import numpy as np

from .base import MXNetError

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img"]

_MAGIC = 0xced7230a
_LMASK = (1 << 29) - 1

IRHeader = namedtuple("IRHeader", ["flag", "label", "id", "id2"])
_IR_FORMAT = "IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


class MXRecordIO:
    """Sequential RecordIO reader/writer (parity: recordio.MXRecordIO)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.handle = None
        self.open()

    def open(self):
        from .filesystem import open_uri
        if self.flag == "w":
            self.handle = open_uri(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.handle = open_uri(self.uri, "rb")
            self.writable = False
        else:
            raise MXNetError("invalid flag %r" % self.flag)
        self.pid = os.getpid()

    def close(self):
        if self.handle is not None:
            self.handle.close()
            self.handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def __getstate__(self):
        d = dict(self.__dict__)
        d["handle"] = None
        return d

    def __setstate__(self, d):
        self.__dict__.update(d)
        self.open()

    def _check_pid(self):
        # fork-safety (parity: reference re-opens in child processes)
        if self.pid != os.getpid():
            self.open()

    def write(self, buf):
        assert self.writable
        self._check_pid()
        length = len(buf)
        self.handle.write(struct.pack("<II", _MAGIC, length & _LMASK))
        self.handle.write(buf)
        pad = (4 - length % 4) % 4
        if pad:
            self.handle.write(b"\x00" * pad)

    def read(self):
        assert not self.writable
        self._check_pid()
        hdr = self.handle.read(8)
        if len(hdr) < 8:
            return None
        magic, lrec = struct.unpack("<II", hdr)
        if magic != _MAGIC:
            raise MXNetError("invalid RecordIO magic at offset %d"
                             % (self.handle.tell() - 8))
        length = lrec & _LMASK
        buf = self.handle.read(length)
        pad = (4 - length % 4) % 4
        if pad:
            self.handle.read(pad)
        return buf

    def reset(self):
        self.close()
        self.open()

    def tell(self):
        return self.handle.tell()

    def seek(self, pos):
        self.handle.seek(pos)


class MXIndexedRecordIO(MXRecordIO):
    """Random-access RecordIO with a .idx sidecar
    (parity: recordio.MXIndexedRecordIO)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        self.fidx = None
        super().__init__(uri, flag)

    def open(self):
        from .filesystem import open_uri
        super().open()
        self.idx = {}
        self.keys = []
        if self.flag == "r":
            try:
                f = open_uri(self.idx_path, "r")
            except FileNotFoundError:
                f = None
            if f is not None:
                with f:
                    for line in f:
                        parts = line.strip().split("\t")
                        if len(parts) != 2:
                            continue
                        key = self.key_type(parts[0])
                        self.idx[key] = int(parts[1])
                        self.keys.append(key)
            self.fidx = None
        elif self.flag == "w":
            self.fidx = open_uri(self.idx_path, "w")

    def close(self):
        if self.fidx is not None:
            self.fidx.close()
            self.fidx = None
        super().close()

    def read_idx(self, idx):
        self.seek(self.idx[idx])
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.fidx.write("%s\t%d\n" % (str(key), pos))
        self.idx[key] = pos
        self.keys.append(key)


def pack(header, s):
    """Pack an IRHeader + payload (parity: recordio.pack). A list/array
    label becomes flag=len(label) with the float32 labels prepended to
    the payload — the multi-label wire format unpack expects."""
    header = IRHeader(*header)
    label = header.label
    if isinstance(label, (list, tuple, np.ndarray)):
        labels = np.asarray(label, np.float32)
        header = header._replace(flag=labels.size, label=0.0)
        s = labels.tobytes() + s
    return struct.pack(_IR_FORMAT, header.flag, header.label, header.id,
                       header.id2) + s


def unpack(s):
    """(parity: recordio.unpack)"""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    payload = s[_IR_SIZE:]
    if header.flag > 0:
        # multi-label: flag floats follow the header
        label = np.frombuffer(payload, np.float32, header.flag)
        header = header._replace(label=label)
        payload = payload[header.flag * 4:]
    return header, payload


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Pack an image array as raw uint8 CHW bytes. The reference uses
    OpenCV JPEG encode (tools/im2rec); this build stores raw tensors —
    HBM-bound training prefers pre-decoded records anyway."""
    img = np.ascontiguousarray(np.asarray(img, np.uint8))
    return pack(header, img.tobytes())


def unpack_img(s, iscolor=-1):
    header, payload = unpack(s)
    arr = np.frombuffer(payload, np.uint8)
    return header, arr
