"""Class registries keyed by base class.

Parity: reference ``python/mxnet/registry.py`` (backed there by
``MXNetCallbackList`` in the C API; here plain Python — no ABI needed).
Used by optimizer/metric/initializer to implement ``@register``,
``@alias`` and ``create(name_or_instance, **kwargs)``; ``create`` also
accepts the reference's JSON-encoded ``[name, kwargs]`` strings so
serialized optimizer configs (kvstore set_optimizer) round-trip.
"""
from __future__ import annotations

import json
import logging

from .base import MXNetError, _KIND_REGISTRIES


def _registry_for(base_class, nickname):
    # shared with base.registry_create(nickname): optimizer/metric/
    # initializer built-ins registered through those kind registries are
    # visible here, and vice versa
    return _KIND_REGISTRIES.setdefault(nickname, {})


def get_register_func(base_class, nickname):
    """Make a ``register`` decorator for subclasses of ``base_class``."""
    registry = _registry_for(base_class, nickname)

    def register(klass, name=None):
        assert issubclass(klass, base_class), \
            "Can only register subclass of %s" % base_class.__name__
        if name is None:
            name = klass.__name__
        name = name.lower()
        if name in registry and registry[name] is not klass:
            logging.warning(
                "\033[91mNew %s %s.%s registered with name %s is overriding "
                "existing %s %s.%s\033[0m", nickname, klass.__module__,
                klass.__name__, name, nickname,
                registry[name].__module__, registry[name].__name__)
        registry[name] = klass
        return klass

    register.__doc__ = "Register %s to the %s factory" % (nickname, nickname)
    return register


def get_alias_func(base_class, nickname):
    """Make an ``alias`` decorator registering extra names."""
    register = get_register_func(base_class, nickname)

    def alias(*aliases):
        def reg(klass):
            for name in aliases:
                register(klass, name)
            return klass
        return reg

    return alias


def get_create_func(base_class, nickname):
    """Make a ``create`` factory for registered subclasses.

    Accepts a name string, a JSON ``[name, kwargs]`` string (the wire
    format kvstore uses to ship optimizers to servers), or an existing
    instance (returned as-is when no extra kwargs are given).
    """
    registry = _registry_for(base_class, nickname)

    def create(*args, **kwargs):
        if len(args):
            name = args[0]
            args = args[1:]
        else:
            name = kwargs.pop(nickname)
        if isinstance(name, base_class):
            assert not args and not kwargs, \
                "%s is already an instance. Additional arguments are invalid" \
                % nickname
            return name
        if isinstance(name, str) and name.startswith("["):
            assert not args and not kwargs
            name, kwargs = json.loads(name)
        name = name.lower()
        if name not in registry:
            raise MXNetError(
                "%s is not registered. Known %ss: %s"
                % (name, nickname, ", ".join(sorted(registry))))
        klass = registry[name]
        # the kind registry is shared by nickname (so built-ins registered
        # via base.registry_create stay visible); guard against a
        # same-nickname registry for an unrelated base handing back a
        # non-subclass
        if not issubclass(klass, base_class):
            raise MXNetError(
                "%s %r resolves to %s which is not a subclass of %s"
                % (nickname, name, klass.__name__, base_class.__name__))
        return klass(*args, **kwargs)

    create.__doc__ = "Create a %s instance from config" % nickname
    return create
