"""Storage manager facade.

Parity: reference ``include/mxnet/storage.h`` (``Storage::Get()->
Alloc/Free/DirectFree(Handle)``) + ``src/storage/`` (SURVEY.md §2.1
"Storage manager"). TPU-native split of responsibilities:

* **Device (HBM) memory** is owned by the PJRT allocator — XLA plans and
  pools device buffers itself, so the framework does not (and must not)
  run its own HBM free-list. This facade surfaces PJRT's per-device
  stats (``device_stats``) where the reference exposed pool counters.
* **Host staging memory** (IO batch assembly, h2d staging) IS framework-
  managed: a native size-bucketed pooled allocator (src/storage.cc, the
  ``GPUPooledStorageManager`` design applied to the host side) with a
  pure-numpy fallback when the library isn't built.

``alloc`` returns a ``Handle`` whose ``.array(shape, dtype)`` view is a
numpy array backed by the pooled buffer, so producers can fill it in
place and hand it to ``mx.nd.array`` for the device copy.
"""
from __future__ import annotations

import ctypes
import os
import threading

import numpy as np

from .base import MXNetError

__all__ = ["Handle", "Storage"]

_lib = None
_lib_tried = False
_lock = threading.Lock()


def _native():
    global _lib, _lib_tried
    with _lock:
        if _lib_tried:
            return _lib
        _lib_tried = True
        path = os.path.join(os.path.dirname(__file__), "_lib",
                            "libmxtpu_storage.so")
        if not os.path.exists(path):
            return None
        try:
            L = ctypes.CDLL(path)
        except OSError:
            return None
        L.sto_alloc.restype = ctypes.c_void_p
        L.sto_alloc.argtypes = [ctypes.c_size_t]
        L.sto_free.argtypes = [ctypes.c_void_p, ctypes.c_size_t]
        L.sto_direct_free.argtypes = [ctypes.c_void_p, ctypes.c_size_t]
        L.sto_stats.argtypes = [ctypes.POINTER(ctypes.c_size_t)] * 3
        L.sto_release_all.argtypes = []
        _lib = L
        return _lib


class Handle:
    """One allocation (parity: Storage::Handle — ptr, size, ctx)."""

    __slots__ = ("ptr", "size", "_np", "_freed")

    def __init__(self, ptr, size, np_fallback=None):
        self.ptr = ptr
        self.size = size
        self._np = np_fallback
        self._freed = False

    def array(self, shape, dtype=np.float32):
        """Numpy view over the buffer (fill in place, then ship to device)."""
        if self._freed:
            raise MXNetError("use-after-free of a storage handle")
        n = int(np.prod(shape)) * np.dtype(dtype).itemsize
        if n > self.size:
            raise MXNetError("view of %d bytes exceeds allocation of %d"
                             % (n, self.size))
        if self._np is not None:
            return self._np[:n].view(dtype).reshape(shape)
        buf = (ctypes.c_uint8 * n).from_address(self.ptr)
        return np.frombuffer(buf, dtype=dtype).reshape(shape)


class Storage:
    """Singleton facade (parity: Storage::Get())."""

    _instance = None

    @staticmethod
    def get():
        with _lock:
            if Storage._instance is None:
                Storage._instance = Storage()
        return Storage._instance

    def __init__(self):
        self._fallback_allocated = 0
        self._fallback_peak = 0

    @property
    def native(self):
        return _native() is not None

    def alloc(self, nbytes):
        """(parity: Storage::Alloc) pooled host buffer of >= nbytes."""
        L = _native()
        if L is None:
            arr = np.empty(nbytes, np.uint8)
            self._fallback_allocated += nbytes
            self._fallback_peak = max(self._fallback_peak,
                                      self._fallback_allocated)
            return Handle(arr.ctypes.data, nbytes, np_fallback=arr)
        ptr = L.sto_alloc(nbytes)
        if not ptr:
            raise MXNetError("host storage allocation of %d bytes failed"
                             % nbytes)
        return Handle(ptr, nbytes)

    def free(self, handle):
        """(parity: Storage::Free) return the buffer to the pool."""
        if handle._freed:
            return
        handle._freed = True
        if handle._np is not None:
            self._fallback_allocated -= handle.size
            handle._np = None
            return
        _native().sto_free(handle.ptr, handle.size)

    def direct_free(self, handle):
        """(parity: Storage::DirectFree) bypass the pool."""
        if handle._freed:
            return
        handle._freed = True
        if handle._np is not None:
            self._fallback_allocated -= handle.size
            handle._np = None
            return
        _native().sto_direct_free(handle.ptr, handle.size)

    def release_all(self):
        L = _native()
        if L is not None:
            L.sto_release_all()

    def stats(self):
        """Host-pool counters: allocated / pooled / peak bytes."""
        L = _native()
        if L is None:
            return {"allocated": self._fallback_allocated, "pooled": 0,
                    "peak": self._fallback_peak}
        a, p, k = (ctypes.c_size_t(), ctypes.c_size_t(), ctypes.c_size_t())
        L.sto_stats(ctypes.byref(a), ctypes.byref(p), ctypes.byref(k))
        return {"allocated": a.value, "pooled": p.value, "peak": k.value}

    @staticmethod
    def device_stats(device=None):
        """Per-device HBM stats from PJRT (parity: the reference's pool
        counters / MXNET_GPU_MEM_POOL_RESERVE visibility)."""
        import jax
        d = device or jax.devices()[0]
        try:
            return dict(d.memory_stats() or {})
        except (AttributeError, RuntimeError):
            return {}

    @staticmethod
    def ledger_report():
        """The live device-buffer ledger (telemetry's per-context
        alive/peak counters — the framework's view of device memory)
        RECONCILED against PJRT's own per-device stats where the
        platform exposes them (``bytes_in_use``): ``delta_bytes`` is
        allocator-held minus ledger-tracked, i.e. memory the framework
        does not account for (XLA temp arenas, donated-buffer slack,
        untracked raw jax arrays) — the first thing to read when an
        allocation fails unexpectedly."""
        from . import telemetry
        import jax
        led = telemetry.ledger()
        report = {"contexts": led, "devices": {},
                  "top_buffers": telemetry.ledger_top(8)}
        ledger_alive = sum(st["alive_bytes"] for st in led.values())
        pjrt_in_use = 0
        have_stats = False
        try:
            devices = jax.local_devices()
        except RuntimeError:
            devices = []
        for d in devices:
            stats = Storage.device_stats(d)
            if stats:
                report["devices"][str(d)] = stats
                if "bytes_in_use" in stats:
                    have_stats = True
                    pjrt_in_use += int(stats["bytes_in_use"])
        if have_stats:
            report["reconciliation"] = {
                "pjrt_bytes_in_use": pjrt_in_use,
                "ledger_alive_bytes": ledger_alive,
                "delta_bytes": pjrt_in_use - ledger_alive,
            }
        return report
