"""Flight recorder: metrics time-series sampler, OpenMetrics endpoint,
and crash postmortems (ISSUE 10).

No reference counterpart — the reference's observability ended at a
chrome trace you had to *ask* for. Everything PR 3/4 instruments is a
point-in-time snapshot: when a serving process dies mid-burst or a
training run diverges, the evidence evaporates with the process. This
module is the black box on top of ``telemetry.py``:

* a **metrics sampler** — a daemon thread snapshots counter DELTAS,
  serving queue depth, ledger bytes, the online MFU estimate and
  breaker/shed state into a bounded in-memory time-series ring every
  ``MXNET_METRICS_INTERVAL_MS`` (``sampler_start()``/``sampler_stop()``
  programmatically). ``series()`` reads the ring, ``series_dump()``
  exports it as JSONL — the per-phase timeline bench banks next to its
  endpoint snapshots;

* an **OpenMetrics endpoint** — ``metrics_http_start()`` (or
  ``MXNET_METRICS_PORT``) serves ``/metrics`` as Prometheus-scrapable
  text from a stdlib ``http.server`` thread. OFF by default and bound
  to loopback (127.0.0.1) only — the endpoint exposes counter names
  and program shapes, so exposing it beyond the host is an explicit
  operator decision (``MXNET_METRICS_HOST``);

* **crash postmortems** — ``postmortem(reason, exc=...)`` dumps one
  flight-record JSON (the last-N span ring with causal req/step ids,
  the discrete-event ring, counters, the recent time-series window,
  program cards, ledger top, fault-registry counts, live engine
  breaker/queue state) through ``checkpoint.atomic_write`` into
  ``MXNET_FLIGHT_DIR``. ``install()`` arms ``sys.excepthook`` /
  ``threading.excepthook`` (a dying coalescer thread writes its own
  black box) and an atexit series flush; the runtime triggers dumps
  explicitly on ``DeviceMemoryError``, ``DivergenceError``, serving
  breaker trips / terminal batch failures, and ``TrainingPreempted``.
  Everything is inert until a flight dir is configured — the hot paths
  pay nothing.

``tools/flight_view.py`` pretty-prints a dump (event timeline, top
counter deltas, slowest requests by wait/batch/d2h breakdown).
"""
from __future__ import annotations

import atexit
import collections
import json
import os
import sys
import threading
import time
import traceback
import weakref

from . import telemetry
from .checkpoint import atomic_write

__all__ = [
    "configure", "flight_dir", "install", "installed",
    "postmortem", "last_postmortem", "gather_peer_postmortems",
    "sampler_start", "sampler_stop", "sampler_running",
    "series", "series_window", "series_dump",
    "metrics_http_start", "metrics_http_stop", "openmetrics_text",
    "register_engine", "engine_states",
    "SERIES_RING_SIZE", "POSTMORTEM_SCHEMA",
]

ENV_DIR = "MXNET_FLIGHT_DIR"
ENV_INTERVAL = "MXNET_METRICS_INTERVAL_MS"
ENV_PORT = "MXNET_METRICS_PORT"
ENV_HOST = "MXNET_METRICS_HOST"

# time-series ring bound: at the 500 ms default interval this holds
# ~17 min of trajectory; a crash dump carries the most recent window
SERIES_RING_SIZE = 2048
DEFAULT_INTERVAL_MS = 500.0

POSTMORTEM_SCHEMA = "mxnet_tpu.flight/1"

# most recent samples a postmortem embeds (the full ring can be large;
# the dump wants the window AROUND the crash, not the whole session)
_POSTMORTEM_SERIES = 240
_POSTMORTEM_SPANS = 512
# per-reason dump throttle: a breaker flapping open/closed must not
# turn the flight dir into a disk-filling loop
_THROTTLE_S = 1.0

_lock = threading.Lock()
_dir = None                  # guarded by: _lock
_env_loaded = False          # guarded by: _lock
_installed = False           # guarded by: _lock
_prev_excepthook = None      # guarded by: _lock
_prev_threading_hook = None  # guarded by: _lock
_series = collections.deque(maxlen=SERIES_RING_SIZE)  # guarded by: _lock
_sampler_thread = None       # guarded by: _lock
_sampler_stop = None         # guarded by: _lock
_sampler_interval_s = None   # guarded by: _lock
_http_server = None          # guarded by: _lock
_http_thread = None          # guarded by: _lock
_engines = weakref.WeakSet()  # guarded by: _lock
_last_dump = {}              # guarded by: _lock
                             # reason -> monotonic instant of last dump
_last_path = None            # guarded by: _lock
_seq = 0                     # guarded by: _lock


# ---------------------------------------------------------------------------
# Configuration
# ---------------------------------------------------------------------------

def _load_env_locked():
    """Lazily adopt MXNET_FLIGHT_DIR. Caller holds _lock."""
    global _dir, _env_loaded
    if _env_loaded:
        return
    _env_loaded = True
    env = os.environ.get(ENV_DIR)
    if env and _dir is None:
        _dir = env


def configure(directory):
    """Set (or clear, with None) the postmortem directory and arm the
    process hooks. Explicit calls override ``MXNET_FLIGHT_DIR``."""
    global _dir, _env_loaded
    with _lock:
        _env_loaded = True
        _dir = None if directory is None else str(directory)
        armed = _dir is not None
    if armed:
        install()


def flight_dir():
    """The active postmortem directory, or None (recorder inert)."""
    with _lock:
        _load_env_locked()
        return _dir


# ---------------------------------------------------------------------------
# Live-engine registry (serving breaker/queue state for dumps/samples)
# ---------------------------------------------------------------------------

def register_engine(engine):
    """Track a live ``serving.InferenceEngine`` (weakly) so samples and
    postmortems can report its queue/breaker state. The engine calls
    this at construction; a collected engine drops out on its own."""
    with _lock:
        _engines.add(engine)


def engine_states():
    """[light overload-state dict per live engine] — each read under
    the engine's own lock via ``InferenceEngine.overload_state()``."""
    with _lock:
        engines = list(_engines)
    out = []
    for e in engines:
        try:
            out.append(e.overload_state())
        except Exception:   # a half-closed engine must not kill a dump
            pass
    return out


# ---------------------------------------------------------------------------
# Metrics sampler
# ---------------------------------------------------------------------------

def _build_sample(last, dt_s):
    """One time-series sample: counter deltas over the interval plus
    the derived gauges. Returns (sample, new_cumulative_baseline)."""
    cum = telemetry.counters()
    if any(cum.get(k, 0) < v for k, v in last.items()):
        # telemetry.reset() opened a new accounting window mid-interval:
        # deltas against the old baseline are meaningless
        deltas, reset = {}, True
    else:
        deltas = {k: v - last.get(k, 0) for k, v in cum.items()
                  if v != last.get(k, 0)}
        reset = False
    led = telemetry.ledger()
    online = telemetry.online()
    engines = engine_states()
    sample = {
        "ts": round(time.time(), 3),
        "dt_ms": round(dt_s * 1e3, 1),
        "rank": telemetry.process_identity()["rank"],
        "counters": deltas,
        "queue_depth": telemetry.serving_queue_depth(cum),
        "ledger_bytes": sum(st.get("alive_bytes", 0)
                            for st in led.values()),
        "mfu": online.get("mfu"),
        "model_flops_per_s": online.get("model_flops_per_s"),
        "serving": {
            "queued_rows": sum(e.get("queued_rows", 0) for e in engines),
            "breaker_open": any(e.get("breaker_open") for e in engines),
            "engines": len(engines),
            # slot occupancy across registered decode engines (0 for a
            # fleet of stateless engines — their overload_state carries
            # no active_slots key)
            "active_slots": sum(e.get("active_slots", 0)
                                for e in engines),
        },
    }
    try:
        from . import heartbeat as _hb
        gate = _hb.gate_stats()
    except Exception:
        gate = {}
    if gate:
        # per-channel gate-wait series (ISSUE 18): the straggler's
        # signature — one rank's step time flat while every peer's
        # gate_wait climbs — is only visible when the sampler folds
        # the gate stats into the SAME timeline as MFU/queue depth
        sample["gate"] = {
            ch: {"crossings": s["crossings"],
                 "wait_ms_total": round(s["wait_ms_total"], 3),
                 "last_wait_ms": round(s["last_wait_ms"], 3),
                 "last_rank": s["last_rank"],
                 "last_excess_ms": round(s["last_excess_ms"], 3)}
            for ch, s in gate.items()}
    if reset:
        sample["registry_reset"] = True
    return sample, cum


def _sampler_loop(stop, interval_s):
    last = telemetry.counters()
    last_t = time.monotonic()
    while not stop.wait(interval_s):
        now = time.monotonic()
        try:
            sample, last = _build_sample(last, now - last_t)
        except Exception:    # a torn read must not kill the sampler
            last_t = now
            continue
        last_t = now
        with _lock:
            _series.append(sample)


def sampler_start(interval_ms=None):
    """Start the daemon sampler thread (idempotent; a second call with
    a different interval restarts it). ``interval_ms`` defaults to
    ``MXNET_METRICS_INTERVAL_MS`` or 500; an interval <= 0 means
    DISABLED (returns None without starting — so an operator's
    ``MXNET_METRICS_INTERVAL_MS=0`` turns the sampler off instead of
    spinning it at the clamp floor). Returns the interval in ms."""
    global _sampler_thread, _sampler_stop, _sampler_interval_s
    if interval_ms is None:
        interval_ms = float(os.environ.get(ENV_INTERVAL,
                                           DEFAULT_INTERVAL_MS))
    if float(interval_ms) <= 0:
        return None
    interval_s = max(0.001, float(interval_ms) / 1e3)
    restart = False
    with _lock:
        if _sampler_thread is not None and _sampler_thread.is_alive():
            if _sampler_interval_s == interval_s:
                return interval_s * 1e3
            restart = True
    if restart:
        sampler_stop()
    with _lock:
        if _sampler_thread is not None and _sampler_thread.is_alive():
            return _sampler_interval_s * 1e3
        _sampler_stop = threading.Event()
        _sampler_interval_s = interval_s
        _sampler_thread = threading.Thread(
            target=_sampler_loop, args=(_sampler_stop, interval_s),
            name="mxtpu-flight-sampler", daemon=True)
        _sampler_thread.start()
    return interval_s * 1e3


def sampler_stop():
    """Stop the sampler thread (the ring keeps its samples)."""
    global _sampler_thread, _sampler_stop
    with _lock:
        thread, stop = _sampler_thread, _sampler_stop
        _sampler_thread = _sampler_stop = None
    if stop is not None:
        stop.set()
    if thread is not None and thread.is_alive():
        thread.join(timeout=5.0)


def sampler_running():
    with _lock:
        return _sampler_thread is not None and _sampler_thread.is_alive()


def sampler_interval_ms():
    """The running sampler's interval in ms, or None."""
    with _lock:
        if _sampler_thread is None or not _sampler_thread.is_alive():
            return None
        return _sampler_interval_s * 1e3


def series(n=None):
    """Copy of the time-series ring (oldest first); ``n`` keeps only
    the newest n samples."""
    with _lock:
        out = list(_series)
    if n is not None:
        out = out[-int(n):]
    return out


def series_window(n=_POSTMORTEM_SERIES):
    """The artifact-friendly tail of the ring: ``{"interval_ms", "n",
    "samples"}`` — what bench banks next to its snapshot block."""
    samples = series(n)
    return {"interval_ms": sampler_interval_ms(),
            "process": telemetry.process_identity(),
            "n": len(samples), "samples": samples}


def series_clear():
    """Drop every retained sample (a fresh measurement window)."""
    with _lock:
        _series.clear()


def series_dump(path=None, n=None):
    """The ring as JSONL text (one sample per line, oldest first).
    ``path`` additionally writes it atomically. Returns the text."""
    text = "".join(json.dumps(s, sort_keys=True) + "\n"
                   for s in series(n))
    if path:
        atomic_write(path, text)
    return text


# ---------------------------------------------------------------------------
# OpenMetrics endpoint
# ---------------------------------------------------------------------------

def _metric_name(name):
    """Counter name -> OpenMetrics-safe sample name."""
    out = []
    for ch in name:
        out.append(ch if ch.isalnum() else "_")
    return "mxnet_tpu_" + "".join(out)


def _escape_label(val):
    return str(val).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def openmetrics_text():
    """The registry as OpenMetrics/Prometheus exposition text: every
    telemetry counter as a ``counter`` family (``_total`` samples),
    plus the derived gauges (queue depth, per-context ledger bytes,
    online MFU, live-engine queued rows / breaker state). Every sample
    carries ``rank``/``host`` labels (ISSUE 18) so one Prometheus
    scraping a fleet can aggregate or slice per rank without relabel
    rules."""
    ident = telemetry.process_identity()
    who = {"rank": ident["rank"], "host": ident["host"]}

    def labels_tail(labels):
        return "{%s}" % ",".join(
            '%s="%s"' % (k, _escape_label(v))
            for k, v in sorted(labels.items()))

    cum = telemetry.counters()
    lines = []
    for name in sorted(cum):
        m = _metric_name(name)
        lines.append("# TYPE %s counter" % m)
        lines.append("%s_total%s %s" % (m, labels_tail(who), cum[name]))

    typed = set()

    def gauge(name, value, labels=None):
        if value is None:
            return
        # ONE metadata line per metric family: a labeled gauge emitted
        # per context (the ledger) must not repeat its '# TYPE' — the
        # OpenMetrics parser rejects duplicate family metadata
        if name not in typed:
            typed.add(name)
            lines.append("# TYPE %s gauge" % name)
        merged = dict(who)
        if labels:
            merged.update(labels)
        lines.append("%s%s %s" % (name, labels_tail(merged), value))

    gauge("mxnet_tpu_serving_queue_depth",
          telemetry.serving_queue_depth(cum))
    for ctx, st in sorted(telemetry.ledger().items()):
        gauge("mxnet_tpu_ledger_alive_bytes", st.get("alive_bytes", 0),
              {"ctx": ctx})
    online = telemetry.online()
    gauge("mxnet_tpu_online_mfu", online.get("mfu"))
    gauge("mxnet_tpu_online_model_flops_per_s",
          online.get("model_flops_per_s"))
    engines = engine_states()
    gauge("mxnet_tpu_serving_queued_rows",
          sum(e.get("queued_rows", 0) for e in engines))
    gauge("mxnet_tpu_serving_breaker_open",
          int(any(e.get("breaker_open") for e in engines)))
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def metrics_http_start(port=None, host=None):
    """Serve ``/metrics`` from a stdlib http.server daemon thread.
    OFF unless called (or ``MXNET_METRICS_PORT`` set > 0); binds
    LOOPBACK ONLY by default — the text exposes internal counter names
    and program shapes, so a wider bind (``host=``/
    ``MXNET_METRICS_HOST``) is an explicit operator decision. A
    PROGRAMMATIC ``port=0`` picks an ephemeral port (tests); the env
    knob treats 0 as OFF, matching the sampler's interval semantics.
    Returns the bound port."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
    if port is None:
        port = int(os.environ.get(ENV_PORT, "0") or "0")
    if host is None:
        host = os.environ.get(ENV_HOST, "127.0.0.1")

    class _Handler(BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path.split("?", 1)[0] not in ("/", "/metrics"):
                self.send_error(404)
                return
            body = openmetrics_text().encode()
            self.send_response(200)
            self.send_header(
                "Content-Type",
                "application/openmetrics-text; version=1.0.0; "
                "charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):   # scrapes must not spam stderr
            pass

    global _http_server, _http_thread
    with _lock:
        if _http_server is not None:
            return _http_server.server_address[1]
        _http_server = ThreadingHTTPServer((host, int(port)), _Handler)
        _http_server.daemon_threads = True
        _http_thread = threading.Thread(
            target=_http_server.serve_forever,
            name="mxtpu-flight-metrics", daemon=True)
        _http_thread.start()
        return _http_server.server_address[1]


def metrics_http_stop():
    global _http_server, _http_thread
    with _lock:
        server, thread = _http_server, _http_thread
        _http_server = _http_thread = None
    if server is not None:
        server.shutdown()
        server.server_close()
    if thread is not None and thread.is_alive():
        thread.join(timeout=5.0)


# ---------------------------------------------------------------------------
# Crash postmortems
# ---------------------------------------------------------------------------

def _exc_record(exc):
    if exc is None:
        return None
    rec = {
        "type": type(exc).__name__,
        "message": str(exc),
        "traceback": "".join(traceback.format_exception(
            type(exc), exc, exc.__traceback__))[-16384:],
    }
    site = getattr(exc, "site", None)
    if site is not None:            # faults.InjectedFault names its site
        rec["fault_site"] = site
    return rec


def _process_identity():
    """Which worker of a multi-process job wrote this dump (a pod-scale
    postmortem is read next to its peers' — "whose flight recorder is
    this" must not require correlating pids with launcher logs). The
    uniform block lives in telemetry (ISSUE 18) so snapshots, series
    windows, bench artifacts and dumps all agree on its shape."""
    return telemetry.process_identity()


def _build_record(reason, exc=None, extra=None):
    rec = {
        "schema": POSTMORTEM_SCHEMA,
        "reason": reason,
        "ts": round(time.time(), 6),
        "pid": os.getpid(),
        "process": _process_identity(),
        "argv": list(sys.argv),
        "python": sys.version.split()[0],
        "exception": _exc_record(exc),
        "extra": extra,
        "counters": telemetry.counters(),
        "events": telemetry.events(),
        "spans": telemetry.recent_spans(_POSTMORTEM_SPANS),
        "series": series(_POSTMORTEM_SERIES),
        "programs": telemetry.programs(),
        "online": telemetry.online(),
        "ledger": telemetry.ledger(),
        "ledger_top": telemetry.ledger_top(16),
        "engines": engine_states(),
    }
    try:
        from . import faults
        rec["faults"] = {"spec": faults.spec(), "counts": faults.counts()}
    except Exception:
        rec["faults"] = None
    return rec


def postmortem(reason, exc=None, extra=None, path=None, force=False):
    """Dump one flight-record JSON. ``reason`` names the trigger
    (``uncaught_exception``, ``device_memory_error``, ``divergence``,
    ``breaker_trip``, ``serving_dispatch_failure``,
    ``training_preempted``, ...); ``exc`` rides as a structured
    exception record (an ``InjectedFault``'s site is surfaced);
    ``extra`` carries trigger-specific facts — the serving path passes
    the dying batch's member ``req_ids``.

    Writes to ``path`` when given, else to the configured flight dir
    (``MXNET_FLIGHT_DIR`` / ``configure()``); with NEITHER, this is a
    no-op returning None — the triggers stay wired permanently and cost
    one check while the recorder is off. Dumps of one reason are
    throttled to one per second unless ``force=True`` (a flapping
    breaker must not fill the disk). Returns the written path, and
    never raises — a postmortem failing must not mask the crash being
    recorded."""
    global _seq, _last_path
    try:
        target = path
        throttled = False
        if target is None:
            d = flight_dir()
            if d is None:
                return None
            with _lock:
                now = time.monotonic()
                if not force and now - _last_dump.get(reason, -1e9) \
                        < _THROTTLE_S:
                    return None
                _seq += 1
                seq = _seq
            throttled = True
            # rank-disambiguated filename: a fleet shares ONE
            # MXNET_FLIGHT_DIR over NFS, where pids collide across
            # hosts — two ranks dumping the same reason must land as
            # two files, never clobber. (The per-reason throttle above
            # is in-process state, so it is rank-local by
            # construction — rank 0 dumping dead_worker never
            # suppresses rank 2's.)
            target = os.path.join(
                d, "postmortem-r%d-%d-%03d-%s.json" % (
                    telemetry.process_identity()["rank"], os.getpid(),
                    seq, _safe_reason(reason)))
        rec = _build_record(reason, exc=exc, extra=extra)
        atomic_write(target, json.dumps(rec, sort_keys=True,
                                        default=str))
        with _lock:
            _last_path = target
            if throttled:
                # stamp the throttle slot only AFTER a successful
                # write: a transient disk failure must not suppress
                # the next genuine trigger of the same reason
                _last_dump[reason] = time.monotonic()
        telemetry.counter_inc("flight.postmortem")
        telemetry.record_event("flight.postmortem", reason=reason,
                               path=target)
        return target
    except Exception as e:
        telemetry.counter_inc("flight.postmortem_fail")
        try:
            from . import log as _log
            _log.get_logger("mxnet_tpu.flight").warning(
                "flight: postmortem %r failed: %s", reason, e)
        except Exception:
            pass
        return None


def _safe_reason(reason):
    return "".join(ch if ch.isalnum() or ch in "-_" else "_"
                   for ch in str(reason))[:64] or "unknown"


def last_postmortem():
    """Path of the most recent dump this process wrote, or None."""
    with _lock:
        return _last_path


_PM_RANK_RE = None      # compiled lazily; module stays regex-free otherwise


def gather_peer_postmortems(directory=None, exclude_rank=None,
                            max_events=8):
    """Light summaries of OTHER ranks' newest postmortems in the shared
    flight dir — the survivor's ``dead_worker`` dump embeds these so
    the cluster view shows the victim's last seconds, not just the
    survivor's keyhole. One entry per rank (its newest dump by mtime):
    ``{"rank", "path", "reason", "ts", "exception", "events_tail"}``.
    Best-effort end to end: a corrupt or half-written peer dump is
    skipped, and nothing here ever raises — this runs inside elastic
    recovery, where a second failure must not mask the first."""
    global _PM_RANK_RE
    try:
        import re as _re
        if _PM_RANK_RE is None:
            _PM_RANK_RE = _re.compile(r"^postmortem-r(\d+)-.*\.json$")
        d = directory or flight_dir()
        if d is None:
            return []
        me = telemetry.process_identity()["rank"] \
            if exclude_rank is None else int(exclude_rank)
        newest = {}                 # rank -> (mtime, path)
        for name in os.listdir(d):
            m = _PM_RANK_RE.match(name)
            if not m:
                continue
            rank = int(m.group(1))
            if rank == me:
                continue
            path = os.path.join(d, name)
            try:
                mt = os.path.getmtime(path)
            except OSError:
                continue
            if rank not in newest or mt > newest[rank][0]:
                newest[rank] = (mt, path)
        out = []
        for rank in sorted(newest):
            _mt, path = newest[rank]
            try:
                with open(path) as f:
                    rec = json.load(f)
            except (OSError, ValueError):
                continue
            exc = rec.get("exception")
            out.append({
                "rank": rank,
                "path": path,
                "reason": rec.get("reason"),
                "ts": rec.get("ts"),
                "exception": {"type": exc.get("type"),
                              "message": exc.get("message")}
                if isinstance(exc, dict) else None,
                "events_tail": (rec.get("events") or [])[-max_events:],
            })
        return out
    except Exception:
        return []


# ---------------------------------------------------------------------------
# Process hooks
# ---------------------------------------------------------------------------

def _excepthook(exc_type, exc, tb):
    if not (exc_type is KeyboardInterrupt or exc_type is SystemExit):
        if exc is not None and exc.__traceback__ is None:
            exc.__traceback__ = tb
        postmortem("uncaught_exception", exc=exc, force=True)
    prev = _prev_excepthook   # mxlint: disable=lock-discipline -- read-after-install: install() wrote it once under the lock before arming this hook
    (prev or sys.__excepthook__)(exc_type, exc, tb)


def _threading_hook(args):
    if args.exc_type is not SystemExit:
        postmortem(
            "uncaught_thread_exception", exc=args.exc_value,
            extra={"thread": getattr(args.thread, "name", None)},
            force=True)
    prev = _prev_threading_hook   # mxlint: disable=lock-discipline -- read-after-install: install() wrote it once under the lock before arming this hook
    (prev or threading.__excepthook__)(args)


def _atexit_flush():
    sampler_stop()
    d = flight_dir()
    if d is not None and series(1):
        try:
            series_dump(os.path.join(
                d, "flight-series-r%d-%d.jsonl" % (
                    telemetry.process_identity()["rank"],
                    os.getpid())))
        except Exception:
            pass


def install():
    """Arm the process-level hooks (idempotent): ``sys.excepthook`` and
    ``threading.excepthook`` dump a postmortem on any uncaught
    exception (then chain to the previous hook), and an atexit handler
    flushes the time-series ring to the flight dir. Called by
    ``configure()`` and the env autostart."""
    global _installed, _prev_excepthook, _prev_threading_hook
    with _lock:
        if _installed:
            return
        _installed = True
        _prev_excepthook = sys.excepthook
        _prev_threading_hook = threading.excepthook
    sys.excepthook = _excepthook
    threading.excepthook = _threading_hook
    atexit.register(_atexit_flush)


def installed():
    with _lock:
        return _installed


def _maybe_autostart():
    """Adopt the env knobs at package import: a flight dir arms the
    hooks, an interval > 0 starts the sampler, a port > 0 starts the
    scrape endpoint (<= 0 means OFF for both, matching the sampler's
    documented knob). All three default OFF. A malformed value or an
    already-bound port warns and runs recorder-free (the faults.py env
    posture) — observability must never break ``import mxnet_tpu``;
    bench's subprocess children inherit the parent's env, so a second
    process racing for the same metrics port is NORMAL, not fatal."""

    def _adopt(what, fn):
        try:
            fn()
        except Exception as e:
            try:
                from . import log as _log
                _log.get_logger("mxnet_tpu.flight").warning(
                    "flight: ignoring %s autostart: %s", what, e)
            except Exception:
                pass

    if flight_dir() is not None:
        _adopt("hook", install)
    if os.environ.get(ENV_INTERVAL):
        _adopt(ENV_INTERVAL, sampler_start)   # <= 0 no-ops inside
    port_env = os.environ.get(ENV_PORT)
    if port_env:
        def _start_port():
            if int(port_env) > 0:
                metrics_http_start()
        _adopt(ENV_PORT, _start_port)
