"""Standalone inference predictor.

Parity: reference ``src/c_api/c_predict_api.cc`` / ``include/mxnet/
c_predict_api.h`` — the minimal deployment path (load symbol JSON +
param blob, bind a forward-only executor, feed inputs, fetch outputs)
used by the amalgamation mobile builds and
``example/image-classification/predict-cpp``.

TPU-native design: "bind" compiles the whole inference graph to one XLA
executable via jit (the reference's static no-grad executor ≙ a jitted
pure function with weights closed over as constants on device); repeated
``forward`` calls hit the compile cache as long as input shapes hold.
"""
from __future__ import annotations

import numpy as np

from .base import MXNetError
from . import symbol as sym
from . import ndarray as nd
from .executor import Executor  # noqa: F401  (bind path)

__all__ = ["Predictor", "create"]


class Predictor:
    """(parity: MXPredCreate/MXPredForward/MXPredGetOutput)

    Parameters
    ----------
    symbol_json : str — graph JSON text (or a Symbol)
    param_bytes : bytes | str | dict — ``.params`` blob path/bytes as
        written by ``model.save_checkpoint`` (arg:/aux: prefixed), or a
        plain {name: NDArray} dict
    input_shapes : dict of name -> shape
    """

    def __init__(self, symbol_json, param_bytes, input_shapes, ctx=None,
                 dtype=None, shared_exec=None):
        if isinstance(symbol_json, sym.Symbol):
            self._symbol = symbol_json
        else:
            self._symbol = sym.load_json(symbol_json)
        arg_params, aux_params = _load_params(param_bytes)
        self._input_names = list(input_shapes.keys())
        self._input_shapes = {k: tuple(v) for k, v in input_shapes.items()}
        self._auto_args = set()
        self._ctx = ctx
        self._dtype = dtype

        arg_names = self._symbol.list_arguments()
        aux_names = self._symbol.list_auxiliary_states()
        # args absent from the blob (e.g. softmax_label at inference time)
        # get zero arrays at their partially-inferred shapes — the
        # reference's predictor likewise feeds dummy labels
        known = dict(self._input_shapes)
        known.update({k: tuple(v.shape) for k, v in arg_params.items()})
        try:
            inferred, _, _ = self._symbol.infer_shape_partial(**known)
            inferred = dict(zip(arg_names, inferred))
        except MXNetError:
            inferred = {}
        args = {}
        for name in arg_names:
            if name in self._input_shapes:
                args[name] = nd.zeros(self._input_shapes[name],
                                      dtype=dtype or "float32")
            elif name in arg_params:
                args[name] = arg_params[name]
            elif name.endswith("_label") and inferred.get(name) is not None:
                # inference-time dummy for loss-layer labels only — a
                # missing *weight* must stay a hard error
                args[name] = nd.zeros(inferred[name])
                self._auto_args.add(name)
            else:
                raise MXNetError(
                    "predictor: missing parameter %r (not an input, not in "
                    "the param blob)" % name)
        aux = {}
        for name in aux_names:
            if name not in aux_params:
                raise MXNetError("predictor: missing aux state %r" % name)
            aux[name] = aux_params[name]

        self._executor = self._symbol.bind(
            ctx, args, args_grad=None, grad_req="null", aux_states=aux,
            shared_exec=shared_exec)
        self._outputs = None

    # -- c_predict_api surface ---------------------------------------------
    def set_input(self, name, data):
        """(parity: MXPredSetInput)"""
        if name not in self._input_shapes:
            raise MXNetError("unknown input %r" % name)
        arr = data if isinstance(data, nd.NDArray) else nd.array(data)
        if tuple(arr.shape) != self._input_shapes[name]:
            raise MXNetError(
                "input %r shape %s != bound shape %s — use reshape()"
                % (name, arr.shape, self._input_shapes[name]))
        self._executor.arg_dict[name][:] = arr

    def forward(self, **kwargs):
        """(parity: MXPredForward) — kwargs are input name -> array."""
        for name, data in kwargs.items():
            self.set_input(name, data)
        self._outputs = self._executor.forward(is_train=False)
        return self._outputs

    def get_output(self, index=0):
        """(parity: MXPredGetOutput)"""
        if self._outputs is None:
            self.forward()
        return self._outputs[index]

    def reshape(self, new_input_shapes):
        """(parity: MXPredReshape) — rebind for new input shapes. The new
        predictor SHARES this one's compiled-program cache (the
        executor's per-signature AOT cache), so XLA compiles at most once
        per (shape, dtype) signature across the whole reshape lineage —
        bouncing between two shapes re-traces nothing."""
        shapes = dict(self._input_shapes)
        shapes.update({k: tuple(v) for k, v in new_input_shapes.items()})
        arg_params = {("arg:%s" % k): v
                      for k, v in self._executor.arg_dict.items()
                      if k not in self._input_shapes
                      and k not in self._auto_args}
        arg_params.update({("aux:%s" % k): v
                           for k, v in self._executor.aux_dict.items()})
        return Predictor(self._symbol, arg_params, shapes, ctx=self._ctx,
                         dtype=self._dtype, shared_exec=self._executor)

    def engine(self, **kwargs):
        """A ``serving.InferenceEngine`` sharing this predictor's
        programs and device-resident parameters — the batched serving
        surface over the same compiled cache (kwargs: ``max_batch``,
        ``max_wait_ms``, ...)."""
        from .serving import InferenceEngine
        return InferenceEngine(predictor=self, **kwargs)


def _load_params(param_bytes):
    """Accept a path, raw bytes, or a dict; split arg:/aux: prefixes."""
    if isinstance(param_bytes, dict):
        arg_params, aux_params = {}, {}
        for k, v in param_bytes.items():
            if k.startswith("arg:"):
                arg_params[k[4:]] = v
            elif k.startswith("aux:"):
                aux_params[k[4:]] = v
            else:
                arg_params[k] = v
        return arg_params, aux_params
    if isinstance(param_bytes, (bytes, bytearray)):
        import tempfile
        import os
        fd, path = tempfile.mkstemp(suffix=".params")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(param_bytes)
            blob = nd.load(path)
        finally:
            os.unlink(path)
    else:
        blob = nd.load(param_bytes)
    return _load_params(blob)


def create(symbol_file, param_file, input_shapes, ctx=None):
    """Convenience mirroring MXPredCreate's (file, file) signature."""
    with open(symbol_file) as f:
        js = f.read()
    return Predictor(js, param_file, input_shapes, ctx=ctx)
