"""Python-side helpers for the native C predict API (src/c_predict_api.cc).

Parity: reference ``include/mxnet/c_predict_api.h`` / ``src/c_api/
c_predict_api.cc`` — the standalone inference ABI used by amalgamation
mobile builds. TPU-native design: the C library embeds CPython and calls
these primitive-typed helpers (strings, ints, raw addresses) so the C++
side needs no numpy/Python C API beyond object calls; the compute itself
is the same XLA executor the rest of the framework uses.
"""
from __future__ import annotations

import ctypes

import numpy as np

from .predictor import Predictor

__all__ = ["create", "set_input", "forward", "output_ndim", "output_shape",
           "output_size", "copy_output", "num_outputs"]


def create(symbol_json, param_bytes, dev_type, dev_id, names, shapes):
    """(parity: MXPredCreate) names/shapes describe the input nodes."""
    from .context import Context
    ctx = Context(Context.devtype2str.get(dev_type, "cpu"), dev_id) \
        if isinstance(dev_type, int) else None
    input_shapes = {n: tuple(int(d) for d in s)
                    for n, s in zip(names, shapes)}
    return Predictor(symbol_json, bytes(param_bytes), input_shapes, ctx=ctx)


def set_input(pred, name, addr, size):
    """(parity: MXPredSetInput) size = number of float32 elements."""
    buf = (ctypes.c_float * size).from_address(addr)
    arr = np.frombuffer(buf, np.float32).copy()
    shape = pred._input_shapes[name]
    pred.set_input(name, arr.reshape(shape))


def forward(pred):
    pred.forward()


def num_outputs(pred):
    return len(pred._executor.outputs)


def output_ndim(pred, index):
    return len(pred.get_output(index).shape)


def output_shape(pred, index):
    return [int(d) for d in pred.get_output(index).shape]


def output_size(pred, index):
    return int(np.prod(pred.get_output(index).shape))


def copy_output(pred, index, addr, size):
    """(parity: MXPredGetOutput) copy float32 output into caller memory."""
    out = pred.get_output(index).asnumpy().astype(np.float32, copy=False)
    flat = np.ascontiguousarray(out).ravel()
    if size < flat.size:
        raise ValueError("output buffer too small: %d < %d"
                         % (size, flat.size))
    ctypes.memmove(addr, flat.ctypes.data, flat.size * 4)
