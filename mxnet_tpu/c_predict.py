"""Python-side helpers for the native C predict API (src/c_predict_api.cc).

Parity: reference ``include/mxnet/c_predict_api.h`` / ``src/c_api/
c_predict_api.cc`` — the standalone inference ABI used by amalgamation
mobile builds. TPU-native design: the C library embeds CPython and calls
these primitive-typed helpers (strings, ints, raw addresses) so the C++
side needs no numpy/Python C API beyond object calls; the compute itself
is the same XLA executor the rest of the framework uses.
"""
from __future__ import annotations

import ctypes

import numpy as np

from .predictor import Predictor

__all__ = ["create", "set_input", "forward", "reshape", "output_ndim",
           "output_shape", "output_size", "copy_output", "num_outputs",
           "ndlist_create", "ndlist_len", "ndlist_entry"]


def create(symbol_json, param_bytes, dev_type, dev_id, names, shapes,
           output_keys=None):
    """(parity: MXPredCreate / MXPredCreatePartialOut) names/shapes
    describe the input nodes; ``output_keys`` (if given) selects internal
    nodes as the outputs, reference-style ``name`` or ``name_output``."""
    from .context import Context
    ctx = Context(Context.devtype2str.get(dev_type, "cpu"), dev_id) \
        if isinstance(dev_type, int) else None
    input_shapes = {n: tuple(int(d) for d in s)
                    for n, s in zip(names, shapes)}
    symbol = symbol_json
    if output_keys:
        from . import symbol as _sym
        if not isinstance(symbol, _sym.Symbol):
            symbol = _sym.load_json(symbol)
        internals = symbol.get_internals()
        avail = set(internals.list_outputs())
        picked = []
        for key in output_keys:
            name = key if key in avail else key + "_output"
            if name not in avail:
                raise ValueError("unknown output node %r" % key)
            picked.append(internals[name])
        symbol = picked[0] if len(picked) == 1 else _sym.Group(picked)
    return Predictor(symbol, bytes(param_bytes), input_shapes, ctx=ctx)


def set_input(pred, name, addr, size):
    """(parity: MXPredSetInput) size = number of float32 elements."""
    buf = (ctypes.c_float * size).from_address(addr)
    arr = np.frombuffer(buf, np.float32).copy()
    shape = pred._input_shapes[name]
    pred.set_input(name, arr.reshape(shape))


def forward(pred):
    pred.forward()


def reshape(pred, names, shapes):
    """(parity: MXPredReshape) — new predictor handle for new input
    shapes. Shares the donor's compiled-program cache, so flipping
    between shapes costs at most one XLA compile per signature."""
    return pred.reshape({n: tuple(int(d) for d in s)
                         for n, s in zip(names, shapes)})


def num_outputs(pred):
    return len(pred._executor.outputs)


def output_ndim(pred, index):
    return len(pred.get_output(index).shape)


def output_shape(pred, index):
    return [int(d) for d in pred.get_output(index).shape]


def output_size(pred, index):
    return int(np.prod(pred.get_output(index).shape))


def copy_output(pred, index, addr, size):
    """(parity: MXPredGetOutput) copy float32 output into caller memory."""
    out = pred.get_output(index).asnumpy().astype(np.float32, copy=False)
    flat = np.ascontiguousarray(out).ravel()
    if size < flat.size:
        raise ValueError("output buffer too small: %d < %d"
                         % (size, flat.size))
    ctypes.memmove(addr, flat.ctypes.data, flat.size * 4)


# -- NDArray-list blob access (parity: MXNDListCreate/Get/Free) -------------
# The C handle owns the Python list returned by ndlist_create; every
# pointer handed to C (name bytes, float32 data, uint32 shape) is backed
# by an object stored IN that list, so it stays valid until MXNDListFree
# drops the handle.

def ndlist_create(param_bytes):
    """Parse an ``nd.save`` blob into [(name_bytes, f32_data, u32_shape)]."""
    from .ndarray import utils as _nd_utils
    loaded = _nd_utils.load_frombuffer(bytes(param_bytes))
    if isinstance(loaded, dict):
        items = list(loaded.items())
    else:
        items = [("", v) for v in loaded]
    out = []
    for name, arr in items:
        data = np.ascontiguousarray(arr.asnumpy().astype(np.float32,
                                                         copy=False))
        shape = np.asarray(data.shape, np.uint32)
        out.append((name.encode("utf-8"), data.ravel(), shape))
    return out


def ndlist_len(lst):
    return len(lst)


def ndlist_entry(lst, index):
    """-> (name_bytes, data_addr, shape_addr, ndim) for the C side."""
    name, data, shape = lst[index]
    return (name, int(data.ctypes.data), int(shape.ctypes.data),
            int(shape.size))
