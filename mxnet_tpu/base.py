"""Foundation utilities: errors, registries, env-var config.

TPU-native re-design of what the reference gets from dmlc-core
(logging, registry, GetEnv — see reference include/mxnet/base.h and
SURVEY.md §2.1 "dmlc-core equivalent"). There is no C ABI boundary here:
the Python layer talks straight to JAX/XLA, so the 159-function C API
(reference src/c_api/) collapses into ordinary Python calls.
"""
from __future__ import annotations

import os
import string
import threading

__all__ = [
    "MXNetError",
    "get_env",
    "registry_create",
    "NameManager",
]


class MXNetError(RuntimeError):
    """Error raised by the framework (parity: reference python/mxnet/base.py MXNetError)."""


def get_env(name, default=None, typ=None):
    """Typed environment-variable lookup (parity: dmlc::GetEnv, SURVEY.md §5.6).

    All reference ``MXNET_*`` runtime knobs route through here.
    """
    val = os.environ.get(name)
    if val is None:
        return default
    if typ is bool or isinstance(default, bool):
        return val.lower() not in ("0", "false", "off", "")
    if typ is int or isinstance(default, int):
        return int(val)
    if typ is float or isinstance(default, float):
        return float(val)
    return val


# one entries dict per registry kind, shared with mxnet_tpu.registry so
# mx.registry.get_create_func(Optimizer, "optimizer") sees the same
# classes that @optimizer.register put in (the reference's mx.registry IS
# the backing store for optimizer.create)
_KIND_REGISTRIES = {}


def registry_create(kind):
    """Create a tiny (register, alias, create, get) registry.

    Parity: dmlc registry pattern used for optimizers, metrics,
    initializers, data iterators in the reference.
    """
    entries = _KIND_REGISTRIES.setdefault(kind, {})

    def register(cls=None, name=None):
        def _reg(cls):
            key = (name or cls.__name__).lower()
            entries[key] = cls
            return cls

        if cls is None:
            return _reg
        return _reg(cls)

    def alias(name, cls):
        entries[name.lower()] = cls

    def create(name, *args, **kwargs):
        key = name.lower()
        if key not in entries:
            raise MXNetError(
                "%s %r is not registered (known: %s)"
                % (kind, name, sorted(entries))
            )
        return entries[key](*args, **kwargs)

    def get(name):
        return entries.get(name.lower())

    return register, alias, create, get


from .name import NameManager  # noqa: E402  (re-export; see name.py)


_VALID_NAME_CHARS = set(string.ascii_letters + string.digits + "_-.")


def check_name(name):
    if not name or not set(name) <= _VALID_NAME_CHARS:
        raise MXNetError("invalid name %r" % (name,))
    return name
